"""Llama model config.

Capability parity: reference `models/llama/llama_config.py:7-32` (all HF
Llama hparams + gradient-checkpointing knobs), with TPU-native additions:
`scan_layers` (compile-time: one traced layer scanned over depth) and
`attention_impl` (xla reference path vs pallas flash kernel).
"""

from __future__ import annotations

from typing import Any, Literal

from pydantic import model_validator

from llm_training_tpu.models.base import BaseModelConfig
from llm_training_tpu.ops.rope_utils import RoPEConfig


class LlamaConfig(BaseModelConfig):
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    head_dim: int | None = None  # defaults to hidden_size // num_attention_heads
    max_position_embeddings: int = 4096
    initializer_range: float = 0.02
    rms_norm_eps: float = 1e-6
    pad_token_id: int | None = None
    bos_token_id: int | None = 1
    # a list on several HF families (Llama-3.x instruct, GLM)
    eos_token_id: int | list[int] | None = 2
    tie_word_embeddings: bool = False
    rope_theta: float = 10000.0
    attention_bias: bool = False
    # Qwen2-style asymmetry: q/k/v carry biases, o_proj does not.
    # None = same as attention_bias
    attention_out_bias: bool | None = None
    attention_dropout: float = 0.0
    mlp_bias: bool = False
    rope_scaling: dict[str, Any] | None = None
    # Mistral/Qwen2-style local attention (None = full causal); consumed by
    # LlamaAttention via ops.dot_product_attention's sliding_window arg
    sliding_window: int | None = None
    # OLMo-3-style per-layer 'sliding_attention' / 'full_attention' pattern;
    # sliding layers use UNSCALED default rope, full layers the configured
    # rope (+ rope_scaling). None = sliding_window applies to every layer.
    layer_types: list[str] | None = None
    # OLMo-3: sliding layers rotate with the UNSCALED default rope tables
    # while full layers use rope_scaling. Ministral shares the layer_types
    # pattern but rotates every layer with ONE table, so this stays False
    # for it.
    dual_local_rope: bool = False
    # Qwen3-style per-head RMSNorm on q and k (over head_dim, before RoPE);
    # scope 'full' is the OLMo-2/OLMoE variant (one norm over the whole
    # projected width, applied before the head reshape)
    qk_norm: bool = False
    qk_norm_scope: Literal["head", "full"] = "head"
    # HunYuan applies the per-head norms AFTER rotary; everyone else before
    qk_norm_position: Literal["pre_rope", "post_rope"] = "pre_rope"
    # OLMo/OLMoE: clamp q/k/v activations to [-clip_qkv, clip_qkv] after the
    # projections (and qk-norm), before the head reshape
    clip_qkv: float | None = None
    # 'pre' = Llama pre-norm blocks; 'post' = OLMo-2 reordering
    # (x + norm(block(x)) with NO input norms); 'parallel' = Cohere/Phi's
    # single input norm feeding attention AND mlp, summed into one residual
    # add; 'parallel2' = GPT-NeoX's TWO norms (input_layernorm ->
    # attention, post_attention_layernorm -> mlp) over the SAME block
    # input, one residual join; 'sandwich' = GLM-4's four norms (input
    # norm AND output norm around both the attention and the mlp)
    norm_scheme: Literal["pre", "post", "parallel", "parallel2", "sandwich"] = "pre"
    # exact (erf) vs tanh-approximate gelu for mlp_type='gelu'
    # (Starcoder2/Phi use tanh; GPT-NeoX's 'gelu' is exact)
    gelu_approximate: bool = True
    # GPT-NeoX checkpoint naming (gpt_neox. prefix, fused interleaved
    # query_key_value, embed_in/embed_out) — needed explicitly for the
    # use_parallel_residual=False variant, whose pre-norm graph would
    # otherwise be indistinguishable from Starcoder2 naming
    neox_naming: bool = False
    # Starcoder2: biased LayerNorm instead of RMSNorm (rms_norm_eps doubles
    # as its epsilon), and a non-gated c_fc -> gelu_tanh -> c_proj MLP.
    # 'layernorm_nobias' is Cohere's mean-centered weight-only norm;
    # 'layernorm1p' is Nemotron's zero-centered (1 + w) biased LayerNorm.
    # 'relu2' is Nemotron's non-gated up_proj -> relu^2 -> down_proj MLP.
    # 'xielu' is Apertus' non-gated up -> xIELU -> down MLP with two
    # learnable activation scalars per layer.
    # 'layernorm_nonparam' is OLMo-1's fully non-parametric F.layer_norm
    # (no weight, no bias — zero norm keys in the checkpoint)
    norm_type: Literal[
        "rmsnorm", "layernorm", "layernorm_nobias", "layernorm1p",
        "layernorm_nonparam",
    ] = "rmsnorm"
    mlp_type: Literal["swiglu", "gelu", "relu2", "xielu"] = "swiglu"
    # Cohere/GLM/Ernie: interleaved (GPT-J) rope pairing; Cohere also has a
    # multiplicative logit scale. fused_gate_up marks GLM-style checkpoints
    # whose HF files store gate|up as ONE fused tensor (split/re-fused at
    # the conversion boundary; the module always keeps them separate).
    rope_interleaved: bool = False
    logit_scale: float | None = None
    fused_gate_up: bool = False
    # GPT-2: learned absolute position embeddings (wpe) instead of rotary
    position_embedding_type: Literal["rope", "learned"] = "rope"
    # SmolLM3 NoPE: per-layer rope flags, HF spelling (1 = rotate, 0 = NoPE)
    no_rope_layers: list[int] | None = None
    # Phi-1/1.5/2: rotate only the first fraction of each head's dims
    # (rope tables span int(partial_rotary_factor * head_dim)), and the
    # untied lm_head carries a bias
    partial_rotary_factor: float = 1.0
    lm_head_bias: bool = False
    # Granite (IBM) scalar multipliers; the defaults are the Llama identity
    # values. attention_multiplier None = the standard 1/sqrt(head_dim).
    embedding_multiplier: float = 1.0
    attention_multiplier: float | None = None
    residual_multiplier: float = 1.0
    logits_scaling: float = 1.0

    # --- mixture of experts (Mixtral / Qwen2-MoE / Qwen3-MoE); None = dense
    num_experts: int | None = None
    num_experts_per_tok: int = 2
    moe_intermediate_size: int | None = None
    norm_topk_prob: bool = True
    shared_expert_intermediate_size: int | None = None  # Qwen2-MoE
    router_aux_loss_coef: float = 0.001
    # conversion/export naming: 'qwen' (mlp.experts.{i}.gate_proj),
    # 'mixtral' (block_sparse_moe.experts.{i}.w1/w3/w2), or 'granite'
    # (block_sparse_moe.input_linear [E, 2I, H] fused gate/up stacks +
    # router.layer)
    moe_style: Literal["qwen", "mixtral", "granite"] = "qwen"
    # router selection: plain softmax top-k, or Phi-3.5-MoE's SparseMixer
    # (sequential argmax picks weighted by a band-masked softmax —
    # models/moe.py:sparsemixer_topk; requires top_k=2)
    moe_router_impl: Literal["softmax", "sparsemixer"] = "softmax"
    router_jitter_eps: float = 0.01  # SparseMixer masking band half-width
    # qwen2-moe gates the shared expert with a per-token sigmoid;
    # granitemoeshared runs it always-on (no gate parameter)
    shared_expert_gated: bool = True
    # 'ragged' = dropless grouped matmul (lax.ragged_dot, the TPU training
    # path); 'dense' = every expert on every token (exact, for parity
    # tests); 'bucketed' = fixed per-expert capacity buckets + ONE dense
    # batched matmul — trades token drops under imbalance (surfaced by the
    # ep_dropped_rows metric) for fully-dense MXU work where ragged_dot's
    # lowering underperforms (see BASELINE.md's grouped-matmul sweep)
    moe_impl: Literal["auto", "dense", "ragged", "bucketed"] = "auto"
    # per-rank buffer slack for the expert-parallel dispatch: capacity =
    # ceil(T*K/ep * factor) rows (clamped to T*K); routing beyond it is
    # dropped, so raise this if EP training shows imbalance-driven drops
    ep_capacity_factor: float = 2.0
    # per-EXPERT bucket slack for moe_impl='bucketed': capacity =
    # ceil(T*K/E * factor) rows per expert (clamped to T*K); 1.0 = exactly
    # balanced, larger absorbs imbalance at padding cost
    moe_capacity_factor: float = 1.25

    enable_gradient_checkpointing: bool = False
    recompute_granularity: Literal["full", "selective"] = "full"

    # TPU-native knobs
    scan_layers: bool = True
    attention_impl: Literal["auto", "xla", "pallas"] = "auto"
    # context parallelism: shard the sequence axis and run ring attention
    # over it (requires a mesh with sequence_parallel_size > 1); goes beyond
    # the reference, which reaches long context via TP+SP only (SURVEY.md §5.7)
    ring_attention: bool = False
    # GPipe pipeline parallelism (models/pipeline.py): split the scanned
    # stack into this many stages over the 'pipe' mesh axis (mesh
    # pipeline_parallel_size must match). Beyond the reference, which has
    # no PP. Changes the layer-stack param layout to [S, L/S, ...]
    pipeline_stages: int = 1
    # microbatches per step (defaults to pipeline_stages); bubble fraction
    # is (S-1)/(microbatches+S-1)
    pipeline_microbatches: int | None = None

    @model_validator(mode="after")
    def _validate(self) -> "LlamaConfig":
        if self.num_attention_heads % self.num_key_value_heads != 0:
            raise ValueError(
                f"num_attention_heads ({self.num_attention_heads}) must be divisible "
                f"by num_key_value_heads ({self.num_key_value_heads})"
            )
        if self.attention_out_bias is None:
            self.attention_out_bias = self.attention_bias
        if self.attention_dropout != 0.0:
            # fail loudly rather than silently training without the dropout a
            # user (or an HF config) asked for
            raise ValueError("attention_dropout is not supported; set it to 0.0")
        if self.num_experts is not None:
            if self.mlp_type != "swiglu":
                raise ValueError("MoE layers only support the swiglu mlp_type")
            if (
                self.moe_style == "granite"
                and self.shared_expert_intermediate_size
                and self.shared_expert_gated
            ):
                # the granite conversion layout has no gate tensor; a gated
                # shared expert would silently drop its weight on export
                raise ValueError(
                    "moe_style='granite' shared experts are always-on; set "
                    "shared_expert_gated=False (granitemoeshared has no "
                    "shared gate parameter)"
                )
            if self.moe_intermediate_size is None:
                raise ValueError("num_experts requires moe_intermediate_size")
            if not 0 < self.num_experts_per_tok <= self.num_experts:
                raise ValueError(
                    f"num_experts_per_tok ({self.num_experts_per_tok}) must be "
                    f"in [1, num_experts={self.num_experts}]"
                )
        if self.layer_types is not None:
            if len(self.layer_types) != self.num_hidden_layers:
                raise ValueError(
                    f"layer_types has {len(self.layer_types)} entries for "
                    f"{self.num_hidden_layers} layers"
                )
            bad = set(self.layer_types) - {"sliding_attention", "full_attention"}
            if bad:
                raise ValueError(
                    f"unknown layer_types entries {sorted(bad)}; expected "
                    "'sliding_attention' or 'full_attention'"
                )
            if "sliding_attention" in self.layer_types and not self.sliding_window:
                raise ValueError("sliding layer_types require sliding_window")
            # per-layer windows/ropes break the uniform scanned body
            if self.scan_layers and "scan_layers" in self.model_fields_set:
                raise ValueError(
                    "layer_types requires looped layers; set scan_layers=False"
                )
            self.scan_layers = False
            # back-compat: before dual_local_rope existed, layer_types +
            # rope_scaling implied OLMo-3 dual tables; preserve that for
            # hand-written configs carrying the OLMo-3 signature (post-norm)
            # unless the flag was set explicitly
            if (
                "dual_local_rope" not in self.model_fields_set
                and self.rope_scaling
                and self.norm_scheme == "post"
            ):
                self.dual_local_rope = True
        if self.no_rope_layers is not None:
            if self.position_embedding_type == "learned":
                raise ValueError(
                    "no_rope_layers is meaningless with learned positions"
                )
            if len(self.no_rope_layers) != self.num_hidden_layers:
                raise ValueError(
                    f"no_rope_layers has {len(self.no_rope_layers)} entries "
                    f"for {self.num_hidden_layers} layers"
                )
            # per-layer rope on/off breaks the uniform scanned body
            if self.scan_layers and "scan_layers" in self.model_fields_set:
                raise ValueError(
                    "no_rope_layers requires looped layers; set "
                    "scan_layers=False"
                )
            self.scan_layers = False
        if self.pipeline_stages > 1:
            if not self.scan_layers:
                raise ValueError(
                    "pipeline_stages > 1 requires scan_layers=True (stages "
                    "are a leading axis over the scanned stack)"
                )
            if self.num_hidden_layers % self.pipeline_stages != 0:
                raise ValueError(
                    f"num_hidden_layers {self.num_hidden_layers} must split "
                    f"evenly over pipeline_stages {self.pipeline_stages}"
                )
            if self.position_embedding_type == "learned":
                raise ValueError(
                    "pipeline_stages > 1 requires rotary positions"
                )
            if self.ring_attention:
                raise ValueError(
                    "pipeline_stages > 1 does not compose with "
                    "ring_attention (the ring's shard_map cannot sit under "
                    "the stage vmap); shard long sequences with "
                    "tensor/sequence-parallel attention instead"
                )
        self.rope_config  # construct to trigger RoPEConfig validation
        return self

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @property
    def rope_config(self) -> RoPEConfig:
        from llm_training_tpu.ops.rope_utils import rope_config_from_hf

        return rope_config_from_hf(
            self.rope_scaling, self.rope_theta,
            # Phi: tables span only the rotated fraction of each head
            int(self.resolved_head_dim * self.partial_rotary_factor),
            self.max_position_embeddings,
        )

    @property
    def local_rope_config(self) -> RoPEConfig:
        """OLMo-3 sliding layers: same theta, NEVER scaled."""
        from llm_training_tpu.ops.rope_utils import rope_config_from_hf

        return rope_config_from_hf(
            None, self.rope_theta,
            int(self.resolved_head_dim * self.partial_rotary_factor),
            self.max_position_embeddings,
        )

    def layer_sliding_window(self, layer_idx: int) -> int | None:
        if self.layer_types is None:
            return self.sliding_window
        if self.layer_types[layer_idx] == "sliding_attention":
            return self.sliding_window
        return None
