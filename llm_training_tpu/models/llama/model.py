"""Llama 2/3/3.x decoder, TPU-native.

Capability parity: reference `models/llama/llama_model.py` — GQA attention
(`:430-663`), RMSNorm blocks (`:271-286`), rotary embedding with all scaling
variants (`:289-412`), SwiGLU MLP (`:415-427`), tied embeddings (`:57-58`),
full/selective activation checkpointing (`:98-121,506-534`), and the TP/FSDP
sharding plans (`:197-268`) — re-designed as a single flax.linen module tree:

- the three attention impls (eager/SDPA/FA2) collapse into
  `ops.dot_product_attention` (XLA reference path or Pallas flash kernel);
  packed-document masks are segment ids, so no unpad/repad exists
- the DTensor TP plan + FSDP2 plan become logical-axis names on each kernel
  (`nn.with_logical_partitioning`), resolved by the rule table in
  `parallel/sharding.py`
- `recompute_granularity`: 'full' == remat everything per layer;
  'selective' == save matmul outputs, recompute the rest (the analogue of
  checkpointing only core attention)
- `scan_layers` compiles ONE decoder layer and `nn.scan`s it over depth —
  constant compile time in num_hidden_layers (no torch analogue)
"""

from __future__ import annotations

from functools import partial as _partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from llm_training_tpu.models.base import (
    CausalLMOutput,
    DecodeState,
    PagedDecodeState,
    RouterStats,
)
from llm_training_tpu.models.remat import remat_policy as _remat_policy
from llm_training_tpu.models.llama.config import LlamaConfig
from llm_training_tpu.ops import apply_rope, dot_product_attention, rms_norm
from llm_training_tpu.ops.rope_utils import compute_rope_cos_sin, compute_rope_frequencies
from llm_training_tpu.ops.swiglu import silu_mul


class RMSNorm(nn.Module):
    eps: float
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        weight = self.param(
            "weight",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        return rms_norm(x, weight.astype(x.dtype), self.eps)


class LayerNorm(nn.Module):
    """Mean-centered LayerNorm with fp32 stats over the LAST dim.

    use_bias=True is the Starcoder2 block norm (HF param names weight/bias);
    use_bias=False is Cohere's weight-only CohereLayerNorm, whose weight may
    be multi-dim ([heads, head_dim] for the per-head qk-norm) spanning the
    trailing dims of x; zero_centered=True is Nemotron's LayerNorm1P
    (weight stored zero-centered, applied as 1 + w)."""

    eps: float
    param_dtype: jnp.dtype
    use_bias: bool = True
    zero_centered: bool = False
    # OLMo-1: F.layer_norm with NO weight and NO bias at all
    parametric: bool = True
    weight_shape: tuple[int, ...] | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x32 = x.astype(jnp.float32)
        mean = x32.mean(axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        normed = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        if not self.parametric:
            return normed.astype(x.dtype)
        shape = self.weight_shape or (x.shape[-1],)
        axes = (None,) * (len(shape) - 1) + ("norm",)
        weight = self.param(
            "weight",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init() if self.zero_centered
                else nn.initializers.ones,
                axes,
            ),
            shape,
            self.param_dtype,
        )
        if self.zero_centered:
            weight = weight + jnp.ones_like(weight)
        out = normed * weight.astype(jnp.float32)
        if self.use_bias:
            bias = self.param(
                "bias",
                nn.with_logical_partitioning(nn.initializers.zeros_init(), axes),
                shape,
                self.param_dtype,
            )
            out = out + bias.astype(jnp.float32)
        return out.astype(x.dtype)


_NORM_CLASSES = {
    "rmsnorm": RMSNorm,
    "layernorm": LayerNorm,
    "layernorm_nobias": _partial(LayerNorm, use_bias=False),
    "layernorm1p": _partial(LayerNorm, zero_centered=True),
    # OLMo-1: fully non-parametric LayerNorm (no keys in the checkpoint)
    "layernorm_nonparam": _partial(LayerNorm, use_bias=False, parametric=False),
}


def _norm_cls(config):
    return _NORM_CLASSES[getattr(config, "norm_type", "rmsnorm")]


def _dense(config: LlamaConfig, features: int, logical_axes: tuple[str, str], name: str,
           use_bias: bool) -> nn.Dense:
    return nn.Dense(
        features=features,
        use_bias=use_bias,
        dtype=config.compute_jnp_dtype,
        param_dtype=config.param_jnp_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(config.initializer_range), logical_axes
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (logical_axes[-1],)
        ),
        name=name,
    )


class LlamaAttention(nn.Module):
    """GQA attention (reference `llama_model.py:434-663`).

    `sliding_window_override` carries the per-layer window for layer_types
    models (set by the looped `_layers`; the scanned path never uses it —
    "unset" means fall back to config.sliding_window).

    q/k/v projections are colwise-parallel ('heads'/'kv_heads' → tensor axis),
    o_proj rowwise ('embed' output) — the reference TP plan
    (`llama_model.py:197-244`) via logical axes.

    Also serves Phi-3 (reference `phi3_model.py:436-480`): the config may
    carry `sliding_window` and `attention_compute_dtype` (Phi-3's SDPA
    upcast workaround, `phi3_model.py:172-187`).

    KV-cache decoding (docs/inference.md): `layer_kv` is this layer's
    `(k, v)` cache buffers `[batch, max_length, kv_heads, head_dim]`;
    `kv_index` the shared append position and `kv_segment_ids` the cache's
    filled-slot ids (already including the incoming chunk). When given, the
    post-RoPE k/v are appended at `kv_index` and attention runs against the
    whole cache with `q_offset = kv_index`, and the call returns
    `(out, new_layer_kv)` instead of `out`."""

    config: LlamaConfig
    sliding_window_override: int | None | str = "unset"

    @nn.compact
    def __call__(
        self,
        hidden: jnp.ndarray,
        segment_ids: jnp.ndarray | None,
        cos: jnp.ndarray,
        sin: jnp.ndarray,
        layer_kv: tuple[jnp.ndarray, jnp.ndarray] | None = None,
        kv_index: jnp.ndarray | None = None,
        kv_segment_ids: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        cfg = self.config
        head_dim = cfg.resolved_head_dim
        batch, seq, _ = hidden.shape

        q = _dense(cfg, cfg.num_attention_heads * head_dim, ("embed", "heads"),
                   "q_proj", cfg.attention_bias)(hidden)
        k = _dense(cfg, cfg.num_key_value_heads * head_dim, ("embed", "kv_heads"),
                   "k_proj", cfg.attention_bias)(hidden)
        v = _dense(cfg, cfg.num_key_value_heads * head_dim, ("embed", "kv_heads"),
                   "v_proj", cfg.attention_bias)(hidden)

        if cfg.qk_norm and cfg.qk_norm_scope == "full":
            # OLMo-2/OLMoE: one RMSNorm over the whole projected width, before
            # the head reshape — different statistics than the per-head variant
            q = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="q_norm")(q)
            k = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="k_norm")(k)

        clip = getattr(cfg, "clip_qkv", None)
        if clip is not None:  # OLMo/OLMoE activation clamp, after qk-norm
            q = jnp.clip(q, -clip, clip)
            k = jnp.clip(k, -clip, clip)
            v = jnp.clip(v, -clip, clip)

        q = q.reshape(batch, seq, cfg.num_attention_heads, head_dim)
        k = k.reshape(batch, seq, cfg.num_key_value_heads, head_dim)
        v = v.reshape(batch, seq, cfg.num_key_value_heads, head_dim)

        def _head_qk_norm(q, k):
            if getattr(cfg, "norm_type", "rmsnorm") == "layernorm_nobias":
                # Cohere: per-HEAD weights [heads, head_dim], mean-centered
                q = LayerNorm(
                    cfg.rms_norm_eps, cfg.param_jnp_dtype, use_bias=False,
                    weight_shape=(cfg.num_attention_heads, head_dim), name="q_norm",
                )(q)
                k = LayerNorm(
                    cfg.rms_norm_eps, cfg.param_jnp_dtype, use_bias=False,
                    weight_shape=(cfg.num_key_value_heads, head_dim), name="k_norm",
                )(k)
            else:
                # Qwen3/HunYuan: per-head RMSNorm over head_dim, shared weight
                # (HF applies the q/k norms on the reshaped heads)
                q = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="q_norm")(q)
                k = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="k_norm")(k)
            return q, k

        head_norm = cfg.qk_norm and cfg.qk_norm_scope == "head"
        if head_norm and getattr(cfg, "qk_norm_position", "pre_rope") == "pre_rope":
            q, k = _head_qk_norm(q, k)

        rotary = getattr(cfg, "partial_rotary_factor", 1.0)
        if getattr(cfg, "position_embedding_type", "rope") == "learned":
            pass  # GPT-2: positions entered via wpe, no rotation
        elif rotary != 1.0:
            # Phi: rotate only the first int(factor * head_dim) dims of each
            # head; the remainder passes through unrotated
            rot = int(head_dim * rotary)
            q_rot, k_rot = apply_rope(
                q[..., :rot], k[..., :rot], cos, sin,
                interleaved=getattr(cfg, "rope_interleaved", False),
            )
            q = jnp.concatenate([q_rot, q[..., rot:]], axis=-1)
            k = jnp.concatenate([k_rot, k[..., rot:]], axis=-1)
        else:
            q, k = apply_rope(
                q, k, cos, sin, interleaved=getattr(cfg, "rope_interleaved", False)
            )

        if head_norm and getattr(cfg, "qk_norm_position", "pre_rope") == "post_rope":
            q, k = _head_qk_norm(q, k)  # HunYuan: norms AFTER rotary

        attention_dtype = getattr(cfg, "attention_compute_dtype", None)
        if attention_dtype is not None:
            from llm_training_tpu.models.base import resolve_dtype

            dtype = resolve_dtype(attention_dtype)
            q, k, v = q.astype(dtype), k.astype(dtype), v.astype(dtype)

        new_layer_kv = None
        if layer_kv is not None:
            out, new_layer_kv = self._cached_attention(
                q, k, v, segment_ids, layer_kv, kv_index, kv_segment_ids
            )
        else:
            out = self._attention(q, k, v, segment_ids)
        out = out.astype(hidden.dtype)
        out = out.reshape(batch, seq, cfg.num_attention_heads * head_dim)
        out = _dense(cfg, cfg.hidden_size, ("heads", "embed"), "o_proj", cfg.attention_out_bias)(out)
        if layer_kv is not None:
            return out, new_layer_kv
        return out

    def _cached_attention(self, q, k, v, segment_ids, layer_kv, kv_index, kv_segment_ids):
        """Append this chunk's k/v at `kv_index` and attend q against the
        full static-shape cache. The causal term of the mask (q_offset =
        kv_index) hides slots written after this chunk, and `kv_segment_ids`
        (0 on unwritten/pad slots) hides garbage — so ONE program serves
        both prefill (chunk at index 0) and single-token decode steps.
        Dense-cache attention is always the XLA einsum path: the flash
        kernel's block tiling assumes q_len ≥ a block and a static q_offset.

        A PAGED cache (`PagedDecodeState`, serve/ subsystem) arrives through
        the same plumbing with per-ROW lengths in `kv_index` ([B], vs the
        dense scalar) and the block table in `kv_segment_ids` — dispatched
        to `ops.paged_attention` (ragged Pallas decode kernel on TPU, XLA
        gather fallback elsewhere)."""
        cfg = self.config
        window = (
            getattr(cfg, "sliding_window", None)
            if self.sliding_window_override == "unset"
            else self.sliding_window_override
        )
        if kv_index.ndim == 1:
            from llm_training_tpu.ops.paged_attention import paged_cached_attention

            return paged_cached_attention(
                q, k, v, layer_kv, kv_index, kv_segment_ids,
                segment_ids=segment_ids,
                sliding_window=window,
                scale=getattr(cfg, "attention_multiplier", None),
            )
        ck, cv = layer_kv
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, kv_index, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, kv_index, 0, 0))
        out = dot_product_attention(
            q, ck.astype(k.dtype), cv.astype(v.dtype),
            segment_ids=kv_segment_ids,
            q_segment_ids=segment_ids,
            causal=True,
            sliding_window=window,
            scale=getattr(cfg, "attention_multiplier", None),
            q_offset=kv_index,
            impl="xla",
        )
        return out, (ck, cv)

    def _attention(self, q, k, v, segment_ids):
        """Dispatch: ring attention over a sequence-sharded mesh when enabled,
        otherwise the single-device flash/XLA path (GSPMD handles any other
        sharding by inserting collectives itself)."""
        cfg = self.config
        window = (
            getattr(cfg, "sliding_window", None)
            if self.sliding_window_override == "unset"
            else self.sliding_window_override
        )
        if getattr(cfg, "ring_attention", False):
            from llm_training_tpu.parallel.ring_attention import (
                dispatch_ring_attention,
            )

            out = dispatch_ring_attention(
                q, k, v, segment_ids,
                sliding_window=window,
                scale=getattr(cfg, "attention_multiplier", None),
                impl=cfg.attention_impl,
            )
            if out is not None:
                return out
        return dot_product_attention(
            q, k, v,
            segment_ids=segment_ids,
            causal=True,
            sliding_window=window,
            # Granite replaces 1/sqrt(head_dim) with a config scalar
            scale=getattr(cfg, "attention_multiplier", None),
            impl=cfg.attention_impl,
        )


class LlamaMLP(nn.Module):
    """SwiGLU MLP (reference `llama_model.py:415-427`): gate/up colwise
    ('mlp' → tensor), down rowwise. mlp_type='gelu' is the Starcoder2
    non-gated variant (c_fc → gelu_tanh → c_proj, HF param names)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, hidden: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        if getattr(cfg, "mlp_type", "swiglu") == "gelu":
            up = _dense(cfg, cfg.intermediate_size, ("embed", "mlp"), "c_fc", cfg.mlp_bias)(hidden)
            return _dense(cfg, cfg.hidden_size, ("mlp", "embed"), "c_proj", cfg.mlp_bias)(
                nn.gelu(up, approximate=getattr(cfg, "gelu_approximate", True))
            )
        if getattr(cfg, "mlp_type", "swiglu") == "relu2":
            up = _dense(cfg, cfg.intermediate_size, ("embed", "mlp"), "up_proj", cfg.mlp_bias)(hidden)
            return _dense(cfg, cfg.hidden_size, ("mlp", "embed"), "down_proj", cfg.mlp_bias)(
                jnp.square(nn.relu(up))
            )
        if getattr(cfg, "mlp_type", "swiglu") == "xielu":
            # Apertus xIELU (arXiv 2411.13010): a non-gated MLP whose
            # activation carries two LEARNABLE scalars. Parameters store the
            # softplus PRE-images (HF inits log(expm1(0.8)) and
            # log(expm1(0.8 - beta))); beta/eps are the HF constants.
            up = _dense(cfg, cfg.intermediate_size, ("embed", "mlp"), "up_proj", cfg.mlp_bias)(hidden)
            beta, eps = 0.5, -1e-6
            init_p = float(np.log(np.expm1(0.8)))
            init_n = float(np.log(np.expm1(0.8 - beta)))
            alpha_p = self.param(
                "xielu_alpha_p",
                nn.with_logical_partitioning(
                    nn.initializers.constant(init_p), (None,)
                ),
                (1,), cfg.param_jnp_dtype,
            )
            alpha_n = self.param(
                "xielu_alpha_n",
                nn.with_logical_partitioning(
                    nn.initializers.constant(init_n), (None,)
                ),
                (1,), cfg.param_jnp_dtype,
            )
            x = up.astype(jnp.float32)
            a_p = jax.nn.softplus(alpha_p.astype(jnp.float32))
            a_n = beta + jax.nn.softplus(alpha_n.astype(jnp.float32))
            act = jnp.where(
                x > 0,
                a_p * x * x + beta * x,
                (jnp.expm1(jnp.minimum(x, eps)) - x) * a_n + beta * x,
            ).astype(up.dtype)
            return _dense(cfg, cfg.hidden_size, ("mlp", "embed"), "down_proj", cfg.mlp_bias)(act)
        gate = _dense(cfg, cfg.intermediate_size, ("embed", "mlp"), "gate_proj", cfg.mlp_bias)(hidden)
        up = _dense(cfg, cfg.intermediate_size, ("embed", "mlp"), "up_proj", cfg.mlp_bias)(hidden)
        return _dense(cfg, cfg.hidden_size, ("mlp", "embed"), "down_proj", cfg.mlp_bias)(silu_mul(gate, up))


class LlamaDecoderLayer(nn.Module):
    """Pre-norm block (reference `llama_model.py:747-789`).

    With a KV cache (`layer_kv` et al. — see `LlamaAttention`) the layer
    returns `(hidden, (aux, new_layer_kv))`; without one the return stays
    `(hidden, aux)` and the traced graph is identical to before the cache
    existed."""

    config: LlamaConfig
    sliding_window_override: int | None | str = "unset"

    @nn.compact
    def __call__(
        self,
        hidden: jnp.ndarray,
        segment_ids: jnp.ndarray | None,
        cos: jnp.ndarray,
        sin: jnp.ndarray,
        layer_kv: tuple[jnp.ndarray, jnp.ndarray] | None = None,
        kv_index: jnp.ndarray | None = None,
        kv_segment_ids: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        cfg = self.config
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))
        norm = lambda name: _norm_cls(cfg)(cfg.rms_norm_eps, cfg.param_jnp_dtype, name=name)

        new_kv = [None]  # box: written by whichever branch runs attention

        def attention(name):
            module = LlamaAttention(cfg, self.sliding_window_override, name=name)

            def run(x, seg, c, s):
                if layer_kv is None:
                    return module(x, seg, c, s)
                out, new_kv[0] = module(
                    x, seg, c, s, layer_kv, kv_index, kv_segment_ids
                )
                return out

            return run

        def pack(hidden, aux):
            if layer_kv is None:
                return hidden, aux
            return hidden, (aux, new_kv[0])

        def mlp(x):
            """(out, aux): MoE block returns per-layer router stats
            (sel_frac [E], mean_prob [E], dropped scalar); dense SwiGLU a
            zero scalar (the ys type is uniform across layers within one
            model — a config is either all-MoE or all-dense)."""
            if cfg.num_experts:
                from llm_training_tpu.models.moe import MoEMLP

                pad_mask = None if segment_ids is None else segment_ids > 0
                return MoEMLP(cfg, name="mlp")(x, pad_mask)
            return LlamaMLP(cfg, name="mlp")(x), jnp.float32(0.0)

        # Granite scales every block output before the residual add;
        # rm == 1.0 (the default) folds away at trace time
        rm = getattr(cfg, "residual_multiplier", 1.0)
        join = (lambda x: x) if rm == 1.0 else (lambda x: x * jnp.asarray(rm, x.dtype))

        if cfg.norm_scheme == "parallel":
            # Cohere: ONE input norm feeds attention and mlp; both outputs
            # join the residual in a single add
            normed = norm("input_layernorm")(hidden)
            attn = attention("self_attn")(normed, segment_ids, cos, sin)
            mlp_out, aux = mlp(normed)
            hidden = hidden + join(attn) + join(mlp_out)
            return pack(hidden, aux)
        if cfg.norm_scheme == "parallel2":
            # GPT-NeoX: TWO norms over the SAME block input feed attention
            # and mlp in parallel; one residual join
            attn = attention("self_attn")(
                norm("input_layernorm")(hidden), segment_ids, cos, sin
            )
            mlp_out, aux = mlp(norm("post_attention_layernorm")(hidden))
            hidden = hidden + join(attn) + join(mlp_out)
            return pack(hidden, aux)
        if cfg.norm_scheme == "sandwich":
            # GLM-4: pre-norm AND output-norm around both blocks
            normed = norm("input_layernorm")(hidden)
            attn = attention("self_attn")(normed, segment_ids, cos, sin)
            hidden = hidden + join(norm("post_self_attn_layernorm")(attn))
            normed = norm("post_attention_layernorm")(hidden)
            mlp_out, aux = mlp(normed)
            hidden = hidden + join(norm("post_mlp_layernorm")(mlp_out))
            return pack(hidden, aux)
        if cfg.norm_scheme == "post":
            # OLMo-2 reordering: no input norms; normalize each block's
            # OUTPUT before it joins the residual stream
            attn = attention("self_attn")(hidden, segment_ids, cos, sin)
            hidden = hidden + join(norm("post_attention_layernorm")(attn))
            mlp_out, aux = mlp(hidden)
            hidden = hidden + join(norm("post_feedforward_layernorm")(mlp_out))
            return pack(hidden, aux)
        normed = norm("input_layernorm")(hidden)
        hidden = hidden + join(attention("self_attn")(normed, segment_ids, cos, sin))
        normed = norm("post_attention_layernorm")(hidden)
        mlp_out, aux = mlp(normed)
        hidden = hidden + join(mlp_out)
        return pack(hidden, aux)


class _ScannedLayer(nn.Module):
    """Adapter giving LlamaDecoderLayer the (carry, xs) -> (carry, ys)
    signature nn.scan expects; ys carries the per-layer MoE aux loss (and,
    when decoding, this layer's updated KV-cache slice)."""

    config: LlamaConfig
    layer_cls: type

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin,
                 layer_kv=None, kv_index=None, kv_segment_ids=None):
        hidden, ys = self.layer_cls(self.config, name="layer")(
            hidden, segment_ids, cos, sin, layer_kv, kv_index, kv_segment_ids
        )
        return hidden, ys




class Llama(nn.Module):
    """Llama causal LM.

    __call__(input_ids, segment_ids, position_ids, inputs_embeds,
             compute_logits, return_last_hidden_states) -> CausalLMOutput
    mirrors the reference's `CausalLMProto` surface (`lms/protos/clm_proto.py`).
    """

    config: LlamaConfig

    def _layers(self, hidden, segment_ids, cos, sin, local_cos=None, local_sin=None,
                decode_kv=None, kv_index=None, kv_segment_ids=None):
        """Returns (hidden, aux_loss, ep_dropped_rows, layer_stats, new_kv).
        For MoE configs the per-layer router stats (sel_frac, mean_prob,
        dropped) are pooled across depth BEFORE the E * sum(f * P) product —
        matching HF `load_balancing_loss_func`, which concatenates all
        layers' gate logits first, so the loss stays ~top_k when balanced
        regardless of num_hidden_layers. `layer_stats` is the PRE-pooled
        (sel_frac [L, E], mean_prob [L, E]) pair for the health layer
        (None for dense configs).

        `decode_kv` is the whole-stack KV cache `(k, v)` with leading layer
        axis; each layer consumes/produces its slice (the scan axis under
        scan_layers, an indexed axis on the looped path). `new_kv` is the
        updated stack (None on the training path)."""
        cfg = self.config
        policy = _remat_policy(cfg)
        new_kv = None
        if getattr(cfg, "pipeline_stages", 1) > 1:
            from llm_training_tpu.models.pipeline import PipelinedLayers

            if decode_kv is not None:
                raise NotImplementedError(
                    "KV-cache decoding does not compose with "
                    "pipeline_stages > 1; restore the checkpoint with "
                    "pipeline_stages=1 for inference"
                )
            layer_cls = _ScannedLayer
            if policy is not None:
                layer_cls = nn.remat(
                    _ScannedLayer, policy=policy, prevent_cse=False,
                )
            # aux comes back pre-pooled to the scan layout ([L, ...], real
            # microbatches only) so the MoE tail below applies unchanged
            hidden, aux = PipelinedLayers(
                cfg, layer_cls, LlamaDecoderLayer, name="pipeline"
            )(hidden, segment_ids, cos, sin)
        elif cfg.scan_layers:
            layer_cls = _ScannedLayer
            if policy is not None:
                layer_cls = nn.remat(
                    _ScannedLayer, policy=policy, prevent_cse=False,
                )
            if decode_kv is None:
                scanned = nn.scan(
                    layer_cls,
                    variable_axes={"params": 0},
                    split_rngs={"params": True},
                    in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                    length=cfg.num_hidden_layers,
                    metadata_params={nn.PARTITION_NAME: "layers"},
                )(cfg, LlamaDecoderLayer, name="layers")
                hidden, aux = scanned(hidden, segment_ids, cos, sin)
            else:
                # the cache's layer axis IS the scan axis: each step consumes
                # its [B, S, H, D] slice and emits the updated slice as ys
                # (same param scope as the training-path scan above — only
                # one of the two traces per call)
                scanned = nn.scan(
                    layer_cls,
                    variable_axes={"params": 0},
                    split_rngs={"params": True},
                    in_axes=(nn.broadcast, nn.broadcast, nn.broadcast, 0,
                             nn.broadcast, nn.broadcast),
                    length=cfg.num_hidden_layers,
                    metadata_params={nn.PARTITION_NAME: "layers"},
                )(cfg, LlamaDecoderLayer, name="layers")
                hidden, ys = scanned(
                    hidden, segment_ids, cos, sin, decode_kv, kv_index,
                    kv_segment_ids,
                )
                aux, new_kv = ys
        else:
            no_rope = getattr(cfg, "no_rope_layers", None)
            if no_rope is not None and cos is not None:
                # NoPE layers rotate with identity tables — zero layer-body
                # variation, so conversion/remat stay uniform
                id_cos = jnp.ones_like(cos)
                id_sin = jnp.zeros_like(sin)
            layer_types = getattr(cfg, "layer_types", None)
            stats = []
            kv_slices = []
            for i in range(cfg.num_hidden_layers):
                layer_cls = LlamaDecoderLayer
                if policy is not None:
                    layer_cls = nn.remat(LlamaDecoderLayer, policy=policy)
                use_rope = no_rope is None or bool(no_rope[i])
                window = (
                    cfg.layer_sliding_window(i) if layer_types is not None
                    else "unset"
                )
                lcos, lsin = cos, sin
                if not use_rope:
                    lcos, lsin = id_cos, id_sin
                elif layer_types is not None and window and local_cos is not None:
                    # OLMo-3: sliding layers rotate with the UNSCALED tables
                    lcos, lsin = local_cos, local_sin
                layer_kv = (
                    None if decode_kv is None
                    else jax.tree.map(lambda a: a[i], decode_kv)
                )
                hidden, layer_ys = layer_cls(cfg, window, name=f"layers_{i}")(
                    hidden, segment_ids, lcos, lsin, layer_kv, kv_index,
                    kv_segment_ids,
                )
                if decode_kv is not None:
                    layer_ys, layer_new_kv = layer_ys
                    kv_slices.append(layer_new_kv)
                stats.append(layer_ys)
            aux = jax.tree.map(lambda *xs: jnp.stack(xs), *stats)
            if kv_slices:
                new_kv = jax.tree.map(lambda *xs: jnp.stack(xs), *kv_slices)
        if not cfg.num_experts:
            return hidden, jnp.float32(0.0), jnp.float32(0.0), None, new_kv
        sel_frac, mean_prob, dropped = aux  # [L, E], [L, E], [L]
        aux_loss = cfg.num_experts * jnp.sum(
            sel_frac.mean(axis=0) * mean_prob.mean(axis=0)
        )
        return hidden, aux_loss, dropped.sum(), (sel_frac, mean_prob), new_kv

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray | None = None,
        segment_ids: jnp.ndarray | None = None,
        position_ids: jnp.ndarray | None = None,
        inputs_embeds: jnp.ndarray | None = None,
        compute_logits: bool = True,
        return_last_hidden_states: bool = False,
        decode_state: DecodeState | None = None,
    ) -> CausalLMOutput:
        cfg = self.config
        embed_tokens = nn.Embed(
            num_embeddings=cfg.vocab_size,
            features=cfg.hidden_size,
            dtype=cfg.compute_jnp_dtype,
            param_dtype=cfg.param_jnp_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), ("vocab", "embed")
            ),
            name="embed_tokens",
        )
        if inputs_embeds is None:
            if input_ids is None:
                raise ValueError("one of input_ids / inputs_embeds is required")
            inputs_embeds = embed_tokens(input_ids)
        hidden = inputs_embeds
        em = getattr(cfg, "embedding_multiplier", 1.0)
        if em != 1.0:  # Granite scales the embeddings into the residual stream
            hidden = hidden * jnp.asarray(em, hidden.dtype)
        seq = hidden.shape[1]

        paged = isinstance(decode_state, PagedDecodeState)
        kv_segment_ids = None
        if decode_state is not None and not paged:
            # the chunk's q-side segment ids (pads 0, real tokens 1) double
            # as the cache-slot ids for the slots it writes; merge them into
            # the cache's filled-slot map BEFORE the layers so every layer
            # masks against the same updated view
            if segment_ids is None:
                segment_ids = jnp.ones((hidden.shape[0], seq), jnp.int32)
            kv_segment_ids = jax.lax.dynamic_update_slice(
                decode_state.segment_ids, segment_ids.astype(jnp.int32),
                (0, decode_state.index),
            )
        elif paged:
            # paged plumbing reuses the dense arg slots: kv_index carries
            # the per-row lengths, kv_segment_ids the block table (see
            # LlamaAttention._cached_attention); q-side segment ids mark
            # padded chunk positions, which the paged append redirects to
            # the trash block
            if segment_ids is None:
                segment_ids = jnp.ones((hidden.shape[0], seq), jnp.int32)
            kv_segment_ids = decode_state.block_tables

        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]
        learned = getattr(cfg, "position_embedding_type", "rope") == "learned"
        if learned:
            if seq > cfg.max_position_embeddings:
                raise ValueError(
                    f"sequence length {seq} exceeds the learned position "
                    f"table ({cfg.max_position_embeddings}); jnp.take would "
                    "silently clamp out-of-range positions"
                )
            # GPT-2: learned absolute positions into the residual stream
            wpe = nn.Embed(
                num_embeddings=cfg.max_position_embeddings,
                features=cfg.hidden_size,
                dtype=cfg.compute_jnp_dtype,
                param_dtype=cfg.param_jnp_dtype,
                embedding_init=nn.with_logical_partitioning(
                    nn.initializers.normal(cfg.initializer_range), (None, "embed")
                ),
                name="wpe",
            )
            hidden = hidden + wpe(position_ids)
        # host-side rotary tables (static config -> numpy); seq is static at
        # trace time, so seq-dependent variants (dynamic NTK, longrope
        # short/long factor selection — HF Phi3RotaryEmbedding semantics)
        # resolve per compiled shape. Learned-position models carry no
        # rotation at all. Under a KV cache the chunk is 1 token wide but
        # positions span the generation, so the table-selection length is
        # the cache's (static) planned length, not the chunk width.
        rope_len = seq if decode_state is None else decode_state.table_length
        if learned:
            cos = sin = None
        else:
            inv_freq, attention_scaling = compute_rope_frequencies(
                cfg.rope_config, seq_len=rope_len
            )
            cos, sin = compute_rope_cos_sin(inv_freq, position_ids, attention_scaling)
        if cos is not None and getattr(cfg, "rope_interleaved", False):
            # repeat_interleave(freqs, 2) layout instead of concat halves
            half = cos.shape[-1] // 2
            cos = jnp.repeat(cos[..., :half], 2, axis=-1)
            sin = jnp.repeat(sin[..., :half], 2, axis=-1)

        local_cos = local_sin = None
        if (
            getattr(cfg, "layer_types", None) is not None
            and cfg.rope_scaling
            and getattr(cfg, "dual_local_rope", False)
        ):
            # sliding layers use the UNSCALED default tables (OLMo-3;
            # Ministral's layer_types pattern keeps ONE table everywhere)
            inv_freq_l, scaling_l = compute_rope_frequencies(
                cfg.local_rope_config, seq_len=rope_len
            )
            local_cos, local_sin = compute_rope_cos_sin(
                inv_freq_l, position_ids, scaling_l
            )
            if getattr(cfg, "rope_interleaved", False):
                half = local_cos.shape[-1] // 2
                local_cos = jnp.repeat(local_cos[..., :half], 2, axis=-1)
                local_sin = jnp.repeat(local_sin[..., :half], 2, axis=-1)
        hidden, aux_loss, ep_dropped, layer_stats, new_kv = self._layers(
            hidden, segment_ids, cos, sin, local_cos, local_sin,
            decode_kv=(
                None if decode_state is None
                else (decode_state.k, decode_state.v)
            ),
            kv_index=(
                None if decode_state is None
                else decode_state.lengths if paged
                else decode_state.index
            ),
            kv_segment_ids=kv_segment_ids,
        )
        new_decode_state = None
        if paged:
            # per-row advance by the chunk's REAL token count (padded tail
            # positions of a final prefill chunk don't occupy cache slots)
            new_decode_state = decode_state.replace(
                k=new_kv[0], v=new_kv[1],
                lengths=decode_state.lengths
                + jnp.sum(segment_ids > 0, axis=1).astype(jnp.int32),
            )
        elif decode_state is not None:
            new_decode_state = decode_state.replace(
                k=new_kv[0], v=new_kv[1],
                index=decode_state.index + seq,
                segment_ids=kv_segment_ids,
            )
        hidden = _norm_cls(cfg)(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="norm")(hidden)
        mult = getattr(cfg, "logit_scale", None)
        if mult is not None:
            # Cohere multiplies the logits by logit_scale; folded into the
            # hidden states for the same fused-CE reason as logits_scaling
            hidden = hidden * jnp.asarray(mult, hidden.dtype)
        ls = getattr(cfg, "logits_scaling", 1.0)
        if ls != 1.0:
            # Granite divides the logits by logits_scaling; folding the
            # division into the final hidden states makes the fused-CE path
            # (which consumes last_hidden_states + the head weights, see
            # lms/clm.py) see exactly logits/ls too
            hidden = hidden / jnp.asarray(ls, hidden.dtype)
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))

        logits = None
        if compute_logits:
            if cfg.tie_word_embeddings:
                logits = embed_tokens.attend(hidden)
            else:
                logits = _dense(
                    cfg, cfg.vocab_size, ("embed", "vocab"), "lm_head",
                    getattr(cfg, "lm_head_bias", False),
                )(hidden)
            logits = nn.with_logical_constraint(logits, ("batch", "act_seq", "act_vocab"))

        router_stats = None
        if cfg.num_experts and layer_stats is not None:
            router_stats = RouterStats(
                sel_frac=layer_stats[0],
                mean_prob=layer_stats[1],
                dropped=ep_dropped,
                layer_ids=tuple(range(cfg.num_hidden_layers)),
            )
        return CausalLMOutput(
            logits=logits,
            last_hidden_states=hidden if return_last_hidden_states else None,
            # unscaled load-balancing loss; the objective applies
            # router_aux_loss_coef (None for dense models)
            aux_loss=aux_loss if cfg.num_experts else None,
            ep_dropped_rows=ep_dropped if cfg.num_experts else None,
            router_stats=router_stats,
            decode_state=new_decode_state,
        )

    def get_input_embeddings_path(self) -> str:
        """Param-tree path of the embedding table (NEFTune hook point,
        reference `clm.py:45-82`)."""
        return "embed_tokens/embedding"

    def get_output_embeddings_path(self) -> str | None:
        if self.config.tie_word_embeddings:
            return "embed_tokens/embedding"
        return "lm_head/kernel"
