from llm_training_tpu.models.glm4_moe.config import Glm4MoeConfig
from llm_training_tpu.models.glm4_moe.model import Glm4Moe

__all__ = ["Glm4Moe", "Glm4MoeConfig"]
