"""GLM-4.5 (glm4_moe) model config.

Family member beyond the reference's named models (reached by the reference
only through torch wrapping, `hf_causal_lm.py:22`). Mirrors HF
`Glm4MoeConfig`: standard GQA attention with partial rotary and optional
per-head qk-norm, plus the DeepSeek-V3-style noaux MoE — the MoE field
names match what `models.deepseek.model.DeepseekMoE` reads, so the block is
reused directly (`version` is pinned to 3 for the sigmoid router).
"""

from __future__ import annotations

from typing import Any, Literal

from pydantic import model_validator

from llm_training_tpu.models.base import BaseModelConfig


class Glm4MoeConfig(BaseModelConfig):
    vocab_size: int = 151552
    hidden_size: int = 4096
    intermediate_size: int = 10944  # dense layers (and the MoE-free prefix)
    num_hidden_layers: int = 46
    num_attention_heads: int = 96
    num_key_value_heads: int = 8
    head_dim: int = 128
    max_position_embeddings: int = 131072
    initializer_range: float = 0.02
    rms_norm_eps: float = 1e-5
    pad_token_id: int | None = None
    bos_token_id: int | None = None
    eos_token_id: int | list[int] | None = None
    tie_word_embeddings: bool = False
    rope_theta: float = 10000.0
    rope_scaling: dict[str, Any] | None = None
    partial_rotary_factor: float = 0.5
    attention_bias: bool = False
    # dots1 biases o_proj with the SAME flag; GLM-4.5 never biases o_proj
    attention_out_bias: bool = False
    attention_dropout: float = 0.0
    use_qk_norm: bool = False  # per-head RMSNorm (GLM-4.5-Air; always on dots1)
    # dots1: per-layer sliding/full attention (qwen2-style inverted pattern)
    sliding_window: int | None = None
    layer_types: list[str] | None = None
    # which HF architecture this config round-trips as (the graphs overlap:
    # dots1 == glm4_moe attention at partial_rotary 1.0 + the same V3 MoE)
    hf_flavor: Literal["glm4_moe", "dots1"] = "glm4_moe"

    # --- DeepSeek-V3-style MoE (field names shared with DeepseekMoE)
    version: Literal[3] = 3  # sigmoid router + noaux bias, always
    n_routed_experts: int = 128
    n_shared_experts: int = 1
    num_experts_per_tok: int = 8
    moe_intermediate_size: int | None = None
    first_k_dense_replace: int = 1
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    n_group: int | None = None
    topk_group: int | None = None
    moe_impl: Literal["auto", "dense", "ragged"] = "auto"
    # per-rank buffer slack for the expert-parallel dispatch: capacity =
    # ceil(T*K/ep * factor) rows (clamped to T*K); routing beyond it is
    # dropped, so raise this if EP training shows imbalance-driven drops
    ep_capacity_factor: float = 2.0

    enable_gradient_checkpointing: bool = False
    recompute_granularity: Literal["full", "selective"] = "full"
    # the dense prefix is looped; the uniform MoE suffix scans so compile
    # time stays ~flat in depth
    scan_layers: bool = True
    attention_impl: Literal["auto", "xla", "pallas"] = "auto"

    @model_validator(mode="after")
    def _validate(self) -> "Glm4MoeConfig":
        if self.attention_dropout != 0.0:
            raise ValueError("attention_dropout is not supported; set it to 0.0")
        if self.num_attention_heads % self.num_key_value_heads:
            raise ValueError(
                f"num_attention_heads ({self.num_attention_heads}) must be "
                f"divisible by num_key_value_heads ({self.num_key_value_heads})"
            )
        if self.moe_intermediate_size is None:
            raise ValueError("glm4_moe requires moe_intermediate_size")
        if self.n_group is not None:
            if self.n_routed_experts % self.n_group:
                raise ValueError("n_routed_experts must divide into n_group groups")
            if self.topk_group is None:
                raise ValueError("n_group requires topk_group")
        if self.layer_types is not None:
            if len(self.layer_types) != self.num_hidden_layers:
                raise ValueError(
                    f"layer_types has {len(self.layer_types)} entries for "
                    f"{self.num_hidden_layers} layers"
                )
            bad = set(self.layer_types) - {"sliding_attention", "full_attention"}
            if bad:
                raise ValueError(
                    f"unknown layer_types entries {sorted(bad)}; expected "
                    "'sliding_attention' or 'full_attention'"
                )
            if "sliding_attention" in self.layer_types and not self.sliding_window:
                raise ValueError("sliding layer_types require sliding_window")
        self.rope_config
        return self

    # DeepseekMoE reads cfg.num_experts... no — it reads n_routed_experts;
    # keep parity with its expectations via identical field names above.

    @property
    def rope_config(self):
        from llm_training_tpu.ops.rope_utils import rope_config_from_hf

        return rope_config_from_hf(
            self.rope_scaling, self.rope_theta,
            int(self.head_dim * self.partial_rotary_factor),
            self.max_position_embeddings,
        )

    def layer_is_moe(self, layer_idx: int) -> bool:
        return layer_idx >= self.first_k_dense_replace

    def layer_sliding_window(self, layer_idx: int) -> int | None:
        if self.layer_types is None:
            return self.sliding_window
        if self.layer_types[layer_idx] == "sliding_attention":
            return self.sliding_window
        return None

    @property
    def num_scanned_layers(self) -> int:
        """Depth of the scanned uniform MoE suffix (0 = loop everything).
        A mixed sliding/full pattern over the suffix breaks its uniformity,
        so those layers loop."""
        if not self.scan_layers:
            return 0
        if self.layer_types is not None and len(
            set(self.layer_types[self.first_k_dense_replace:])
        ) > 1:
            return 0
        return self.num_hidden_layers - self.first_k_dense_replace
