"""GLM-4.5 decoder, TPU-native.

Graph verified against HF `modeling_glm4_moe.py`: standard pre-norm GQA
attention with partial rotary (factor 0.5, half-rotation pairing — unlike
dense GLM-4, NOT interleaved) and optional per-head qk-norm, plus the
DeepSeek-V3-style noaux MoE (sigmoid router + e_score_correction_bias +
top-2-sum group selection, always-on shared experts, dense layer prefix) —
the MoE block is `models.deepseek.model.DeepseekMoE`, reused as-is.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from llm_training_tpu.models.base import CausalLMOutput, RouterStats
from llm_training_tpu.models.deepseek.model import DeepseekMLP, DeepseekMoE
from llm_training_tpu.models.glm4_moe.config import Glm4MoeConfig
from llm_training_tpu.models.llama.model import RMSNorm, _dense
from llm_training_tpu.models.remat import remat_policy as _remat_policy
from llm_training_tpu.ops import apply_rope, dot_product_attention
from llm_training_tpu.ops.rope_utils import compute_rope_cos_sin, compute_rope_frequencies


class Glm4MoeAttention(nn.Module):
    config: Glm4MoeConfig
    sliding_window: int | None = None

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        batch, seq, _ = hidden.shape
        heads, d = cfg.num_attention_heads, cfg.head_dim
        q = _dense(cfg, heads * d, ("embed", "heads"), "q_proj",
                   cfg.attention_bias)(hidden)
        k = _dense(cfg, cfg.num_key_value_heads * d, ("embed", "kv_heads"),
                   "k_proj", cfg.attention_bias)(hidden)
        v = _dense(cfg, cfg.num_key_value_heads * d, ("embed", "kv_heads"),
                   "v_proj", cfg.attention_bias)(hidden)
        q = q.reshape(batch, seq, heads, d)
        k = k.reshape(batch, seq, cfg.num_key_value_heads, d)
        v = v.reshape(batch, seq, cfg.num_key_value_heads, d)
        if cfg.use_qk_norm:
            q = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="q_norm")(q)
            k = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="k_norm")(k)
        rot = int(d * cfg.partial_rotary_factor)
        q_rot, k_rot = apply_rope(q[..., :rot], k[..., :rot], cos, sin)
        q = jnp.concatenate([q_rot, q[..., rot:]], axis=-1)
        k = jnp.concatenate([k_rot, k[..., rot:]], axis=-1)
        out = dot_product_attention(
            q, k, v, segment_ids=segment_ids, causal=True,
            sliding_window=self.sliding_window,
            impl=cfg.attention_impl,
        )
        out = out.astype(hidden.dtype).reshape(batch, seq, heads * d)
        # HF GLM-4.5 biases q/k/v but NEVER o_proj (released checkpoints set
        # attention_bias=true); dots1 biases all four with one flag
        return _dense(cfg, cfg.hidden_size, ("heads", "embed"), "o_proj",
                      cfg.attention_out_bias)(out)


class Glm4MoeDecoderLayer(nn.Module):
    config: Glm4MoeConfig
    is_moe: bool
    sliding_window: int | None = None

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))
        norm = lambda name: RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name=name)
        normed = norm("input_layernorm")(hidden)
        hidden = hidden + Glm4MoeAttention(
            cfg, self.sliding_window, name="self_attn"
        )(normed, segment_ids, cos, sin)
        normed = norm("post_attention_layernorm")(hidden)
        if self.is_moe:
            pad_mask = None if segment_ids is None else segment_ids > 0
            mlp_out, stats = DeepseekMoE(cfg, name="mlp")(normed, pad_mask)
        else:
            mlp_out = DeepseekMLP(cfg, cfg.intermediate_size, name="mlp")(normed)
            stats = None
        return hidden + mlp_out, stats


class _MoEScanBody(nn.Module):
    """Scan body: one MoE layer (the uniform suffix after the dense prefix —
    GLM-4.5 is 92 layers deep, so scanning is what keeps compile time flat).
    ys carries the router health triple (sel_frac, mean_prob, dropped)."""

    config: Glm4MoeConfig

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        # the scanned suffix is uniform by construction (num_scanned_layers
        # returns 0 for mixed sliding/full suffixes), so one window applies
        hidden, stats = Glm4MoeDecoderLayer(
            cfg, True, cfg.layer_sliding_window(cfg.num_hidden_layers - 1),
            name="layer",
        )(hidden, segment_ids, cos, sin)
        return hidden, stats


class Glm4Moe(nn.Module):
    """GLM-4.5 causal LM with the `CausalLMProto` surface."""

    config: Glm4MoeConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray | None = None,
        segment_ids: jnp.ndarray | None = None,
        position_ids: jnp.ndarray | None = None,
        inputs_embeds: jnp.ndarray | None = None,
        compute_logits: bool = True,
        return_last_hidden_states: bool = False,
    ) -> CausalLMOutput:
        cfg = self.config
        embed_tokens = nn.Embed(
            num_embeddings=cfg.vocab_size,
            features=cfg.hidden_size,
            dtype=cfg.compute_jnp_dtype,
            param_dtype=cfg.param_jnp_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), ("vocab", "embed")
            ),
            name="embed_tokens",
        )
        if inputs_embeds is None:
            if input_ids is None:
                raise ValueError("one of input_ids / inputs_embeds is required")
            inputs_embeds = embed_tokens(input_ids)
        hidden = inputs_embeds
        seq = hidden.shape[1]

        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]
        inv_freq, attention_scaling = compute_rope_frequencies(
            cfg.rope_config, seq_len=seq
        )
        cos, sin = compute_rope_cos_sin(inv_freq, position_ids, attention_scaling)

        policy = _remat_policy(cfg)
        n_scanned = cfg.num_scanned_layers
        ep_dropped = jnp.float32(0.0)
        moe_sel, moe_prob, moe_ids = [], [], []
        for i in range(cfg.num_hidden_layers - n_scanned):
            layer_cls = Glm4MoeDecoderLayer
            if policy is not None:
                layer_cls = nn.remat(Glm4MoeDecoderLayer, policy=policy)
            hidden, stats = layer_cls(
                cfg, cfg.layer_is_moe(i), cfg.layer_sliding_window(i),
                name=f"layers_{i}",
            )(hidden, segment_ids, cos, sin)
            if stats is not None:
                moe_sel.append(stats[0])
                moe_prob.append(stats[1])
                moe_ids.append(i)
                ep_dropped = ep_dropped + stats[2]
        if n_scanned:
            body = _MoEScanBody
            if policy is not None:
                body = nn.remat(_MoEScanBody, policy=policy, prevent_cse=False)
            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=n_scanned,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="moe_layers")
            hidden, (sel, prob, dropped) = scanned(hidden, segment_ids, cos, sin)
            ep_dropped = ep_dropped + dropped.sum()

        hidden = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="norm")(hidden)
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))

        # per-MoE-layer router stats in layer order (dense prefix carries
        # none) for the health layer; GLM-4.5 balances via the noaux bias,
        # so no aux loss is optimized — only observed
        sel_parts = [jnp.stack(moe_sel)] if moe_sel else []
        prob_parts = [jnp.stack(moe_prob)] if moe_prob else []
        if n_scanned:
            sel_parts.append(sel)
            prob_parts.append(prob)
            moe_ids.extend(
                range(cfg.num_hidden_layers - n_scanned, cfg.num_hidden_layers)
            )
        router_stats = None
        if sel_parts:
            router_stats = RouterStats(
                sel_frac=jnp.concatenate(sel_parts),
                mean_prob=jnp.concatenate(prob_parts),
                dropped=ep_dropped,
                layer_ids=tuple(moe_ids),
            )

        logits = None
        if compute_logits:
            if cfg.tie_word_embeddings:
                logits = embed_tokens.attend(hidden)
            else:
                logits = _dense(cfg, cfg.vocab_size, ("embed", "vocab"), "lm_head", False)(hidden)
            logits = nn.with_logical_constraint(logits, ("batch", "act_seq", "act_vocab"))

        return CausalLMOutput(
            logits=logits,
            last_hidden_states=hidden if return_last_hidden_states else None,
            ep_dropped_rows=ep_dropped,
            router_stats=router_stats,
        )

    def get_input_embeddings_path(self) -> str:
        return "embed_tokens/embedding"

    def get_output_embeddings_path(self) -> str:
        if self.config.tie_word_embeddings:
            return "embed_tokens/embedding"
        return "lm_head/kernel"
