"""GLM-4.5 <-> HuggingFace state-dict conversion.

Capability parity: reference `hf_compat_model.py:96-119` applied to GLM-4.5
(reached by the reference only through torch wrapping, `hf_causal_lm.py:22`).
The MoE key layout is DeepSeek's (gate + e_score_correction_bias + per-expert
projections + shared_experts); the attention is plain GQA.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from llm_training_tpu.models.glm4_moe.config import Glm4MoeConfig
from llm_training_tpu.models.llama.hf_conversion import (
    _get_path,
    _set_path,
    _to_numpy,
)
from llm_training_tpu.models.moe_scan_io import layers_from_hf, layers_to_hf

_ATTN = [
    (("self_attn", "q_proj", "kernel"), "self_attn.q_proj.weight", True),
    (("self_attn", "k_proj", "kernel"), "self_attn.k_proj.weight", True),
    (("self_attn", "v_proj", "kernel"), "self_attn.v_proj.weight", True),
    (("self_attn", "o_proj", "kernel"), "self_attn.o_proj.weight", True),
]

_ATTN_BIASES = [
    (("self_attn", "q_proj", "bias"), "self_attn.q_proj.bias", False),
    (("self_attn", "k_proj", "bias"), "self_attn.k_proj.bias", False),
    (("self_attn", "v_proj", "bias"), "self_attn.v_proj.bias", False),
]

_QK_NORMS = [
    (("self_attn", "q_norm", "weight"), "self_attn.q_norm.weight", False),
    (("self_attn", "k_norm", "weight"), "self_attn.k_norm.weight", False),
]

_O_BIAS = [
    (("self_attn", "o_proj", "bias"), "self_attn.o_proj.bias", False),
]

_DENSE_MLP = [
    (("mlp", "gate_proj", "kernel"), "mlp.gate_proj.weight", True),
    (("mlp", "up_proj", "kernel"), "mlp.up_proj.weight", True),
    (("mlp", "down_proj", "kernel"), "mlp.down_proj.weight", True),
]

_SHARED_MLP = [
    (("mlp", "shared_experts", "gate_proj", "kernel"), "mlp.shared_experts.gate_proj.weight", True),
    (("mlp", "shared_experts", "up_proj", "kernel"), "mlp.shared_experts.up_proj.weight", True),
    (("mlp", "shared_experts", "down_proj", "kernel"), "mlp.shared_experts.down_proj.weight", True),
]

_NORMS = [
    (("input_layernorm", "weight"), "input_layernorm.weight", False),
    (("post_attention_layernorm", "weight"), "post_attention_layernorm.weight", False),
]

_EXPERT_PROJS = ("gate_proj", "up_proj", "down_proj")


def _layer_params(config: Glm4MoeConfig, i: int) -> list:
    params = list(_ATTN)
    if config.attention_bias:
        # HF gates q/k/v biases on attention_bias (o_proj stays bias-free
        # on GLM-4.5; dots1 biases it with the same flag)
        params += _ATTN_BIASES
    if config.attention_out_bias:
        params += _O_BIAS
    if config.use_qk_norm:
        params += _QK_NORMS
    if not config.layer_is_moe(i):
        params += _DENSE_MLP
    else:
        params += _SHARED_MLP
        params.append((("mlp", "gate_kernel"), "mlp.gate.weight", True))
        params.append(
            (("mlp", "e_score_correction_bias"), "mlp.gate.e_score_correction_bias", False)
        )
    return params + _NORMS


def params_from_hf(
    state_dict: Mapping[str, Any], config: Glm4MoeConfig, leaf_fn: Any = None
) -> dict:
    params: dict = {}
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}

    def put(path, value):
        _set_path(params, path, leaf_fn(path, value) if leaf_fn else value)

    put(("embed_tokens", "embedding"), _to_numpy(sd["embed_tokens.weight"]))
    put(("norm", "weight"), _to_numpy(sd["norm.weight"]))
    if not config.tie_word_embeddings:
        put(("lm_head", "kernel"), _to_numpy(sd["lm_head.weight"]).T)

    def expert_parts(sd, i):
        return {
            ("mlp", f"experts_{proj}"): lambda proj=proj: np.stack([
                _to_numpy(sd[f"layers.{i}.mlp.experts.{e}.{proj}.weight"]).T
                for e in range(config.n_routed_experts)
            ])
            for proj in _EXPERT_PROJS
        }

    layers_from_hf(sd, config, put, _layer_params, expert_parts)
    return {"params": params}


def params_to_hf(params: Mapping, config: Glm4MoeConfig) -> dict[str, np.ndarray]:
    import flax.linen as nn

    p = params.get("params", params)
    p = nn.meta.unbox(p)
    out: dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(_get_path(p, ("embed_tokens", "embedding")))
    out["model.norm.weight"] = np.asarray(_get_path(p, ("norm", "weight")))
    if not config.tie_word_embeddings:
        out["lm_head.weight"] = np.asarray(_get_path(p, ("lm_head", "kernel"))).T

    def expert_out(get, i, out):
        for proj in _EXPERT_PROJS:
            stacked = get(("mlp", f"experts_{proj}"))  # [E, in, out]
            for e in range(config.n_routed_experts):
                out[f"model.layers.{i}.mlp.experts.{e}.{proj}.weight"] = stacked[e].T

    layers_to_hf(p, config, out, _layer_params, expert_out)
    return out


def config_to_hf(config: Glm4MoeConfig, torch_dtype: str = "bfloat16") -> dict[str, Any]:
    if config.hf_flavor == "dots1":
        if config.partial_rotary_factor != 1.0 or not config.use_qk_norm:
            raise ValueError(
                "dots1 exports require partial_rotary_factor=1.0 and "
                "use_qk_norm (the HF Dots1 graph hardcodes both)"
            )
        if config.attention_bias != config.attention_out_bias:
            raise ValueError(
                "HF Dots1 biases all four attention projections from ONE "
                "attention_bias flag; asymmetric biases cannot be exported"
            )
        return {
            "architectures": ["Dots1ForCausalLM"],
            "model_type": "dots1",
            "vocab_size": config.vocab_size,
            "hidden_size": config.hidden_size,
            "intermediate_size": config.intermediate_size,
            "moe_intermediate_size": config.moe_intermediate_size,
            "num_hidden_layers": config.num_hidden_layers,
            "num_attention_heads": config.num_attention_heads,
            "num_key_value_heads": config.num_key_value_heads,
            "head_dim": config.head_dim,
            "n_routed_experts": config.n_routed_experts,
            "n_shared_experts": config.n_shared_experts,
            "num_experts_per_tok": config.num_experts_per_tok,
            "first_k_dense_replace": config.first_k_dense_replace,
            "norm_topk_prob": config.norm_topk_prob,
            "routed_scaling_factor": config.routed_scaling_factor,
            "n_group": config.n_group,
            "topk_group": config.topk_group,
            "hidden_act": "silu",
            "max_position_embeddings": config.max_position_embeddings,
            "initializer_range": config.initializer_range,
            "rms_norm_eps": config.rms_norm_eps,
            "pad_token_id": config.pad_token_id,
            "bos_token_id": config.bos_token_id,
            "eos_token_id": config.eos_token_id,
            "tie_word_embeddings": config.tie_word_embeddings,
            "rope_theta": config.rope_theta,
            "rope_scaling": config.rope_scaling,
            "attention_bias": config.attention_bias,
            "attention_dropout": config.attention_dropout,
            "sliding_window": config.sliding_window,
            "layer_types": (
                list(config.layer_types)
                if config.layer_types is not None
                else ["full_attention"] * config.num_hidden_layers
            ),
            "use_cache": True,
            "torch_dtype": torch_dtype,
        }
    if config.sliding_window is not None or config.layer_types is not None:
        raise ValueError(
            "HF glm4_moe has no sliding-window fields; set hf_flavor='dots1' "
            "to export a windowed config"
        )
    if config.attention_out_bias:
        raise ValueError(
            "HF glm4_moe never biases o_proj; set hf_flavor='dots1' "
            "(whose attention_bias covers all four projections)"
        )
    return {
        "architectures": ["Glm4MoeForCausalLM"],
        "model_type": "glm4_moe",
        "vocab_size": config.vocab_size,
        "hidden_size": config.hidden_size,
        "intermediate_size": config.intermediate_size,
        "moe_intermediate_size": config.moe_intermediate_size,
        "num_hidden_layers": config.num_hidden_layers,
        "num_attention_heads": config.num_attention_heads,
        "num_key_value_heads": config.num_key_value_heads,
        "head_dim": config.head_dim,
        "partial_rotary_factor": config.partial_rotary_factor,
        "use_qk_norm": config.use_qk_norm,
        "n_routed_experts": config.n_routed_experts,
        "n_shared_experts": config.n_shared_experts,
        "num_experts_per_tok": config.num_experts_per_tok,
        "first_k_dense_replace": config.first_k_dense_replace,
        "norm_topk_prob": config.norm_topk_prob,
        "routed_scaling_factor": config.routed_scaling_factor,
        "n_group": config.n_group,
        "topk_group": config.topk_group,
        "hidden_act": "silu",
        "max_position_embeddings": config.max_position_embeddings,
        "initializer_range": config.initializer_range,
        "rms_norm_eps": config.rms_norm_eps,
        "pad_token_id": config.pad_token_id,
        "bos_token_id": config.bos_token_id,
        "eos_token_id": config.eos_token_id,
        "tie_word_embeddings": config.tie_word_embeddings,
        "rope_theta": config.rope_theta,
        "rope_scaling": config.rope_scaling,
        "attention_bias": config.attention_bias,
        "attention_dropout": config.attention_dropout,
        "use_cache": True,
        "torch_dtype": torch_dtype,
    }


def config_from_hf(hf_config: Any, **overrides: Any) -> Glm4MoeConfig:
    get = (lambda k, d=None: hf_config.get(k, d)) if isinstance(hf_config, dict) else (
        lambda k, d=None: getattr(hf_config, k, d)
    )
    if get("model_type") == "dots1":
        # dots1 = the same graph at full rotary with always-on per-head
        # qk-norm, ONE bias flag covering o_proj too, and qwen2-style
        # per-layer sliding windows
        layer_types = list(get("layer_types") or []) or None
        if layer_types is None:
            # replicate HF Dots1Config's derivation: layers from
            # max_window_layers on slide, earlier ones are full
            n_layers = get("num_hidden_layers")
            mwl = get("max_window_layers", n_layers)
            layer_types = [
                "sliding_attention"
                if get("sliding_window") is not None and i >= mwl
                else "full_attention"
                for i in range(n_layers)
            ]
        dots = dict(
            partial_rotary_factor=1.0,
            use_qk_norm=True,
            attention_out_bias=get("attention_bias", False),
            sliding_window=get("sliding_window"),
            layer_types=layer_types,
            norm_topk_prob=get("norm_topk_prob", False),
            first_k_dense_replace=get("first_k_dense_replace", 0),
            n_routed_experts=get("n_routed_experts"),
            num_experts_per_tok=get("num_experts_per_tok"),
            n_shared_experts=get("n_shared_experts"),
            # Dots1Config has NO head_dim field; HF falls back to
            # hidden_size // num_attention_heads
            head_dim=(
                get("head_dim")
                or get("hidden_size") // get("num_attention_heads")
            ),
            hf_flavor="dots1",
        )
        # an all-full pattern folds to plain full attention
        if set(dots["layer_types"]) == {"full_attention"}:
            dots["layer_types"] = None
            dots["sliding_window"] = None
        overrides = {**dots, **overrides}
    return Glm4MoeConfig(**{**dict(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        moe_intermediate_size=get("moe_intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads"),
        head_dim=get("head_dim", 128),
        max_position_embeddings=get("max_position_embeddings", 131072),
        initializer_range=get("initializer_range", 0.02),
        rms_norm_eps=get("rms_norm_eps", 1e-5),
        pad_token_id=get("pad_token_id"),
        bos_token_id=get("bos_token_id"),
        eos_token_id=get("eos_token_id"),
        tie_word_embeddings=get("tie_word_embeddings", False),
        rope_theta=get("rope_theta", 10000.0),
        rope_scaling=get("rope_scaling"),
        partial_rotary_factor=get("partial_rotary_factor", 0.5),
        attention_bias=get("attention_bias", False),
        attention_dropout=get("attention_dropout", 0.0),
        use_qk_norm=get("use_qk_norm", False),
        n_routed_experts=get("n_routed_experts", 128),
        n_shared_experts=get("n_shared_experts", 1),
        num_experts_per_tok=get("num_experts_per_tok", 8),
        first_k_dense_replace=get("first_k_dense_replace", 1),
        norm_topk_prob=get("norm_topk_prob", True),
        routed_scaling_factor=get("routed_scaling_factor", 1.0),
        n_group=get("n_group"),
        topk_group=get("topk_group"),
    ), **overrides})
