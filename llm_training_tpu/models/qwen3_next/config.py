"""Qwen3-Next model config.

Family member beyond the reference's named models (the reference reaches
Qwen3-Next only through `HFCausalLM`'s torch wrapping,
`src/llm_training/models/hf_causal_lm/hf_causal_lm.py:22`); here the hybrid
Gated-DeltaNet + gated-attention graph is native. Mirrors HF
`Qwen3NextConfig` (transformers `models/qwen3_next/configuration_qwen3_next.py`).
"""

from __future__ import annotations

from typing import Any, Literal

from pydantic import model_validator

from llm_training_tpu.models.base import BaseModelConfig


class Qwen3NextConfig(BaseModelConfig):
    vocab_size: int = 151936
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_hidden_layers: int = 48
    num_attention_heads: int = 16
    num_key_value_heads: int = 2
    head_dim: int = 256
    max_position_embeddings: int = 32768
    initializer_range: float = 0.02
    rms_norm_eps: float = 1e-6
    pad_token_id: int | None = None
    bos_token_id: int | None = None
    eos_token_id: int | list[int] | None = None
    tie_word_embeddings: bool = False
    rope_theta: float = 10000.0
    rope_scaling: dict[str, Any] | None = None
    partial_rotary_factor: float = 0.25
    attention_bias: bool = False
    attention_dropout: float = 0.0
    mlp_bias: bool = False  # read by the shared MLP/MoE blocks

    # per-layer 'linear_attention' / 'full_attention'; None = the HF default
    # pattern (full attention on every 4th layer)
    layer_types: list[str] | None = None

    # --- gated DeltaNet (linear-attention layers)
    linear_num_key_heads: int = 16
    linear_num_value_heads: int = 32
    linear_key_head_dim: int = 128
    linear_value_head_dim: int = 128
    linear_conv_kernel_dim: int = 4
    delta_chunk_size: int = 64  # chunked delta-rule block length
    # opt-in: reset the DeltaNet fast-weight state at packed-document
    # boundaries (HF leaks state across documents; see model docstring)
    segment_state_reset: bool = False

    # --- MoE (qwen-style: softmax top-k + shared expert with sigmoid gate);
    # field names match what models.moe.MoEMLP reads from its config
    num_experts: int | None = None
    num_experts_per_tok: int = 10
    moe_intermediate_size: int | None = None
    norm_topk_prob: bool = True
    shared_expert_intermediate_size: int | None = None
    router_aux_loss_coef: float = 0.001
    moe_impl: Literal["auto", "dense", "ragged"] = "auto"
    # per-rank buffer slack for the expert-parallel dispatch: capacity =
    # ceil(T*K/ep * factor) rows (clamped to T*K); routing beyond it is
    # dropped, so raise this if EP training shows imbalance-driven drops
    ep_capacity_factor: float = 2.0

    enable_gradient_checkpointing: bool = False
    recompute_granularity: Literal["full", "selective"] = "full"
    # the 3×linear+full period scans as a 4-layer body — `scan_period`
    # detects the repetition; non-periodic layer_types loop
    scan_layers: bool = True
    attention_impl: Literal["auto", "xla", "pallas"] = "auto"

    @model_validator(mode="after")
    def _validate(self) -> "Qwen3NextConfig":
        if self.attention_dropout != 0.0:
            raise ValueError("attention_dropout is not supported; set it to 0.0")
        if self.layer_types is not None and len(self.layer_types) != self.num_hidden_layers:
            raise ValueError(
                f"layer_types has {len(self.layer_types)} entries for "
                f"{self.num_hidden_layers} layers"
            )
        if self.linear_num_value_heads % self.linear_num_key_heads:
            raise ValueError(
                "linear_num_value_heads must be a multiple of linear_num_key_heads"
            )
        if self.num_experts is not None and self.moe_intermediate_size is None:
            raise ValueError("num_experts requires moe_intermediate_size")
        self.rope_config
        return self

    @property
    def rope_config(self):
        from llm_training_tpu.ops.rope_utils import rope_config_from_hf

        return rope_config_from_hf(
            self.rope_scaling, self.rope_theta,
            int(self.head_dim * self.partial_rotary_factor),
            self.max_position_embeddings,
        )

    # MoEMLP reads this name on the llama config; keep the same spelling
    moe_style: str = "qwen"

    def layer_is_linear(self, layer_idx: int) -> bool:
        kind = (
            self.layer_types[layer_idx]
            if self.layer_types is not None
            # HF default: full attention every 4th layer
            else ("full_attention" if layer_idx % 4 == 3 else "linear_attention")
        )
        return kind == "linear_attention"

    @property
    def scan_period(self) -> int:
        """Scan-body depth (0 = loop): 4 for the stock 3×linear+full
        pattern."""
        if not self.scan_layers:
            return 0
        from llm_training_tpu.models.moe_scan_io import detect_period

        return detect_period(
            [self.layer_is_linear(i) for i in range(self.num_hidden_layers)]
        )
