"""Qwen3-Next <-> HuggingFace state-dict conversion.

Capability parity: reference `hf_compat_model.py:96-119` applied to
Qwen3-Next (reached by the reference only through torch wrapping,
`hf_causal_lm.py:22`). Layers are looped (linear/full mix); MoE expert
weights stack through the shared llama `_moe_layer_parts` helpers; the
depthwise conv kernel converts between HF's [C, 1, K] and our [K, C].
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

import functools

from llm_training_tpu.models.llama.hf_conversion import (
    _get_path,
    _moe_key_set,
    _moe_layer_out,
    _moe_layer_parts,
    _set_path,
    _to_numpy,
)
from llm_training_tpu.models.moe_scan_io import (
    periodic_layers_from_hf,
    periodic_layers_to_hf,
)
from llm_training_tpu.models.qwen3_next.config import Qwen3NextConfig

_FULL_ATTN = [
    (("self_attn", "q_proj", "kernel"), "self_attn.q_proj.weight", True),
    (("self_attn", "k_proj", "kernel"), "self_attn.k_proj.weight", True),
    (("self_attn", "v_proj", "kernel"), "self_attn.v_proj.weight", True),
    (("self_attn", "o_proj", "kernel"), "self_attn.o_proj.weight", True),
    (("self_attn", "q_norm", "weight"), "self_attn.q_norm.weight", False),
    (("self_attn", "k_norm", "weight"), "self_attn.k_norm.weight", False),
]

_LINEAR_ATTN = [
    (("linear_attn", "in_proj_qkvz", "kernel"), "linear_attn.in_proj_qkvz.weight", True),
    (("linear_attn", "in_proj_ba", "kernel"), "linear_attn.in_proj_ba.weight", True),
    (("linear_attn", "out_proj", "kernel"), "linear_attn.out_proj.weight", True),
    (("linear_attn", "norm", "weight"), "linear_attn.norm.weight", False),
    (("linear_attn", "A_log"), "linear_attn.A_log", False),
    (("linear_attn", "dt_bias"), "linear_attn.dt_bias", False),
]

_NORMS = [
    (("input_layernorm", "weight"), "input_layernorm.weight", False),
    (("post_attention_layernorm", "weight"), "post_attention_layernorm.weight", False),
]


def _layer_params(config: Qwen3NextConfig, i: int) -> list:
    return (_LINEAR_ATTN if config.layer_is_linear(i) else _FULL_ATTN) + _NORMS


def params_from_hf(
    state_dict: Mapping[str, Any], config: Qwen3NextConfig, leaf_fn: Any = None
) -> dict:
    params: dict = {}
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}

    def put(path, value):
        _set_path(params, path, leaf_fn(path, value) if leaf_fn else value)

    put(("embed_tokens", "embedding"), _to_numpy(sd["embed_tokens.weight"]))
    put(("norm", "weight"), _to_numpy(sd["norm.weight"]))
    if not config.tie_word_embeddings:
        put(("lm_head", "kernel"), _to_numpy(sd["lm_head.weight"]).T)

    def extras(sd, i):
        parts = {}
        if config.layer_is_linear(i):
            # HF depthwise conv [C, 1, K] -> our [K, C]
            parts[("linear_attn", "conv_kernel")] = lambda: _to_numpy(
                sd[f"layers.{i}.linear_attn.conv1d.weight"]
            )[:, 0, :].T
        if config.num_experts:
            memo: dict = {}

            def moe(sub):
                if not memo:
                    memo.update(_moe_layer_parts(sd, config, i))
                # each key is read exactly once per layer: pop so the memo
                # drains and host memory stays one stacked tensor at a time
                return memo.pop(sub)

            for sub in _moe_key_set(config):
                parts[sub] = functools.partial(moe, sub)
        return parts

    periodic_layers_from_hf(sd, config, put, _layer_params, extras_fn=extras)
    return {"params": params}


def params_to_hf(params: Mapping, config: Qwen3NextConfig) -> dict[str, np.ndarray]:
    import flax.linen as nn

    p = params.get("params", params)
    p = nn.meta.unbox(p)
    out: dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(_get_path(p, ("embed_tokens", "embedding")))
    out["model.norm.weight"] = np.asarray(_get_path(p, ("norm", "weight")))
    if not config.tie_word_embeddings:
        out["lm_head.weight"] = np.asarray(_get_path(p, ("lm_head", "kernel"))).T

    def extras_out(get, i, out):
        if config.layer_is_linear(i):
            conv = get(("linear_attn", "conv_kernel"))
            out[f"model.layers.{i}.linear_attn.conv1d.weight"] = conv.T[:, None, :]
        if config.num_experts:
            _moe_layer_out(get, config, i, out)

    periodic_layers_to_hf(p, config, out, _layer_params, extras_out_fn=extras_out)
    return out


def config_to_hf(config: Qwen3NextConfig, torch_dtype: str = "bfloat16") -> dict[str, Any]:
    return {
        "architectures": ["Qwen3NextForCausalLM"],
        "model_type": "qwen3_next",
        "vocab_size": config.vocab_size,
        "hidden_size": config.hidden_size,
        "intermediate_size": config.intermediate_size,
        "num_hidden_layers": config.num_hidden_layers,
        "num_attention_heads": config.num_attention_heads,
        "num_key_value_heads": config.num_key_value_heads,
        "head_dim": config.head_dim,
        "partial_rotary_factor": config.partial_rotary_factor,
        "layer_types": [
            "linear_attention" if config.layer_is_linear(i) else "full_attention"
            for i in range(config.num_hidden_layers)
        ],
        "linear_num_key_heads": config.linear_num_key_heads,
        "linear_num_value_heads": config.linear_num_value_heads,
        "linear_key_head_dim": config.linear_key_head_dim,
        "linear_value_head_dim": config.linear_value_head_dim,
        "linear_conv_kernel_dim": config.linear_conv_kernel_dim,
        "num_experts": config.num_experts,
        "num_experts_per_tok": config.num_experts_per_tok,
        "moe_intermediate_size": config.moe_intermediate_size,
        "norm_topk_prob": config.norm_topk_prob,
        "shared_expert_intermediate_size": config.shared_expert_intermediate_size,
        "router_aux_loss_coef": config.router_aux_loss_coef,
        "decoder_sparse_step": 1,
        "mlp_only_layers": [],
        "output_router_logits": False,
        "hidden_act": "silu",
        "max_position_embeddings": config.max_position_embeddings,
        "initializer_range": config.initializer_range,
        "rms_norm_eps": config.rms_norm_eps,
        "pad_token_id": config.pad_token_id,
        "bos_token_id": config.bos_token_id,
        "eos_token_id": config.eos_token_id,
        "tie_word_embeddings": config.tie_word_embeddings,
        "rope_theta": config.rope_theta,
        "rope_scaling": config.rope_scaling,
        "attention_bias": config.attention_bias,
        "attention_dropout": config.attention_dropout,
        "use_cache": True,
        "torch_dtype": torch_dtype,
    }


def config_from_hf(hf_config: Any, **overrides: Any) -> Qwen3NextConfig:
    get = (lambda k, d=None: hf_config.get(k, d)) if isinstance(hf_config, dict) else (
        lambda k, d=None: getattr(hf_config, k, d)
    )
    if get("decoder_sparse_step", 1) != 1 or get("mlp_only_layers"):
        raise ValueError(
            "mixed dense/sparse layer schedules (decoder_sparse_step != 1 or "
            "mlp_only_layers) are not supported"
        )
    return Qwen3NextConfig(**{**dict(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads"),
        head_dim=get("head_dim", 256),
        max_position_embeddings=get("max_position_embeddings", 32768),
        initializer_range=get("initializer_range", 0.02),
        rms_norm_eps=get("rms_norm_eps", 1e-6),
        pad_token_id=get("pad_token_id"),
        bos_token_id=get("bos_token_id"),
        eos_token_id=get("eos_token_id"),
        tie_word_embeddings=get("tie_word_embeddings", False),
        rope_theta=get("rope_theta", 10000.0),
        rope_scaling=get("rope_scaling"),
        partial_rotary_factor=get("partial_rotary_factor", 0.25),
        attention_bias=get("attention_bias", False),
        attention_dropout=get("attention_dropout", 0.0),
        layer_types=list(get("layer_types") or []) or None,
        linear_num_key_heads=get("linear_num_key_heads", 16),
        linear_num_value_heads=get("linear_num_value_heads", 32),
        linear_key_head_dim=get("linear_key_head_dim", 128),
        linear_value_head_dim=get("linear_value_head_dim", 128),
        linear_conv_kernel_dim=get("linear_conv_kernel_dim", 4),
        num_experts=get("num_experts"),
        num_experts_per_tok=get("num_experts_per_tok", 10),
        moe_intermediate_size=get("moe_intermediate_size"),
        norm_topk_prob=get("norm_topk_prob", True),
        shared_expert_intermediate_size=get("shared_expert_intermediate_size"),
        router_aux_loss_coef=get("router_aux_loss_coef", 0.001),
    ), **overrides})
