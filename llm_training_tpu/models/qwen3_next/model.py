"""Qwen3-Next decoder, TPU-native.

Graph verified against HF `modeling_qwen3_next.py`:

- hybrid layer stack: 3-of-4 layers are gated DeltaNet linear attention,
  every 4th is gated full attention; every layer's MLP is the qwen-style
  sparse MoE (softmax top-k + shared expert with sigmoid gate — the shared
  `MoEMLP` block).
- gated full attention: q_proj emits [q | gate] per head, zero-centered
  (1+w) per-head qk-norms, PARTIAL rotary (factor 0.25), and the attention
  output multiplies sigmoid(gate) before o_proj.
- gated DeltaNet: fused qkvz/ba projections, a depthwise causal conv (silu)
  over the concatenated q|k|v channels, per-head decay
  g = -exp(A_log) * softplus(a + dt_bias) and write strength
  beta = sigmoid(b), then the CHUNKED gated delta rule. The reference's
  per-row forward-substitution loop is a unit-lower-triangular inverse,
  computed here as ONE `solve_triangular` per chunk (the TPU-idiomatic
  form); the cross-chunk recurrence is a `lax.scan` over the running
  [dk, dv] state. All delta-rule math runs in fp32 like the HF kernel.
- norms are zero-centered (1+w) RMSNorms; the DeltaNet output norm is the
  gated variant (norm(x) * w * silu(z)).

Padding semantics mirror HF: padded tokens are zeroed at the layer input,
but the recurrent state still decays THROUGH padding and across packed
documents by default (HF parity). `segment_state_reset=True` (opt-in)
resets the fast-weight state at document boundaries via the log-decay
trick (`segment_reset_decay`) — packing is this framework's default
pre-training mode, so the no-cross-contamination guarantee can extend to
the recurrence where HF cannot offer it.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from llm_training_tpu.models.base import CausalLMOutput, RouterStats
from llm_training_tpu.models.moe import MoEMLP
from llm_training_tpu.models.qwen3_next.config import Qwen3NextConfig
from llm_training_tpu.models.remat import remat_policy as _remat_policy
from llm_training_tpu.models.llama.model import _dense
from llm_training_tpu.ops import apply_rope, dot_product_attention
from llm_training_tpu.ops.rope_utils import compute_rope_cos_sin, compute_rope_frequencies


class ZeroCenteredRMSNorm(nn.Module):
    """(1 + w) RMSNorm with fp32 stats, product BEFORE the downcast (HF
    Qwen3NextRMSNorm)."""

    eps: float
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        weight = self.param(
            "weight",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("norm",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (normed * (1.0 + weight.astype(jnp.float32))).astype(x.dtype)


class GatedRMSNorm(nn.Module):
    """norm(x) * w * silu(z) (HF Qwen3NextRMSNormGated; NON-zero-centered
    weight, gate applied after the weighted norm in fp32)."""

    eps: float
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray, gate: jnp.ndarray) -> jnp.ndarray:
        weight = self.param(
            "weight",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        out = (weight.astype(jnp.float32) * normed).astype(x.dtype)
        return (
            out.astype(jnp.float32) * jax.nn.silu(gate.astype(jnp.float32))
        ).astype(x.dtype)


def _l2norm(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    return x * jax.lax.rsqrt(jnp.sum(x * x, axis=-1, keepdims=True) + eps)


_RESET_LOG_DECAY = -1e4  # exp() underflows to exactly 0.0 in fp32


def segment_reset_decay(segment_ids: jnp.ndarray) -> jnp.ndarray:
    """[B, S] extra log-decay: `_RESET_LOG_DECAY` at each document START.

    Adding this to a recurrence's log-decay sequence makes every cross-
    boundary decay product underflow to zero (a k-boundary gap accumulates
    k·(-1e4)) while within-document terms are untouched — an EXACT state
    reset that needs no change to the chunked scan structure. Packing is
    this framework's default pre-training mode, so opt-in resets extend the
    no-cross-contamination guarantee (ops/attention.py) to the recurrent
    families, which HF faithfully leaks across documents."""
    prev = jnp.concatenate(
        [segment_ids[:, :1], segment_ids[:, :-1]], axis=1
    )
    return jnp.where(segment_ids != prev, _RESET_LOG_DECAY, 0.0)


def chunk_gated_delta_rule(
    q: jnp.ndarray,  # [B, S, H, dk]
    k: jnp.ndarray,  # [B, S, H, dk]
    v: jnp.ndarray,  # [B, S, H, dv]
    g: jnp.ndarray,  # [B, S, H] log-decay (negative)
    beta: jnp.ndarray,  # [B, S, H] write strength in (0, 1)
    chunk_size: int = 64,
    reset_decay: jnp.ndarray | None = None,  # [B, S] from segment_reset_decay
) -> jnp.ndarray:
    """Chunked gated delta rule (HF `torch_chunk_gated_delta_rule`), fp32.

    Within each chunk the delta-rule corrections solve a unit-lower-
    triangular system (the reference's forward-substitution loop); across
    chunks a `lax.scan` carries the [dk, dv] fast-weight state.
    """
    in_dtype = q.dtype
    q = _l2norm(q.astype(jnp.float32))
    k = _l2norm(k.astype(jnp.float32))
    v = v.astype(jnp.float32)
    g = g.astype(jnp.float32)
    beta = beta.astype(jnp.float32)
    if reset_decay is not None:
        g = g + reset_decay.astype(jnp.float32)[..., None]

    batch, seq, heads, dk = q.shape
    dv = v.shape[-1]
    pad = (-seq) % chunk_size
    if pad:
        q, k, v = (jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0))) for x in (q, k, v))
        g, beta = (jnp.pad(x, ((0, 0), (0, pad), (0, 0))) for x in (g, beta))
    nc = (seq + pad) // chunk_size
    c = chunk_size

    # -> [B, H, nc, c, d]
    def chunked(x):
        return x.reshape(batch, nc, c, heads, -1).transpose(0, 3, 1, 2, 4)

    q = chunked(q) * (dk ** -0.5)
    k = chunked(k)
    v = chunked(v)
    g = g.reshape(batch, nc, c, heads).transpose(0, 3, 1, 2)  # [B, H, nc, c]
    beta = beta.reshape(batch, nc, c, heads).transpose(0, 3, 1, 2)

    v_beta = v * beta[..., None]
    k_beta = k * beta[..., None]

    g = jnp.cumsum(g, axis=-1)
    # decay_ij = exp(g_i - g_j) on the lower triangle (i >= j), else 0
    tril = jnp.tril(jnp.ones((c, c), bool))
    decay = jnp.where(tril, jnp.exp(g[..., :, None] - g[..., None, :]), 0.0)

    # strictly-lower correction matrix, then T = (I - A)^{-1} via a
    # triangular solve — the reference computes this with a per-row loop
    strict = jnp.tril(jnp.ones((c, c), bool), -1)
    a_mat = jnp.where(
        strict,
        -jnp.einsum("bhncd,bhnmd->bhncm", k_beta, k) * decay,
        0.0,
    )
    eye = jnp.eye(c, dtype=jnp.float32)
    t_mat = jax.scipy.linalg.solve_triangular(
        eye - a_mat, jnp.broadcast_to(eye, a_mat.shape), lower=True, unit_diagonal=True
    )
    v_corr = jnp.einsum("bhncm,bhnmd->bhncd", t_mat, v_beta)
    k_cumdecay = jnp.einsum(
        "bhncm,bhnmd->bhncd", t_mat, k_beta * jnp.exp(g)[..., None]
    )

    # [nc, B, H, ...] for the scan over chunks
    def lead(x):
        return jnp.moveaxis(x, 2, 0)

    q_s, k_s, v_s, kc_s = lead(q), lead(k), lead(v_corr), lead(k_cumdecay)
    g_s, decay_s = lead(g), lead(decay)

    def step(state, xs):
        q_i, k_i, v_i, kc_i, g_i, decay_i = xs
        attn = jnp.where(
            tril,
            jnp.einsum("bhcd,bhmd->bhcm", q_i, k_i) * decay_i,
            0.0,
        )
        v_prime = jnp.einsum("bhcd,bhdv->bhcv", kc_i, state)
        v_new = v_i - v_prime
        inter = jnp.einsum("bhcd,bhdv->bhcv", q_i * jnp.exp(g_i)[..., None], state)
        out_i = inter + jnp.einsum("bhcm,bhmv->bhcv", attn, v_new)
        g_last = g_i[..., -1]
        state = state * jnp.exp(g_last)[..., None, None] + jnp.einsum(
            "bhcd,bhcv->bhdv",
            k_i * jnp.exp(g_last[..., None] - g_i)[..., None],
            v_new,
        )
        return state, out_i

    init = jnp.zeros((batch, heads, dk, dv), jnp.float32)
    _, out = jax.lax.scan(step, init, (q_s, k_s, v_s, kc_s, g_s, decay_s))
    # [nc, B, H, c, dv] -> [B, S, H, dv]
    out = jnp.moveaxis(out, 0, 2).reshape(batch, heads, nc * c, dv)
    out = out.transpose(0, 2, 1, 3)[:, :seq]
    return out.astype(in_dtype)


class GatedDeltaNet(nn.Module):
    config: Qwen3NextConfig

    @nn.compact
    def __call__(self, hidden, pad_mask, segment_ids=None):
        cfg = self.config
        batch, seq, _ = hidden.shape
        kh, vh = cfg.linear_num_key_heads, cfg.linear_num_value_heads
        dk, dv = cfg.linear_key_head_dim, cfg.linear_value_head_dim
        group = vh // kh
        key_dim, value_dim = kh * dk, vh * dv

        if pad_mask is not None:  # HF zeroes padded tokens at the layer input
            hidden = hidden * pad_mask[..., None].astype(hidden.dtype)

        qkvz = _dense(
            cfg, key_dim * 2 + value_dim * 2, ("embed", "heads"),
            "in_proj_qkvz", False,
        )(hidden)
        ba = _dense(cfg, vh * 2, ("embed", "heads"), "in_proj_ba", False)(hidden)

        # HF interleaves per k-head: [q(dk) | k(dk) | v(group*dv) | z(group*dv)]
        qkvz = qkvz.reshape(batch, seq, kh, 2 * dk + 2 * group * dv)
        qh = qkvz[..., :dk]
        khd = qkvz[..., dk:2 * dk]
        vhd = qkvz[..., 2 * dk:2 * dk + group * dv].reshape(batch, seq, vh, dv)
        z = qkvz[..., 2 * dk + group * dv:].reshape(batch, seq, vh, dv)
        ba = ba.reshape(batch, seq, kh, 2 * group)
        b = ba[..., :group].reshape(batch, seq, vh)
        a = ba[..., group:].reshape(batch, seq, vh)

        # depthwise causal conv (kernel 4, no bias) + silu over q|k|v channels
        mixed = jnp.concatenate(
            [qh.reshape(batch, seq, key_dim), khd.reshape(batch, seq, key_dim),
             vhd.reshape(batch, seq, value_dim)],
            axis=-1,
        )
        conv_w = self.param(
            "conv_kernel",
            nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), (None, "heads")
            ),
            (cfg.linear_conv_kernel_dim, mixed.shape[-1]),
            cfg.param_jnp_dtype,
        ).astype(mixed.dtype)
        k_conv = cfg.linear_conv_kernel_dim
        padded = jnp.pad(mixed, ((0, 0), (k_conv - 1, 0), (0, 0)))
        reset_on = (
            getattr(cfg, "segment_state_reset", False) and segment_ids is not None
        )
        if reset_on:
            # the causal conv window must not cross document boundaries: a
            # cross-segment tap is replaced by the zero a standalone run's
            # left-padding would supply
            seg_p = jnp.pad(segment_ids, ((0, 0), (k_conv - 1, 0)))
            conv = sum(
                padded[:, i:i + seq]
                * conv_w[i]
                * (seg_p[:, i:i + seq] == segment_ids)[..., None]
                for i in range(k_conv)
            )
        else:
            conv = sum(padded[:, i:i + seq] * conv_w[i] for i in range(k_conv))
        mixed = jax.nn.silu(conv)

        qh = mixed[..., :key_dim].reshape(batch, seq, kh, dk)
        khd = mixed[..., key_dim:2 * key_dim].reshape(batch, seq, kh, dk)
        vhd = mixed[..., 2 * key_dim:].reshape(batch, seq, vh, dv)

        a_log = self.param(
            "A_log",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("heads",)),
            (vh,),
            jnp.float32,
        )
        dt_bias = self.param(
            "dt_bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("heads",)),
            (vh,),
            jnp.float32,
        )
        beta = jax.nn.sigmoid(b.astype(jnp.float32))
        g = -jnp.exp(a_log) * jax.nn.softplus(a.astype(jnp.float32) + dt_bias)

        # broadcast k-heads over the value-head groups
        qh = jnp.repeat(qh, group, axis=2)
        khd = jnp.repeat(khd, group, axis=2)

        reset = None
        if getattr(cfg, "segment_state_reset", False) and segment_ids is not None:
            reset = segment_reset_decay(segment_ids)
        out = chunk_gated_delta_rule(
            qh, khd, vhd, g, beta, chunk_size=cfg.delta_chunk_size,
            reset_decay=reset,
        )
        out = GatedRMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="norm")(out, z)
        out = out.reshape(batch, seq, value_dim)
        return _dense(cfg, cfg.hidden_size, ("heads", "embed"), "out_proj", False)(out)


class GatedAttention(nn.Module):
    config: Qwen3NextConfig

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        batch, seq, _ = hidden.shape
        heads, d = cfg.num_attention_heads, cfg.head_dim

        qg = _dense(cfg, heads * d * 2, ("embed", "heads"), "q_proj",
                    cfg.attention_bias)(hidden)
        qg = qg.reshape(batch, seq, heads, 2 * d)
        q, gate = qg[..., :d], qg[..., d:]
        gate = gate.reshape(batch, seq, heads * d)
        k = _dense(cfg, cfg.num_key_value_heads * d, ("embed", "kv_heads"),
                   "k_proj", cfg.attention_bias)(hidden)
        v = _dense(cfg, cfg.num_key_value_heads * d, ("embed", "kv_heads"),
                   "v_proj", cfg.attention_bias)(hidden)
        k = k.reshape(batch, seq, cfg.num_key_value_heads, d)
        v = v.reshape(batch, seq, cfg.num_key_value_heads, d)

        norm = lambda name: ZeroCenteredRMSNorm(
            cfg.rms_norm_eps, cfg.param_jnp_dtype, name=name
        )
        q = norm("q_norm")(q)
        k = norm("k_norm")(k)

        rot = int(d * cfg.partial_rotary_factor)
        q_rot, k_rot = apply_rope(q[..., :rot], k[..., :rot], cos, sin)
        q = jnp.concatenate([q_rot, q[..., rot:]], axis=-1)
        k = jnp.concatenate([k_rot, k[..., rot:]], axis=-1)

        out = dot_product_attention(
            q, k, v, segment_ids=segment_ids, causal=True,
            impl=cfg.attention_impl,
        )
        out = out.astype(hidden.dtype).reshape(batch, seq, heads * d)
        out = out * jax.nn.sigmoid(gate.astype(jnp.float32)).astype(out.dtype)
        return _dense(cfg, cfg.hidden_size, ("heads", "embed"), "o_proj",
                      cfg.attention_bias)(out)


class Qwen3NextDecoderLayer(nn.Module):
    config: Qwen3NextConfig
    is_linear: bool

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))
        norm = lambda name: ZeroCenteredRMSNorm(
            cfg.rms_norm_eps, cfg.param_jnp_dtype, name=name
        )
        pad_mask = None if segment_ids is None else segment_ids > 0

        normed = norm("input_layernorm")(hidden)
        if self.is_linear:
            attn = GatedDeltaNet(cfg, name="linear_attn")(
                normed, pad_mask, segment_ids
            )
        else:
            attn = GatedAttention(cfg, name="self_attn")(normed, segment_ids, cos, sin)
        hidden = hidden + attn

        normed = norm("post_attention_layernorm")(hidden)
        if cfg.num_experts:
            mlp_out, stats = MoEMLP(cfg, name="mlp")(normed, pad_mask)
        else:
            from llm_training_tpu.models.llama.model import LlamaMLP

            mlp_out, stats = LlamaMLP(cfg, name="mlp")(normed), jnp.float32(0.0)
        return hidden + mlp_out, stats


class _PeriodicBody(nn.Module):
    """Scan body: one period of the linear/full pattern (`scan_period`
    layers, stock Qwen3-Next: linear, linear, linear, full)."""

    config: Qwen3NextConfig

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        stats = []
        for j in range(cfg.scan_period):
            hidden, layer_stats = Qwen3NextDecoderLayer(
                cfg, cfg.layer_is_linear(j), name=f"slot{j}"
            )(hidden, segment_ids, cos, sin)
            stats.append(layer_stats)
        return hidden, jax.tree.map(lambda *xs: jnp.stack(xs), *stats)


class Qwen3Next(nn.Module):
    """Qwen3-Next causal LM with the `CausalLMProto` surface."""

    config: Qwen3NextConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray | None = None,
        segment_ids: jnp.ndarray | None = None,
        position_ids: jnp.ndarray | None = None,
        inputs_embeds: jnp.ndarray | None = None,
        compute_logits: bool = True,
        return_last_hidden_states: bool = False,
    ) -> CausalLMOutput:
        cfg = self.config
        embed_tokens = nn.Embed(
            num_embeddings=cfg.vocab_size,
            features=cfg.hidden_size,
            dtype=cfg.compute_jnp_dtype,
            param_dtype=cfg.param_jnp_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), ("vocab", "embed")
            ),
            name="embed_tokens",
        )
        if inputs_embeds is None:
            if input_ids is None:
                raise ValueError("one of input_ids / inputs_embeds is required")
            inputs_embeds = embed_tokens(input_ids)
        hidden = inputs_embeds
        seq = hidden.shape[1]

        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]
        inv_freq, attention_scaling = compute_rope_frequencies(
            cfg.rope_config, seq_len=seq
        )
        cos, sin = compute_rope_cos_sin(inv_freq, position_ids, attention_scaling)

        policy = _remat_policy(cfg)
        period = cfg.scan_period
        if period:
            body = _PeriodicBody
            if policy is not None:
                body = nn.remat(_PeriodicBody, policy=policy, prevent_cse=False)
            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.num_hidden_layers // period,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
            hidden, stacked_stats = scanned(hidden, segment_ids, cos, sin)
            # [cycles, period, ...] -> [L, ...]; depth order is irrelevant to
            # the mean-pooled aux loss below
            pooled = jax.tree.map(
                lambda x: x.reshape(-1, *x.shape[2:]), stacked_stats
            )
        else:
            stats = []
            for i in range(cfg.num_hidden_layers):
                layer_cls = Qwen3NextDecoderLayer
                if policy is not None:
                    layer_cls = nn.remat(Qwen3NextDecoderLayer, policy=policy)
                hidden, layer_stats = layer_cls(
                    cfg, cfg.layer_is_linear(i), name=f"layers_{i}"
                )(hidden, segment_ids, cos, sin)
                stats.append(layer_stats)
            pooled = jax.tree.map(lambda *xs: jnp.stack(xs), *stats)

        hidden = ZeroCenteredRMSNorm(
            cfg.rms_norm_eps, cfg.param_jnp_dtype, name="norm"
        )(hidden)
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))

        aux_loss = ep_dropped = router_stats = None
        if cfg.num_experts:
            sel_frac, mean_prob, dropped = pooled
            aux_loss = cfg.num_experts * jnp.sum(
                sel_frac.mean(axis=0) * mean_prob.mean(axis=0)
            )
            ep_dropped = dropped.sum()
            router_stats = RouterStats(
                sel_frac=sel_frac,
                mean_prob=mean_prob,
                dropped=ep_dropped,
                layer_ids=tuple(range(cfg.num_hidden_layers)),
            )

        logits = None
        if compute_logits:
            if cfg.tie_word_embeddings:
                logits = embed_tokens.attend(hidden)
            else:
                logits = _dense(cfg, cfg.vocab_size, ("embed", "vocab"), "lm_head", False)(hidden)
            logits = nn.with_logical_constraint(logits, ("batch", "act_seq", "act_vocab"))

        return CausalLMOutput(
            logits=logits,
            last_hidden_states=hidden if return_last_hidden_states else None,
            aux_loss=aux_loss,
            ep_dropped_rows=ep_dropped,
            router_stats=router_stats,
        )

    def get_input_embeddings_path(self) -> str:
        return "embed_tokens/embedding"

    def get_output_embeddings_path(self) -> str:
        if self.config.tie_word_embeddings:
            return "embed_tokens/embedding"
        return "lm_head/kernel"
