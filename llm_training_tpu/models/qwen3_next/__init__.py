from llm_training_tpu.models.qwen3_next.config import Qwen3NextConfig
from llm_training_tpu.models.qwen3_next.model import Qwen3Next

__all__ = ["Qwen3Next", "Qwen3NextConfig"]
