"""Bamba decoder (Mamba-2 SSD + attention hybrid), TPU-native.

Graph verified against HF `modeling_bamba.py` (`BambaMixer.torch_forward`):

- Mamba-2 mixer: one fused in_proj emits [gate | x,B,C | dt]; a depthwise
  causal conv (kernel 4, biased) + silu runs over the x|B|C channels;
  dt = clamp(softplus(dt + dt_bias)); A = -exp(A_log) per head; B/C are
  grouped (GQA-style) and broadcast over heads.
- the chunked SSD scan, written as einsums + ONE `lax.scan` (all fp32):
  within a chunk, Y_diag = (C_i . B_j) * exp(A_cs_i - A_cs_j) applied to
  dt-discretized x over the causal triangle; each chunk contributes a
  [N, P] state sum(B_j * exp(A_last - A_j) (x) x_j); the cross-chunk
  recurrence carries the state with per-chunk decay exp(A_last), and
  Y_off = (C_i . state_prev) * exp(A_cs_i). A D skip (per head) adds the
  raw x. Output passes the gated RMSNorm — x * silu(gate) FIRST, then
  normalize (the Mamba-2 order, opposite of Qwen3-Next's) — and out_proj.
- attention layers (attn_layer_indices) are llama-style GQA with PARTIAL
  rotary (factor 0.5); every layer ends with pre_ff_layernorm + a SwiGLU
  feed_forward.

Padding mirrors HF `apply_mask_to_padding_states`: padded tokens zero at
the mixer input and after the conv, but the SSM state decays THROUGH
padding and across packed documents by default (HF parity).
`segment_state_reset=True` (opt-in) resets the SSD state and confines the
causal conv at packed-document boundaries (see `mamba2_ssd`).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from llm_training_tpu.models.bamba.config import BambaConfig
from llm_training_tpu.models.base import CausalLMOutput
from llm_training_tpu.models.llama.model import LlamaMLP, RMSNorm, _dense
from llm_training_tpu.models.remat import remat_policy as _remat_policy
from llm_training_tpu.ops import apply_rope, dot_product_attention
from llm_training_tpu.ops.rope_utils import compute_rope_cos_sin, compute_rope_frequencies


class GatedRMSNorm(nn.Module):
    """Mamba-2 gated norm: x * silu(gate) FIRST, then RMS-normalize, then
    weight (HF BambaRMSNormGated)."""

    eps: float
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jnp.ndarray, gate: jnp.ndarray) -> jnp.ndarray:
        weight = self.param(
            "weight",
            nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        x32 = x.astype(jnp.float32) * jax.nn.silu(gate.astype(jnp.float32))
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (weight.astype(jnp.float32) * normed).astype(x.dtype)


def mamba2_ssd(
    x: jnp.ndarray,  # [B, S, H, P] raw (pre-discretization)
    dt: jnp.ndarray,  # [B, S, H] post-softplus step sizes
    a: jnp.ndarray,  # [H] negative decay rates
    b_mat: jnp.ndarray,  # [B, S, H, N]
    c_mat: jnp.ndarray,  # [B, S, H, N]
    chunk_size: int,
    reset_decay: jnp.ndarray | None = None,  # [B, S]; see qwen3_next
) -> jnp.ndarray:
    """Chunked Mamba-2 SSD (HF torch_forward's 'ssd naive' branch), fp32.

    `reset_decay` (from `qwen3_next.model.segment_reset_decay`) adds -1e4 to
    the log-decay at document starts: every cross-boundary factor — the
    intra-chunk L matrix, chunk-state writes, the carried-state decay, and
    the inter-chunk reads — then underflows to exactly zero, resetting the
    SSD state per packed document."""
    in_dtype = x.dtype
    x = x.astype(jnp.float32)
    dt = dt.astype(jnp.float32)
    b_mat = b_mat.astype(jnp.float32)
    c_mat = c_mat.astype(jnp.float32)

    batch, seq, heads, p = x.shape
    xbar = x * dt[..., None]
    abar = a.astype(jnp.float32)[None, None, :] * dt  # [B, S, H]
    if reset_decay is not None:
        abar = abar + reset_decay.astype(jnp.float32)[..., None]

    pad = (-seq) % chunk_size
    if pad:
        xbar = jnp.pad(xbar, ((0, 0), (0, pad), (0, 0), (0, 0)))
        b_mat = jnp.pad(b_mat, ((0, 0), (0, pad), (0, 0), (0, 0)))
        c_mat = jnp.pad(c_mat, ((0, 0), (0, pad), (0, 0), (0, 0)))
        abar = jnp.pad(abar, ((0, 0), (0, pad), (0, 0)))
    nc = (seq + pad) // chunk_size
    c = chunk_size

    # -> [nc, B, H, c, ...] for the scan
    def chunked(t):
        return t.reshape(batch, nc, c, heads, -1).transpose(1, 0, 3, 2, 4)

    x_s, b_s, c_s = chunked(xbar), chunked(b_mat), chunked(c_mat)
    a_s = abar.reshape(batch, nc, c, heads).transpose(1, 0, 3, 2)  # [nc,B,H,c]
    a_cs = jnp.cumsum(a_s, axis=-1)

    tril = jnp.tril(jnp.ones((c, c), bool))
    # L_ij = exp(sum_{k=j+1..i} abar_k) on the causal triangle
    l_mat = jnp.where(
        tril, jnp.exp(a_cs[..., :, None] - a_cs[..., None, :]), 0.0
    )
    g_mat = jnp.einsum("kbhin,kbhjn->kbhij", c_s, b_s)
    y_diag = jnp.einsum("kbhij,kbhjp->kbhip", g_mat * l_mat, x_s)

    decay_states = jnp.exp(a_cs[..., -1:] - a_cs)  # [nc,B,H,c]
    states = jnp.einsum(
        "kbhjn,kbhjp->kbhnp", b_s * decay_states[..., None], x_s
    )
    chunk_decay = jnp.exp(a_cs[..., -1])  # [nc,B,H]

    def step(carry, xs):
        states_k, decay_k = xs
        prev = carry
        carry = carry * decay_k[..., None, None] + states_k
        return carry, prev

    init = jnp.zeros((batch, heads, b_s.shape[-1], p), jnp.float32)
    _, prev_states = jax.lax.scan(step, init, (states, chunk_decay))

    y_off = jnp.einsum(
        "kbhin,kbhnp->kbhip", c_s * jnp.exp(a_cs)[..., None], prev_states
    )
    y = y_diag + y_off  # [nc, B, H, c, P]
    y = y.transpose(1, 0, 3, 2, 4).reshape(batch, nc * c, heads, p)[:, :seq]
    return y.astype(in_dtype)


class BambaMixer(nn.Module):
    config: BambaConfig

    @nn.compact
    def __call__(self, hidden, pad_mask, segment_ids=None):
        cfg = self.config
        batch, seq, _ = hidden.shape
        inter = cfg.mamba_intermediate
        heads, p = cfg.mamba_n_heads, cfg.mamba_d_head
        groups, n = cfg.mamba_n_groups, cfg.mamba_d_state
        conv_dim = cfg.mamba_conv_dim

        if pad_mask is not None:
            hidden = hidden * pad_mask[..., None].astype(hidden.dtype)

        proj = _dense(
            cfg, inter + conv_dim + heads, ("embed", "heads"), "in_proj",
            cfg.mamba_proj_bias,
        )(hidden)
        gate = proj[..., :inter]
        xbc = proj[..., inter:inter + conv_dim]
        dt = proj[..., inter + conv_dim:]

        # depthwise causal conv + silu over the x|B|C channels
        conv_w = self.param(
            "conv_kernel",
            nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), (None, "heads")
            ),
            (cfg.mamba_d_conv, conv_dim),
            cfg.param_jnp_dtype,
        ).astype(xbc.dtype)
        padded = jnp.pad(xbc, ((0, 0), (cfg.mamba_d_conv - 1, 0), (0, 0)))
        reset_on = (
            getattr(cfg, "segment_state_reset", False) and segment_ids is not None
        )
        if reset_on:
            # keep the causal conv window inside the document (see
            # qwen3_next.GatedDeltaNet): cross-segment taps become the zeros
            # a standalone run's left-padding would supply
            seg_p = jnp.pad(segment_ids, ((0, 0), (cfg.mamba_d_conv - 1, 0)))
            conv = sum(
                padded[:, i:i + seq]
                * conv_w[i]
                * (seg_p[:, i:i + seq] == segment_ids)[..., None]
                for i in range(cfg.mamba_d_conv)
            )
        else:
            conv = sum(
                padded[:, i:i + seq] * conv_w[i] for i in range(cfg.mamba_d_conv)
            )
        if cfg.mamba_conv_bias:
            conv_b = self.param(
                "conv_bias",
                nn.with_logical_partitioning(nn.initializers.zeros_init(), ("heads",)),
                (conv_dim,),
                cfg.param_jnp_dtype,
            )
            conv = conv + conv_b.astype(conv.dtype)
        xbc = jax.nn.silu(conv)
        if pad_mask is not None:
            xbc = xbc * pad_mask[..., None].astype(xbc.dtype)

        x = xbc[..., :inter].reshape(batch, seq, heads, p)
        b_mat = xbc[..., inter:inter + groups * n].reshape(batch, seq, groups, n)
        c_mat = xbc[..., inter + groups * n:].reshape(batch, seq, groups, n)
        b_mat = jnp.repeat(b_mat, heads // groups, axis=2)
        c_mat = jnp.repeat(c_mat, heads // groups, axis=2)

        a_log = self.param(
            "A_log",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("heads",)),
            (heads,),
            jnp.float32,
        )
        dt_bias = self.param(
            "dt_bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("heads",)),
            (heads,),
            jnp.float32,
        )
        d_skip = self.param(
            "D",
            nn.with_logical_partitioning(nn.initializers.ones, ("heads",)),
            (heads,),
            jnp.float32,
        )
        dt = jax.nn.softplus(dt.astype(jnp.float32) + dt_bias)
        a = -jnp.exp(a_log)

        reset = None
        if getattr(cfg, "segment_state_reset", False) and segment_ids is not None:
            from llm_training_tpu.models.qwen3_next.model import segment_reset_decay

            reset = segment_reset_decay(segment_ids)
        y = mamba2_ssd(
            x, dt, a, b_mat, c_mat, cfg.mamba_chunk_size, reset_decay=reset
        )
        y = y + (d_skip[None, None, :, None] * x.astype(jnp.float32)).astype(y.dtype)
        y = y.reshape(batch, seq, inter)
        y = GatedRMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="norm")(y, gate)
        return _dense(cfg, cfg.hidden_size, ("heads", "embed"), "out_proj",
                      cfg.mamba_proj_bias)(y)


class BambaAttention(nn.Module):
    """llama-style GQA with partial rotary (factor 0.5)."""

    config: BambaConfig

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        batch, seq, _ = hidden.shape
        heads, d = cfg.num_attention_heads, cfg.resolved_head_dim
        q = _dense(cfg, heads * d, ("embed", "heads"), "q_proj",
                   cfg.attention_bias)(hidden)
        k = _dense(cfg, cfg.num_key_value_heads * d, ("embed", "kv_heads"),
                   "k_proj", cfg.attention_bias)(hidden)
        v = _dense(cfg, cfg.num_key_value_heads * d, ("embed", "kv_heads"),
                   "v_proj", cfg.attention_bias)(hidden)
        q = q.reshape(batch, seq, heads, d)
        k = k.reshape(batch, seq, cfg.num_key_value_heads, d)
        v = v.reshape(batch, seq, cfg.num_key_value_heads, d)
        rot = int(d * cfg.partial_rotary_factor)
        q_rot, k_rot = apply_rope(q[..., :rot], k[..., :rot], cos, sin)
        q = jnp.concatenate([q_rot, q[..., rot:]], axis=-1)
        k = jnp.concatenate([k_rot, k[..., rot:]], axis=-1)
        out = dot_product_attention(
            q, k, v, segment_ids=segment_ids, causal=True,
            impl=cfg.attention_impl,
        )
        out = out.astype(hidden.dtype).reshape(batch, seq, heads * d)
        return _dense(cfg, cfg.hidden_size, ("heads", "embed"), "o_proj",
                      cfg.attention_bias)(out)


class BambaDecoderLayer(nn.Module):
    config: BambaConfig
    is_attention: bool

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))
        norm = lambda name: RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name=name)
        pad_mask = None if segment_ids is None else segment_ids > 0

        normed = norm("input_layernorm")(hidden)
        if self.is_attention:
            block = BambaAttention(cfg, name="self_attn")(normed, segment_ids, cos, sin)
        else:
            block = BambaMixer(cfg, name="mamba")(normed, pad_mask, segment_ids)
        hidden = hidden + block

        normed = norm("pre_ff_layernorm")(hidden)
        return hidden + LlamaMLP(cfg, name="feed_forward")(normed)


class _PeriodicBody(nn.Module):
    """Scan body: one period of the mamba/attention pattern."""

    config: BambaConfig

    @nn.compact
    def __call__(self, hidden, segment_ids, cos, sin):
        cfg = self.config
        for j in range(cfg.scan_period):
            hidden = BambaDecoderLayer(
                cfg, cfg.layer_is_attention(j), name=f"slot{j}"
            )(hidden, segment_ids, cos, sin)
        return hidden, None


class Bamba(nn.Module):
    """Bamba causal LM with the `CausalLMProto` surface."""

    config: BambaConfig

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray | None = None,
        segment_ids: jnp.ndarray | None = None,
        position_ids: jnp.ndarray | None = None,
        inputs_embeds: jnp.ndarray | None = None,
        compute_logits: bool = True,
        return_last_hidden_states: bool = False,
    ) -> CausalLMOutput:
        cfg = self.config
        embed_tokens = nn.Embed(
            num_embeddings=cfg.vocab_size,
            features=cfg.hidden_size,
            dtype=cfg.compute_jnp_dtype,
            param_dtype=cfg.param_jnp_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(cfg.initializer_range), ("vocab", "embed")
            ),
            name="embed_tokens",
        )
        if inputs_embeds is None:
            if input_ids is None:
                raise ValueError("one of input_ids / inputs_embeds is required")
            inputs_embeds = embed_tokens(input_ids)
        hidden = inputs_embeds
        seq = hidden.shape[1]

        if position_ids is None:
            position_ids = jnp.arange(seq)[None, :]
        inv_freq, attention_scaling = compute_rope_frequencies(
            cfg.rope_config, seq_len=seq
        )
        cos, sin = compute_rope_cos_sin(inv_freq, position_ids, attention_scaling)

        policy = _remat_policy(cfg)
        period = cfg.scan_period
        if period:
            body = _PeriodicBody
            if policy is not None:
                body = nn.remat(_PeriodicBody, policy=policy, prevent_cse=False)
            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.num_hidden_layers // period,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
            hidden, _ = scanned(hidden, segment_ids, cos, sin)
        else:
            for i in range(cfg.num_hidden_layers):
                layer_cls = BambaDecoderLayer
                if policy is not None:
                    layer_cls = nn.remat(BambaDecoderLayer, policy=policy)
                hidden = layer_cls(
                    cfg, cfg.layer_is_attention(i), name=f"layers_{i}"
                )(hidden, segment_ids, cos, sin)

        hidden = RMSNorm(cfg.rms_norm_eps, cfg.param_jnp_dtype, name="final_layernorm")(hidden)
        hidden = nn.with_logical_constraint(hidden, ("batch", "act_seq", "act_embed"))

        logits = None
        if compute_logits:
            if cfg.tie_word_embeddings:
                logits = embed_tokens.attend(hidden)
            else:
                logits = _dense(cfg, cfg.vocab_size, ("embed", "vocab"), "lm_head", False)(hidden)
            logits = nn.with_logical_constraint(logits, ("batch", "act_seq", "act_vocab"))

        return CausalLMOutput(
            logits=logits,
            last_hidden_states=hidden if return_last_hidden_states else None,
        )

    def get_input_embeddings_path(self) -> str:
        return "embed_tokens/embedding"

    def get_output_embeddings_path(self) -> str:
        if self.config.tie_word_embeddings:
            return "embed_tokens/embedding"
        return "lm_head/kernel"
