from llm_training_tpu.models.bamba.config import BambaConfig
from llm_training_tpu.models.bamba.model import Bamba

__all__ = ["Bamba", "BambaConfig"]
