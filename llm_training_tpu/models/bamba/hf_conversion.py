"""Bamba <-> HuggingFace state-dict conversion.

Capability parity: reference `hf_compat_model.py:96-119` applied to Bamba
(reached by the reference only through torch wrapping, `hf_causal_lm.py:22`).
Layers are looped (mamba/attention mix); the depthwise conv converts between
HF's [C, 1, K] and our [K, C].
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from llm_training_tpu.models.bamba.config import BambaConfig
from llm_training_tpu.models.llama.hf_conversion import (
    _get_path,
    _set_path,
    _to_numpy,
)
from llm_training_tpu.models.moe_scan_io import (
    periodic_layers_from_hf,
    periodic_layers_to_hf,
)

_ATTN = [
    (("self_attn", "q_proj", "kernel"), "self_attn.q_proj.weight", True),
    (("self_attn", "k_proj", "kernel"), "self_attn.k_proj.weight", True),
    (("self_attn", "v_proj", "kernel"), "self_attn.v_proj.weight", True),
    (("self_attn", "o_proj", "kernel"), "self_attn.o_proj.weight", True),
]

_MAMBA = [
    (("mamba", "in_proj", "kernel"), "mamba.in_proj.weight", True),
    (("mamba", "out_proj", "kernel"), "mamba.out_proj.weight", True),
    (("mamba", "norm", "weight"), "mamba.norm.weight", False),
    (("mamba", "A_log"), "mamba.A_log", False),
    (("mamba", "D"), "mamba.D", False),
    (("mamba", "dt_bias"), "mamba.dt_bias", False),
]

_COMMON = [
    (("feed_forward", "gate_proj", "kernel"), "feed_forward.gate_proj.weight", True),
    (("feed_forward", "up_proj", "kernel"), "feed_forward.up_proj.weight", True),
    (("feed_forward", "down_proj", "kernel"), "feed_forward.down_proj.weight", True),
    (("input_layernorm", "weight"), "input_layernorm.weight", False),
    (("pre_ff_layernorm", "weight"), "pre_ff_layernorm.weight", False),
]


def _layer_params(config: BambaConfig, i: int) -> list:
    params = list(_ATTN if config.layer_is_attention(i) else _MAMBA)
    if config.layer_is_attention(i):
        if config.attention_bias:
            params += [
                ((("self_attn", proj, "bias")), f"self_attn.{proj}.bias", False)
                for proj in ("q_proj", "k_proj", "v_proj", "o_proj")
            ]
    else:
        if config.mamba_conv_bias:
            params.append((("mamba", "conv_bias"), "mamba.conv1d.bias", False))
        if config.mamba_proj_bias:
            params += [
                (("mamba", "in_proj", "bias"), "mamba.in_proj.bias", False),
                (("mamba", "out_proj", "bias"), "mamba.out_proj.bias", False),
            ]
    if config.mlp_bias:
        params += [
            ((("feed_forward", proj, "bias")), f"feed_forward.{proj}.bias", False)
            for proj in ("gate_proj", "up_proj", "down_proj")
        ]
    return params + _COMMON


def params_from_hf(
    state_dict: Mapping[str, Any], config: BambaConfig, leaf_fn: Any = None
) -> dict:
    params: dict = {}
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}

    def put(path, value):
        _set_path(params, path, leaf_fn(path, value) if leaf_fn else value)

    put(("embed_tokens", "embedding"), _to_numpy(sd["embed_tokens.weight"]))
    put(("final_layernorm", "weight"), _to_numpy(sd["final_layernorm.weight"]))
    if not config.tie_word_embeddings:
        put(("lm_head", "kernel"), _to_numpy(sd["lm_head.weight"]).T)

    def extras(sd, i):
        if config.layer_is_attention(i):
            return {}
        # HF depthwise conv [C, 1, K] -> our [K, C]
        return {
            ("mamba", "conv_kernel"): lambda: _to_numpy(
                sd[f"layers.{i}.mamba.conv1d.weight"]
            )[:, 0, :].T
        }

    periodic_layers_from_hf(sd, config, put, _layer_params, extras_fn=extras)
    return {"params": params}


def params_to_hf(params: Mapping, config: BambaConfig) -> dict[str, np.ndarray]:
    import flax.linen as nn

    p = params.get("params", params)
    p = nn.meta.unbox(p)
    out: dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(_get_path(p, ("embed_tokens", "embedding")))
    out["model.final_layernorm.weight"] = np.asarray(_get_path(p, ("final_layernorm", "weight")))
    if not config.tie_word_embeddings:
        out["lm_head.weight"] = np.asarray(_get_path(p, ("lm_head", "kernel"))).T

    def extras_out(get, i, out):
        if not config.layer_is_attention(i):
            conv = get(("mamba", "conv_kernel"))
            out[f"model.layers.{i}.mamba.conv1d.weight"] = conv.T[:, None, :]

    periodic_layers_to_hf(p, config, out, _layer_params, extras_out_fn=extras_out)
    return out


def config_to_hf(config: BambaConfig, torch_dtype: str = "bfloat16") -> dict[str, Any]:
    return {
        "architectures": ["BambaForCausalLM"],
        "model_type": "bamba",
        "vocab_size": config.vocab_size,
        "hidden_size": config.hidden_size,
        "intermediate_size": config.intermediate_size,
        "num_hidden_layers": config.num_hidden_layers,
        "num_attention_heads": config.num_attention_heads,
        "num_key_value_heads": config.num_key_value_heads,
        "attn_layer_indices": config.attn_layer_indices,
        "mamba_n_heads": config.mamba_n_heads,
        "mamba_d_head": config.mamba_d_head,
        "mamba_n_groups": config.mamba_n_groups,
        "mamba_d_state": config.mamba_d_state,
        "mamba_expand": config.mamba_expand,
        "mamba_d_conv": config.mamba_d_conv,
        "mamba_conv_bias": config.mamba_conv_bias,
        "mamba_proj_bias": config.mamba_proj_bias,
        "mamba_chunk_size": config.mamba_chunk_size,
        "hidden_act": "silu",
        "max_position_embeddings": config.max_position_embeddings,
        "initializer_range": config.initializer_range,
        "rms_norm_eps": config.rms_norm_eps,
        "pad_token_id": config.pad_token_id,
        "bos_token_id": config.bos_token_id,
        "eos_token_id": config.eos_token_id,
        "tie_word_embeddings": config.tie_word_embeddings,
        "rope_theta": config.rope_theta,
        "rope_scaling": config.rope_scaling,
        "partial_rotary_factor": config.partial_rotary_factor,
        "attention_bias": config.attention_bias,
        "attention_dropout": config.attention_dropout,
        "mlp_bias": config.mlp_bias,
        "use_cache": True,
        "torch_dtype": torch_dtype,
    }


def config_from_hf(hf_config: Any, **overrides: Any) -> BambaConfig:
    get = (lambda k, d=None: hf_config.get(k, d)) if isinstance(hf_config, dict) else (
        lambda k, d=None: getattr(hf_config, k, d)
    )
    return BambaConfig(**{**dict(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads"),
        max_position_embeddings=get("max_position_embeddings", 262144),
        initializer_range=get("initializer_range", 0.02),
        rms_norm_eps=get("rms_norm_eps", 1e-5),
        pad_token_id=get("pad_token_id"),
        bos_token_id=get("bos_token_id", 1),
        eos_token_id=get("eos_token_id", 2),
        tie_word_embeddings=get("tie_word_embeddings", False),
        rope_theta=get("rope_theta", 10000.0),
        rope_scaling=get("rope_scaling"),
        partial_rotary_factor=get("partial_rotary_factor", 0.5),
        attention_bias=get("attention_bias", False),
        attention_dropout=get("attention_dropout", 0.0),
        mlp_bias=get("mlp_bias", False),
        attn_layer_indices=list(get("attn_layer_indices") or []) or None,
        mamba_n_heads=get("mamba_n_heads", 128),
        mamba_d_head=get("mamba_d_head", 64),
        mamba_n_groups=get("mamba_n_groups", 1),
        mamba_d_state=get("mamba_d_state", 256),
        mamba_expand=get("mamba_expand", 2),
        mamba_d_conv=get("mamba_d_conv", 4),
        mamba_conv_bias=get("mamba_conv_bias", True),
        mamba_proj_bias=get("mamba_proj_bias", False),
        mamba_chunk_size=get("mamba_chunk_size", 256),
    ), **overrides})
