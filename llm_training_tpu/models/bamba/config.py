"""Bamba (IBM Mamba-2 / attention hybrid) model config.

Family member beyond the reference's named models (the reference reaches
Bamba only through `HFCausalLM`'s torch wrapping, `hf_causal_lm.py:22`);
here the Mamba-2 SSD graph is native. Mirrors HF `BambaConfig`.
"""

from __future__ import annotations

from typing import Any, Literal

from pydantic import model_validator

from llm_training_tpu.models.base import BaseModelConfig


class BambaConfig(BaseModelConfig):
    vocab_size: int = 128000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 262144
    initializer_range: float = 0.02
    rms_norm_eps: float = 1e-5
    pad_token_id: int | None = 0
    bos_token_id: int | None = 1
    eos_token_id: int | list[int] | None = 2
    tie_word_embeddings: bool = False
    rope_theta: float = 10000.0
    rope_scaling: dict[str, Any] | None = None
    partial_rotary_factor: float = 0.5
    attention_bias: bool = False
    attention_dropout: float = 0.0
    mlp_bias: bool = False

    # attention replaces mamba at these layer indices (None = pure mamba)
    attn_layer_indices: list[int] | None = None

    # --- mamba-2 mixer
    mamba_n_heads: int = 128
    mamba_d_head: int = 64
    mamba_n_groups: int = 1
    mamba_d_state: int = 256
    mamba_expand: int = 2
    mamba_d_conv: int = 4
    mamba_conv_bias: bool = True
    mamba_proj_bias: bool = False
    mamba_chunk_size: int = 256
    # opt-in: reset the SSD state at packed-document boundaries (HF leaks
    # state across documents; see mamba2_ssd)
    segment_state_reset: bool = False

    enable_gradient_checkpointing: bool = False
    recompute_granularity: Literal["full", "selective"] = "full"
    # a periodic mamba/attention pattern scans as one body per period;
    # non-periodic attn_layer_indices (the released Bamba-9B placement) loop
    scan_layers: bool = True
    attention_impl: Literal["auto", "xla", "pallas"] = "auto"

    @model_validator(mode="after")
    def _validate(self) -> "BambaConfig":
        if self.attention_dropout != 0.0:
            raise ValueError("attention_dropout is not supported; set it to 0.0")
        if self.mamba_n_heads * self.mamba_d_head != self.mamba_intermediate:
            raise ValueError(
                "mamba_n_heads * mamba_d_head must equal "
                "mamba_expand * hidden_size"
            )
        if self.num_attention_heads % self.num_key_value_heads:
            raise ValueError(
                f"num_attention_heads ({self.num_attention_heads}) must be "
                f"divisible by num_key_value_heads ({self.num_key_value_heads})"
            )
        if self.mamba_n_heads % self.mamba_n_groups:
            raise ValueError("mamba_n_heads must be divisible by mamba_n_groups")
        if self.attn_layer_indices:
            bad = [i for i in self.attn_layer_indices
                   if not 0 <= i < self.num_hidden_layers]
            if bad:
                raise ValueError(f"attn_layer_indices out of range: {bad}")
        self.rope_config
        return self

    @property
    def mamba_intermediate(self) -> int:
        return self.mamba_expand * self.hidden_size

    @property
    def mamba_conv_dim(self) -> int:
        return self.mamba_intermediate + 2 * self.mamba_n_groups * self.mamba_d_state

    @property
    def resolved_head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def rope_config(self):
        from llm_training_tpu.ops.rope_utils import rope_config_from_hf

        return rope_config_from_hf(
            self.rope_scaling, self.rope_theta,
            int(self.resolved_head_dim * self.partial_rotary_factor),
            self.max_position_embeddings,
        )

    def layer_is_attention(self, layer_idx: int) -> bool:
        return bool(self.attn_layer_indices) and layer_idx in self.attn_layer_indices

    @property
    def scan_period(self) -> int:
        """Scan-body depth (0 = loop), from the mamba/attention repetition."""
        if not self.scan_layers:
            return 0
        from llm_training_tpu.models.moe_scan_io import detect_period

        return detect_period(
            [self.layer_is_attention(i) for i in range(self.num_hidden_layers)]
        )
