"""Base model config + output types.

Capability parity: reference `models/base_model/base_model.py:14-74`
(config-carrying module, init_weights gate, parallelize hooks — the hooks
dissolve into logical-axis metadata here) and
`models/utils/modeling_outputs.py:11-13` (`CausalLMOutput`).
"""

from __future__ import annotations

from typing import Literal

import flax.struct
import jax.numpy as jnp
from pydantic import BaseModel, ConfigDict, field_validator


_DTYPE_MAP = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float64": jnp.float64,
}

DTypeName = Literal["float32", "bfloat16", "float16", "float64"]


def resolve_dtype(name: str) -> jnp.dtype:
    try:
        return _DTYPE_MAP[name]
    except KeyError:
        raise ValueError(f"unknown dtype {name!r}; expected one of {sorted(_DTYPE_MAP)}")


class BaseModelConfig(BaseModel):
    """Common model-config surface.

    `pre_trained_weights` mirrors the reference's weight-source field
    (`base_model.py:32-33`); dtype fields replace its str→torch.dtype
    validator (`base_model_config.py`) with str→jnp names resolved lazily.

    The master-weights scheme of the reference (`optim/master_weight_wrapper.py`)
    is expressed here directly: params live in `param_dtype` (fp32), the
    forward runs in `compute_dtype` (bf16), optimizer state stays fp32.
    """

    model_config = ConfigDict(extra="forbid")

    pre_trained_weights: str | None = None
    compute_dtype: DTypeName = "bfloat16"
    param_dtype: DTypeName = "float32"

    @field_validator("compute_dtype")
    @classmethod
    def _no_fp16_compute(cls, value: str) -> str:
        # fp16 without dynamic loss scaling silently under/overflows; TPUs are
        # bf16-native (same exponent range as fp32), so the reference's fp16 +
        # DeepSpeed loss-scale path (deepspeed_strategy.py:104-108) has no TPU
        # analogue — reject rather than train broken
        if value == "float16":
            raise ValueError(
                "compute_dtype='float16' is not supported: fp16 requires "
                "dynamic loss scaling, which TPUs don't need — use 'bfloat16' "
                "(same exponent range as fp32, MXU-native)"
            )
        return value

    @property
    def compute_jnp_dtype(self) -> jnp.dtype:
        return resolve_dtype(self.compute_dtype)

    @property
    def param_jnp_dtype(self) -> jnp.dtype:
        return resolve_dtype(self.param_dtype)


@flax.struct.dataclass
class CausalLMOutput:
    """Forward output (reference `modeling_outputs.py:11-13`).

    `logits` is None when the objective requests hidden states only (for
    fused-linear-CE, which needs the pre-head activations). `aux_loss` is
    the unscaled MoE load-balancing loss (None for dense models).
    `ep_dropped_rows` counts (token, expert) assignments lost to the
    expert-parallel capacity buffer this step, summed over layers (None for
    dense models; exactly 0 when ep=1 or routing fits the buffer) — the
    observability VERDICT r4 asked for on the static-capacity EP path."""

    logits: jnp.ndarray | None = None
    last_hidden_states: jnp.ndarray | None = None
    aux_loss: jnp.ndarray | None = None
    ep_dropped_rows: jnp.ndarray | None = None
