"""Base model config + output types.

Capability parity: reference `models/base_model/base_model.py:14-74`
(config-carrying module, init_weights gate, parallelize hooks — the hooks
dissolve into logical-axis metadata here) and
`models/utils/modeling_outputs.py:11-13` (`CausalLMOutput`).
"""

from __future__ import annotations

from typing import Literal

import flax.struct
import jax.numpy as jnp
from pydantic import BaseModel, ConfigDict, field_validator


_DTYPE_MAP = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float64": jnp.float64,
}

DTypeName = Literal["float32", "bfloat16", "float16", "float64"]


def resolve_dtype(name: str) -> jnp.dtype:
    try:
        return _DTYPE_MAP[name]
    except KeyError:
        raise ValueError(f"unknown dtype {name!r}; expected one of {sorted(_DTYPE_MAP)}")


class BaseModelConfig(BaseModel):
    """Common model-config surface.

    `pre_trained_weights` mirrors the reference's weight-source field
    (`base_model.py:32-33`); dtype fields replace its str→torch.dtype
    validator (`base_model_config.py`) with str→jnp names resolved lazily.

    The master-weights scheme of the reference (`optim/master_weight_wrapper.py`)
    is expressed here directly: params live in `param_dtype` (fp32), the
    forward runs in `compute_dtype` (bf16), optimizer state stays fp32.
    """

    model_config = ConfigDict(extra="forbid")

    pre_trained_weights: str | None = None
    compute_dtype: DTypeName = "bfloat16"
    param_dtype: DTypeName = "float32"

    @field_validator("compute_dtype")
    @classmethod
    def _no_fp16_compute(cls, value: str) -> str:
        # fp16 without dynamic loss scaling silently under/overflows; TPUs are
        # bf16-native (same exponent range as fp32), so the reference's fp16 +
        # DeepSpeed loss-scale path (deepspeed_strategy.py:104-108) has no TPU
        # analogue — reject rather than train broken
        if value == "float16":
            raise ValueError(
                "compute_dtype='float16' is not supported: fp16 requires "
                "dynamic loss scaling, which TPUs don't need — use 'bfloat16' "
                "(same exponent range as fp32, MXU-native)"
            )
        return value

    @property
    def compute_jnp_dtype(self) -> jnp.dtype:
        return resolve_dtype(self.compute_dtype)

    @property
    def param_jnp_dtype(self) -> jnp.dtype:
        return resolve_dtype(self.param_dtype)


@flax.struct.dataclass
class RouterStats:
    """Per-MoE-layer router statistics, threaded out of every MoE family
    for the model-health layer (`telemetry/health.py:moe_router_health`).

    `sel_frac [L, E]`: fraction of (token, slot) assignments routed to each
    expert per MoE layer (rows sum to ~top_k — each of the K selections per
    token counts, HF `load_balancing_loss_func` scale). `mean_prob [L, E]`:
    mean fp32 routing probability per expert (sigmoid-routed families —
    DeepSeek-V3 — normalize scores per token first so entropy stays
    meaningful). `dropped`: scalar total of (token, expert) assignments
    lost to capacity buffers across layers. `layer_ids` is STATIC (not a
    pytree leaf): the absolute decoder-layer index of each row, so metric
    keys name real layers even when only a suffix of the stack is MoE
    (DeepSeek's dense prefix). The arrays already exist pre-pooling in
    every family's aux-loss computation, so populating this costs nothing
    when unused — XLA dead-code-eliminates the extra outputs."""

    sel_frac: jnp.ndarray
    mean_prob: jnp.ndarray
    dropped: jnp.ndarray
    layer_ids: tuple[int, ...] = flax.struct.field(pytree_node=False, default=())


@flax.struct.dataclass
class DecodeState:
    """Static-shape, mesh-sharded KV cache threaded through the decoder
    stack for autoregressive decoding (`infer/` subsystem, docs/inference.md).

    `k`/`v` are `[num_layers, batch, max_length, num_kv_heads, head_dim]`
    buffers in the cache dtype (param dtype by default, fp32/bf16
    configurable); the leading layer axis is the scan axis under
    `scan_layers` and an indexed axis on the looped path, sharded like the
    scanned param stacks (replicated), while heads shard over 'tensor' and
    batch over 'data'/'fsdp' exactly like attention activations.

    `index` is a traced int32 scalar: the number of tokens already written,
    i.e. the absolute kv position the incoming chunk appends at. It is
    SHARED across the batch — prompts are LEFT-padded to a common width so
    every row appends at the same slot (per-row write offsets would need a
    scatter instead of one `dynamic_update_slice`). `segment_ids [batch,
    max_length]` marks which cache slots hold real tokens (1) vs left-pad /
    not-yet-written garbage (0); the attention mask's `seg > 0` term makes
    unwritten slots unreachable, and the causal term (`q_offset = index`)
    keeps the chunk from seeing slots written after it."""

    k: jnp.ndarray
    v: jnp.ndarray
    index: jnp.ndarray
    segment_ids: jnp.ndarray
    # STATIC (not a pytree leaf): the sequence length the generation will
    # actually reach (padded prompt width + max_new_tokens). Length-
    # dependent RoPE variants (longrope short/long factor selection,
    # dynamic NTK) must key off THIS, not the cache capacity — a cache
    # over-allocated for reuse (max_length >> planned length) must not
    # flip a Phi-3 checkpoint onto its long-context tables. None = fall
    # back to the cache capacity.
    rope_length: int | None = flax.struct.field(pytree_node=False, default=None)

    @property
    def max_length(self) -> int:
        return self.k.shape[2]

    @property
    def table_length(self) -> int:
        """The length RoPE table selection should see (static)."""
        return self.rope_length or self.max_length


@flax.struct.dataclass
class PagedDecodeState:
    """Block-table KV cache for the serving subsystem (`serve/`,
    docs/serving.md) — the continuous-batching successor to `DecodeState`'s
    shared-append-index layout.

    `k`/`v` are `[num_layers, num_blocks, block_size, num_kv_heads,
    head_dim]` POOL buffers: fixed-size blocks allocated to requests by the
    host-side `serve.paged_cache.BlockAllocator` (physical block 0 is a
    reserved trash block — idle decode slots and padded chunk positions
    write there, so garbage rows can never corrupt a live request's cache).
    `block_tables [batch, max_blocks_per_request]` maps each row's logical
    block index to a physical pool block; `lengths [batch]` is each row's
    token count already written — per-row, unlike `DecodeState.index`,
    which is what lets a finished request's blocks be recycled and a new
    request join mid-flight without left-padding anyone.

    The decoder stacks thread this through the SAME `layer_kv`/`kv_index`/
    `kv_segment_ids` plumbing as `DecodeState` (kv_index carries the [B]
    lengths, kv_segment_ids carries the block tables); attention layers
    dispatch on `kv_index.ndim` to `ops.paged_attention`."""

    k: jnp.ndarray
    v: jnp.ndarray
    block_tables: jnp.ndarray
    lengths: jnp.ndarray
    # STATIC: planned total sequence length for length-dependent RoPE table
    # selection (same contract as DecodeState.rope_length); None = the
    # per-request capacity block_tables can address.
    rope_length: int | None = flax.struct.field(pytree_node=False, default=None)

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def max_length(self) -> int:
        """Per-request addressable capacity (blocks per table x block size)."""
        return self.block_tables.shape[1] * self.block_size

    @property
    def table_length(self) -> int:
        """The length RoPE table selection should see (static)."""
        return self.rope_length or self.max_length


@flax.struct.dataclass
class CausalLMOutput:
    """Forward output (reference `modeling_outputs.py:11-13`).

    `logits` is None when the objective requests hidden states only (for
    fused-linear-CE, which needs the pre-head activations). `aux_loss` is
    the unscaled MoE load-balancing loss (None for dense models).
    `ep_dropped_rows` counts (token, expert) assignments lost to the
    expert-parallel capacity buffer this step, summed over layers (None for
    dense models; exactly 0 when ep=1 or routing fits the buffer) — the
    observability VERDICT r4 asked for on the static-capacity EP path.
    `router_stats` carries the pre-pooled per-layer router statistics
    (None for dense models) for the health-metric layer. `decode_state` is
    the updated KV cache when the forward was called with one (None on the
    training path)."""

    logits: jnp.ndarray | None = None
    last_hidden_states: jnp.ndarray | None = None
    aux_loss: jnp.ndarray | None = None
    ep_dropped_rows: jnp.ndarray | None = None
    router_stats: RouterStats | None = None
    decode_state: DecodeState | None = None
