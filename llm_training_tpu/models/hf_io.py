"""HuggingFace checkpoint IO: streamed loading + safetensors export.

Capability parity: the reference's pre-trained-weight path
(`lms/base_lm.py:175-193` — rank-0 `torch.load` + broadcast/scatter) and the
export half of `scripts/convert_to_hf.py:101-162`. TPU-native design: instead
of loading everything on one rank and broadcasting over NCCL, each tensor is
read lazily from safetensors and `jax.device_put` with its `NamedSharding` —
every host reads only once, XLA scatters the shards over ICI, and the host
working set stays one-tensor-sized.

Reading goes through the torch framework of `safetensors` (torch is CPU-only
here) so bf16 files round-trip exactly; writing uses `safetensors.torch` with
`{"format": "pt"}` metadata, which is what `transformers.from_pretrained`
expects.
"""

from __future__ import annotations

import functools
import json
import logging
from collections.abc import Mapping
from pathlib import Path
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

_SAFE_INDEX = "model.safetensors.index.json"
_SAFE_SINGLE = "model.safetensors"

# config-class name -> conversion module; each module provides
# params_from_hf / params_to_hf / config_from_hf / config_to_hf
_FAMILIES: dict[str, str] = {
    "LlamaConfig": "llm_training_tpu.models.llama.hf_conversion",
    "Phi3Config": "llm_training_tpu.models.phi3.hf_conversion",
    "GemmaConfig": "llm_training_tpu.models.gemma.hf_conversion",
    "DeepseekConfig": "llm_training_tpu.models.deepseek.hf_conversion",
    "GptOssConfig": "llm_training_tpu.models.gpt_oss.hf_conversion",
    "Qwen3NextConfig": "llm_training_tpu.models.qwen3_next.hf_conversion",
    "MiniMaxConfig": "llm_training_tpu.models.minimax.hf_conversion",
    "BambaConfig": "llm_training_tpu.models.bamba.hf_conversion",
    "Glm4MoeConfig": "llm_training_tpu.models.glm4_moe.hf_conversion",
    "Ernie45MoeConfig": "llm_training_tpu.models.ernie45_moe.hf_conversion",
    "HunYuanMoeConfig": "llm_training_tpu.models.hunyuan_moe.hf_conversion",
}


def conversion_module(config: Any):
    import importlib

    name = type(config).__name__
    if name not in _FAMILIES:
        raise ValueError(
            f"no HF conversion registered for {name}; known: {sorted(_FAMILIES)}"
        )
    return importlib.import_module(_FAMILIES[name])


class LazyStateDict(Mapping):
    """Mapping over one or more safetensors files that reads each tensor on
    first access (and never holds more than the caller keeps alive)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._key_to_file: dict[str, Path] = {}
        self._handles: dict[Path, Any] = {}
        for file, keys in self._discover():
            for key in keys:
                self._key_to_file[key] = file

    def _discover(self) -> Iterator[tuple[Path, list[str]]]:
        from safetensors import safe_open

        if self.path.is_file():
            files = [self.path]
        elif (self.path / _SAFE_INDEX).exists():
            index = json.loads((self.path / _SAFE_INDEX).read_text())
            files = sorted({self.path / f for f in index["weight_map"].values()})
        elif (self.path / _SAFE_SINGLE).exists():
            files = [self.path / _SAFE_SINGLE]
        else:
            files = sorted(self.path.glob("*.safetensors"))
        if not files:
            raise FileNotFoundError(
                f"no safetensors found under {self.path} "
                f"(expected {_SAFE_SINGLE} or {_SAFE_INDEX})"
            )
        for file in files:
            with safe_open(file, framework="pt") as f:
                yield file, list(f.keys())

    def _handle(self, file: Path):
        from safetensors import safe_open

        if file not in self._handles:
            self._handles[file] = safe_open(file, framework="pt")
        return self._handles[file]

    def __getitem__(self, key: str):
        return self._handle(self._key_to_file[key]).get_tensor(key)

    def __iter__(self):
        return iter(self._key_to_file)

    def __len__(self) -> int:
        return len(self._key_to_file)


def load_hf_config(path: str | Path) -> dict:
    config_file = Path(path) / "config.json" if Path(path).is_dir() else Path(path)
    return json.loads(config_file.read_text())


@functools.lru_cache(maxsize=None)
def _device_cast(dtype_name: str):
    # one compiled cast per (dtype, shape/sharding) via the jit cache —
    # astype preserves the operand's sharding, so no out_shardings needed
    return jax.jit(lambda x: x.astype(jnp.dtype(dtype_name)))


def _pp_stages(config: Any) -> int:
    return int(getattr(config, "pipeline_stages", 1) or 1)


def _pp_wrap_leaf_fn(config: Any, leaf_fn):
    """Pipeline-layout load adapter (models/pipeline.py): conversions emit
    the scan layout — stacked leaves [L, ...] under ('layers', ...) — but a
    pipelined model stores [S, L/S, ...] under ('pipeline', 'ticks',
    'layers', ...). Reshape on host BEFORE placement (so the device_put
    lands on the stage-sharded buffers) and look shardings up under the
    pipeline path; `_pp_relocate` moves the subtree afterwards."""
    stages = _pp_stages(config)
    per = config.num_hidden_layers // stages

    def wrapped(path: tuple[str, ...], value):
        if path and path[0] == "layers":
            value = value.reshape((stages, per) + value.shape[1:])
            path = ("pipeline", "ticks") + path
        return leaf_fn(path, value) if leaf_fn is not None else value

    return wrapped


def _pp_relocate(tree: Any, config: Any) -> Any:
    """Move the converted scan stack to its pipeline-layout position (the
    conversion's `_set_path` keyed it by the original 'layers' path)."""
    params = tree.get("params", tree)
    if "layers" in params:
        params.setdefault("pipeline", {}).setdefault("ticks", {})[
            "layers"
        ] = params.pop("layers")
    return tree


def _pp_as_scan(params: Mapping, config: Any) -> Mapping:
    """Pipeline-layout export adapter: present the [S, L/S, ...] stage
    stacks as the [L, ...] scan layout the conversions consume. The
    reshape merges the stage axis lazily; values cross to host once,
    inside the conversion's own per-path fetch."""
    import flax.linen as nn

    p = params.get("params", params)
    if "pipeline" not in p:
        return params
    p = dict(p)
    stack = nn.meta.unbox(p.pop("pipeline"))["ticks"]["layers"]
    p["layers"] = jax.tree.map(
        lambda v: v.reshape((v.shape[0] * v.shape[1],) + v.shape[2:]), stack
    )
    return {"params": p} if "params" in params else p


def load_pretrained_params(
    config: Any,
    hf_path: str | Path | Mapping,
    shardings: Any | None = None,
    dtypes: Any | None = None,
) -> Any:
    """HF checkpoint dir -> flax param tree `{'params': ...}`.

    When `shardings` (a matching pytree of NamedSharding) is given, each leaf
    is `device_put` straight to its shards and the host copy is dropped —
    the memory-safe analogue of the reference's broadcast distribution
    (`base_lm.py:175-193`). `dtypes` (matching pytree or single dtype) casts
    leaves on the way in (e.g. fp32 master params from a bf16 checkpoint).

    `hf_path` may also be an in-memory Mapping of HF keys -> tensors
    (tests / already-open checkpoints) instead of a directory.
    """
    conv = conversion_module(config)
    state_dict = (
        hf_path if isinstance(hf_path, Mapping) else LazyStateDict(hf_path)
    )

    pipelined = _pp_stages(config) > 1

    if shardings is None and dtypes is None:
        if pipelined:
            tree = conv.params_from_hf(
                state_dict, config, leaf_fn=_pp_wrap_leaf_fn(config, None)
            )
            return _pp_relocate(tree, config)
        return conv.params_from_hf(state_dict, config)

    by_path = _flatten_by_path(shardings)
    dtypes_by_path = (
        _flatten_by_path(dtypes) if _is_pytree(dtypes) else None
    )

    def leaf_fn(path: tuple[str, ...], value: np.ndarray):
        key = ("params",) + path
        dtype = dtypes_by_path[key] if dtypes_by_path is not None else dtypes
        sharding = by_path.get(key) if by_path is not None else None
        if sharding is not None:
            target = jnp.dtype(dtype) if dtype is not None else None
            if target is not None and target.itemsize < value.dtype.itemsize:
                # NARROWING (e.g. fp32 checkpoint -> bf16 leaves): cast on
                # host so the transfer ships the small copy
                value = value.astype(target)
            # WIDENING (bf16 checkpoint -> fp32 masters) happens on device:
            # a host-side astype would hold checkpoint + widened copies
            # simultaneously (at 70B geometry a scanned mlp stack is ~37 GB
            # bf16 — the fp32 cast would transiently need ~112 GB of host
            # RAM; on device the transient is per-chip and freed per leaf)
            placed = jax.device_put(value, sharding)
            if target is not None and placed.dtype != target:
                placed = _device_cast(target.name)(placed)
            return placed
        if dtype is not None:
            value = value.astype(dtype)
        return value

    # each converted leaf is placed (device_put) inside the conversion walk,
    # so the host never holds more than one (stacked) tensor at a time
    if pipelined:
        tree = conv.params_from_hf(
            state_dict, config, leaf_fn=_pp_wrap_leaf_fn(config, leaf_fn)
        )
        return _pp_relocate(tree, config)
    return conv.params_from_hf(state_dict, config, leaf_fn=leaf_fn)


def _is_pytree(value: Any) -> bool:
    return isinstance(value, (dict, list, tuple))


def _flatten_by_path(tree: Any) -> dict[tuple[str, ...], Any] | None:
    """pytree -> {('params', 'embed_tokens', ...): leaf} with string keys."""
    if tree is None:
        return None
    flat: dict[tuple[str, ...], Any] = {}
    for key_path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[tuple(str(getattr(k, "key", k)) for k in key_path)] = leaf
    return flat


def _as_torch_state_dict(state_dict: Mapping[str, np.ndarray], dtype: str):
    import torch

    torch_dtype = getattr(torch, dtype)
    out = {}
    for key, value in state_dict.items():
        array = np.asarray(value)
        if array.dtype.name == "bfloat16":  # ml_dtypes bf16: torch can't ingest it
            array = array.astype(np.float32)
        out[key] = torch.from_numpy(np.ascontiguousarray(array)).to(torch_dtype)
    return out


def save_hf_checkpoint(
    params: Mapping,
    config: Any,
    output_dir: str | Path,
    dtype: str = "bfloat16",
    max_shard_bytes: int = 5 * 1024**3,
    generation_config: dict | None = None,
) -> Path:
    """flax params + config -> HF-layout dir (safetensors shards + index +
    config.json). Reference: `scripts/convert_to_hf.py:76-97`."""
    from safetensors.torch import save_file

    conv = conversion_module(config)
    output_dir = Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)

    if _pp_stages(config) > 1:
        params = _pp_as_scan(params, config)
    state_dict = _as_torch_state_dict(conv.params_to_hf(params, config), dtype)

    # shard greedily in key order, HF-style file naming
    shards: list[dict[str, Any]] = [{}]
    sizes = [0]
    for key, tensor in state_dict.items():
        nbytes = tensor.numel() * tensor.element_size()
        if sizes[-1] + nbytes > max_shard_bytes and shards[-1]:
            shards.append({})
            sizes.append(0)
        shards[-1][key] = tensor
        sizes[-1] += nbytes

    if len(shards) == 1:
        save_file(shards[0], output_dir / _SAFE_SINGLE, metadata={"format": "pt"})
    else:
        weight_map = {}
        for i, shard in enumerate(shards):
            name = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
            save_file(shard, output_dir / name, metadata={"format": "pt"})
            weight_map.update({key: name for key in shard})
        index = {
            "metadata": {"total_size": sum(sizes)},
            "weight_map": weight_map,
        }
        (output_dir / _SAFE_INDEX).write_text(json.dumps(index, indent=2))

    hf_config = conv.config_to_hf(config, torch_dtype=dtype)
    (output_dir / "config.json").write_text(json.dumps(hf_config, indent=2) + "\n")
    if generation_config:
        (output_dir / "generation_config.json").write_text(
            json.dumps(generation_config, indent=2) + "\n"
        )
    return output_dir


_ARCH_TO_FAMILY = {
    # HF model_type -> our (model class path, conversion config name)
    "llama": "llm_training_tpu.models.Llama",
    "mistral": "llm_training_tpu.models.Llama",  # same graph: GQA + SwiGLU + RMSNorm
    "ministral": "llm_training_tpu.models.Llama",  # + per-layer sliding/full pattern
    "helium": "llm_training_tpu.models.Llama",  # llama graph (o_proj bias hardcoded off)
    "arcee": "llm_training_tpu.models.Llama",  # non-gated relu^2 MLP under rmsnorm
    "seed_oss": "llm_training_tpu.models.Llama",  # qkv bias + separate o-bias flag
    "qwen2": "llm_training_tpu.models.Llama",  # + attention_bias (in config.json)
    "qwen3": "llm_training_tpu.models.Llama",  # + per-head qk-norm
    "olmo": "llm_training_tpu.models.Llama",  # OLMo-1: non-parametric LayerNorm, clip_qkv
    "olmo2": "llm_training_tpu.models.Llama",  # + post-norm blocks, full qk-norm
    "olmo3": "llm_training_tpu.models.Llama",  # + per-layer sliding, dual rope
    "granite": "llm_training_tpu.models.Llama",  # + 4 scalar multipliers
    "starcoder2": "llm_training_tpu.models.Llama",  # LayerNorm + gelu MLP + biases
    "stablelm": "llm_training_tpu.models.Llama",  # biased LayerNorm + swiglu + partial rope
    "cohere": "llm_training_tpu.models.Llama",  # parallel blocks, interleaved rope
    "cohere2": "llm_training_tpu.models.Llama",  # + sliding/full pattern, NoPE full layers
    "code_llama": "llm_training_tpu.models.Llama",  # llama graph verbatim
    "phi": "llm_training_tpu.models.Llama",  # parallel + partial rotary + biases
    "nemotron": "llm_training_tpu.models.Llama",  # layernorm1p + relu^2 MLP
    "ernie4_5": "llm_training_tpu.models.Llama",  # interleaved full-dim rope
    "ernie4_5_moe": "llm_training_tpu.models.Ernie45Moe",  # + aux-free softmax MoE
    "hunyuan_v1_dense": "llm_training_tpu.models.Llama",  # post-rope qk-norm
    "hunyuan_v1_moe": "llm_training_tpu.models.HunYuanMoe",  # + softmax top-k MoE
    "gpt2": "llm_training_tpu.models.Llama",  # learned positions, fused qkv
    "gpt_neox": "llm_training_tpu.models.Llama",  # Pythia: two-norm parallel, interleaved fused qkv
    "smollm3": "llm_training_tpu.models.Llama",  # per-layer NoPE
    "exaone4": "llm_training_tpu.models.Llama",  # post-norm + head qk-norm + hybrid NoPE
    "apertus": "llm_training_tpu.models.Llama",  # non-gated xIELU MLP + head qk-norm
    "glm": "llm_training_tpu.models.Llama",  # interleaved partial rope, fused gate_up
    "glm4": "llm_training_tpu.models.Llama",  # + sandwich norms
    "glm4_moe": "llm_training_tpu.models.Glm4Moe",  # GLM-4.5: V3-style noaux MoE
    "dots1": "llm_training_tpu.models.Glm4Moe",  # + full rotary, qk-norm, sliding pattern
    "deepseek_v2": "llm_training_tpu.models.Deepseek",  # MLA + grouped MoE
    "deepseek_v3": "llm_training_tpu.models.Deepseek",  # + sigmoid noaux routing
    "kimi_k2": "llm_training_tpu.models.Deepseek",  # Kimi-K2: V3 graph verbatim
    "gpt_oss": "llm_training_tpu.models.GptOss",  # sink attention + clamped-swiglu MoE
    "qwen3_next": "llm_training_tpu.models.Qwen3Next",  # hybrid gated DeltaNet
    "minimax": "llm_training_tpu.models.MiniMax",  # hybrid lightning attention
    "bamba": "llm_training_tpu.models.Bamba",  # Mamba-2 SSD + attention hybrid
    # sparse MoE variants: stacked-expert MoEMLP block (models/moe.py)
    "mixtral": "llm_training_tpu.models.Llama",
    "phimoe": "llm_training_tpu.models.Llama",  # Phi-3.5-MoE: SparseMixer routing + biased LN
    "granitemoe": "llm_training_tpu.models.Llama",  # granite multipliers + fused-stack MoE
    "granitemoeshared": "llm_training_tpu.models.Llama",  # + always-on shared MLP
    "qwen2_moe": "llm_training_tpu.models.Llama",
    "qwen3_moe": "llm_training_tpu.models.Llama",
    "olmoe": "llm_training_tpu.models.Llama",  # full qk-norm + qwen-style MoE
    "flex_olmo": "llm_training_tpu.models.Llama",  # OLMoE MoE under olmo2 post-norm
    "phi3": "llm_training_tpu.models.Phi3",
    "gemma": "llm_training_tpu.models.Gemma",
    "gemma2": "llm_training_tpu.models.Gemma",  # version=2 graph features
    "gemma3_text": "llm_training_tpu.models.Gemma",  # version=3 graph features
}


def model_class_for_hf(hf_config: dict, assume_llama_layout: bool = False) -> str:
    """HF `config.json` -> our model class path (the `HFCausalLM` analogue,
    reference `models/hf_causal_lm/hf_causal_lm.py:22`, for architectures
    whose computation graph one of our TPU modules reproduces).

    `assume_llama_layout=True` routes UNKNOWN model_types to the Llama
    family: many fine-tune forks only rename a llama-graph architecture, and
    the llama conversion fails loudly on any state-dict key or hparam it
    does not recognize, so a wrong assumption cannot load silently."""
    model_type = hf_config.get("model_type")
    if model_type not in _ARCH_TO_FAMILY:
        if assume_llama_layout:
            logger.warning(
                "unknown HF model_type %r routed to the Llama family "
                "(assume_llama_layout=True): correctness depends on the "
                "checkpoint really using the llama graph/key layout",
                model_type,
            )
            return "llm_training_tpu.models.Llama"
        raise ValueError(
            f"unsupported HF model_type {model_type!r}; supported: "
            f"{sorted(_ARCH_TO_FAMILY)}. If the architecture is a renamed "
            "llama-layout fork, set assume_llama_layout=true on HFCausalLM"
        )
    return _ARCH_TO_FAMILY[model_type]
