"""Dotted-path class resolution shared by the config system, model
provider, and HF architecture router."""

from __future__ import annotations

import importlib


def import_class(class_path: str) -> type:
    module_name, _, class_name = class_path.rpartition(".")
    if not module_name:
        raise ValueError(f"class_path must be fully qualified, got {class_path!r}")
    return getattr(importlib.import_module(module_name), class_name)
