"""Continuous-batching serving engine (docs/serving.md).

Two jitted programs over the SAME sharded decoder stack the trainer runs,
both against the paged pool (donated — the cache mutates in place in HBM):

- `prefill_chunk`: ONE request's next prompt chunk (batch 1, static chunk
  width) written into its own blocks; samples the first new token when the
  chunk completes the prompt;
- `decode_step`: one token for EVERY decoding slot (static `max_batch`
  rows) through the ragged paged-attention path — each row at its own
  length, no shared append index, no left padding. Idle slots carry the
  trash-block table and cost one garbage row.

The host loop (`step()`) executes what the `Scheduler` decides: admission
when free blocks suffice, one prefill chunk interleaved between decode
steps, eviction/requeue under block pressure, slot recycling on eos /
max-tokens. Per-request TTFT/TPOT and engine throughput publish as
`serve/*` gauges (rendered by `report`'s `== Serving ==` section).

Resilience seams (docs/serving.md#resilience):

- every step first expires deadlines and re-evaluates shedding, so a
  terminal chunk (`deadline` / `overloaded`) is never more than one step
  late;
- `reload_weights` hot-swaps the model variables BETWEEN steps: every
  running request is evicted through the standard fold-in requeue (its
  paged cache was built under the old weights and must not mix), the new
  buffers are bound, and `serve/weights_generation` bumps — every chunk
  carries the `generation` it was decoded under, so a client can see
  exactly where the swap landed in its stream;
- an attached `RequestJournal` (`attach_journal`) records accept/progress/
  done so `drain()` — the SIGTERM path — can evict-and-journal everything
  in flight (freeing every pool block) and a relaunch can `submit_resumed`
  the remainder, continuing token-identically without re-streaming;
- chaos serve faults (`LLMT_CHAOS_SERVE_*`, resilience/chaos.py) hook the
  top of `step()` so a wedged step and a mid-stream SIGTERM are injectable
  exactly where they would really land.
"""

from __future__ import annotations

import logging
import math
import os
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import BaseModel, ConfigDict, model_validator

from llm_training_tpu.infer.sampling import (
    SamplingConfig,
    sample_tokens_with_logprob,
)
from llm_training_tpu.models.base import PagedDecodeState
from llm_training_tpu.resilience.chaos import get_chaos
from llm_training_tpu.serve.paged_cache import (
    BlockAllocator,
    init_paged_pool,
    pool_bytes,
    resolve_block_size,
)
from llm_training_tpu.serve.scheduler import (
    Scheduler,
    SchedulerConfig,
    ServeRequest,
)
from llm_training_tpu.telemetry.trace import get_tracer

logger = logging.getLogger(__name__)

# newest terminals live_stats() scans for its rolling TTFT/TPOT
# percentiles: bounds the per-scrape cost on a long-lived server whose
# completed list grows without bound
_LIVE_WINDOW = 512

# terminals that are the engine SHEDDING load to protect its SLO, not
# request failures: counted as serve/requests_shed, never requests_failed
_SHED_REASONS = ("deadline", "overloaded")


class ServeConfig(BaseModel):
    """Serving knobs (docs/serving.md#knobs)."""

    model_config = ConfigDict(extra="forbid")

    max_batch: int = 4  # decode slots (static decode-program batch)
    max_model_len: int = 256  # per-request cap: prompt + generation
    # tokens per KV block; None resolves via ops/pallas/tuning.py
    # (PAGED_BLOCK_K env > tuning table > 16)
    block_size: int | None = None
    # pool capacity in blocks (excl. the trash block); None sizes for
    # max_batch full-length requests — no block pressure by default
    num_blocks: int | None = None
    prefill_chunk: int = 32  # tokens per prefill-chunk program call
    # intake bound: queued requests past this are shed with an honest
    # stop_reason='overloaded' terminal; None = unbounded
    max_queue: int | None = None
    # shed when the queue tail's projected TTFT (EMA service-time
    # estimate) crosses this many ms; None disables
    shed_ttft_ms: float | None = None
    cache_dtype: str | None = None
    seed: int = 0
    eos_token_id: int | None = None
    sampling: SamplingConfig = SamplingConfig()

    @model_validator(mode="after")
    def _validate(self) -> "ServeConfig":
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_model_len < 2:
            raise ValueError(
                f"max_model_len must be >= 2, got {self.max_model_len}"
            )
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}"
            )
        if self.max_queue is not None and self.max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {self.max_queue}")
        if self.shed_ttft_ms is not None and self.shed_ttft_ms <= 0:
            raise ValueError(
                f"shed_ttft_ms must be > 0, got {self.shed_ttft_ms}"
            )
        if self.block_size is not None and self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks is not None and self.num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {self.num_blocks}")
        return self


class ServingEngine:
    """Drives a restored model under continuous batching. Construction
    mirrors `InferenceEngine` (model, variables, optional mesh+rules);
    traffic goes through `submit()` + `step()` (or `run()` for a closed
    request set)."""

    def __init__(
        self,
        model: Any,
        variables: Any,
        config: ServeConfig | None = None,
        mesh: Any | None = None,
        rules: Any = (),
    ):
        from llm_training_tpu.infer.engine import supports_decoding

        if not supports_decoding(model):
            raise NotImplementedError(
                f"{type(model).__name__} does not support KV-cache decoding "
                "(no decode_state in its __call__) — see docs/inference.md"
            )
        self.model = model
        self.variables = variables
        self.mesh = mesh
        self.rules = rules
        self.config = config or ServeConfig()

        model_config = model.config
        self.block_size = resolve_block_size(
            model_config, self.config.max_model_len,
            self.config.block_size, self.config.cache_dtype,
        )
        self.pages_per_request = math.ceil(
            self.config.max_model_len / self.block_size
        )
        num_blocks = self.config.num_blocks
        if num_blocks is None:
            num_blocks = self.config.max_batch * self.pages_per_request
        with self._ctx():
            self._pool_k, self._pool_v = init_paged_pool(
                model_config, num_blocks + 1, self.block_size,
                mesh=self.mesh, rules=self.rules,
                cache_dtype=self.config.cache_dtype,
            )
        self.allocator = BlockAllocator(num_blocks + 1)
        self.scheduler = Scheduler(
            SchedulerConfig(
                max_batch=self.config.max_batch,
                max_model_len=self.config.max_model_len,
                block_size=self.block_size,
                prefill_chunk=self.config.prefill_chunk,
                max_queue=self.config.max_queue,
                shed_ttft_ms=self.config.shed_ttft_ms,
            ),
            self.allocator,
        )
        self._build_programs()
        self._rng = jax.random.key(self.config.seed)
        self._call = 0
        self._t0: float | None = None
        self._step_index = 0
        self.tokens_generated = 0
        self.peak_running = 0
        # hot weight reload (docs/serving.md#resilience): bumps on every
        # reload_weights; every emitted chunk carries the generation it was
        # decoded under
        self.weights_generation = 0
        # request journal (attach_journal): accepted/progress/done records
        # that let a supervised relaunch replay accepted-but-unfinished work
        self.journal = None
        self._journal_every = 1
        # terminals built but possibly not yet delivered to the caller:
        # their journal `done` records are deferred to the NEXT step (or
        # drain), by which point the CLI has flushed the chunks — a death
        # in between re-delivers a detectable duplicate terminal on replay
        # instead of silently losing one the journal claims was delivered
        self._unretired: list[ServeRequest] = []
        self.replayed_requests = 0
        # protocol-truth terminal counters (bumped in _done_event, the one
        # place every terminal passes): live_stats reads them so a scrape
        # never pays O(full completion history) — and they match the
        # client-side census by construction. Shed load (deadline/
        # overloaded — the engine protecting its SLO) is tallied apart
        # from real failures: conflating them poisons both RL rollout
        # accounting and the SLO error-rate stream
        self._done_full = 0
        self._done_shed = 0
        self._done_failed = 0
        # one-shot decode-step attribution (LLMT_PROFILE_ATTR_DECODE=1,
        # docs/observability.md#device-plane): the first real decode batch
        # supplies the concrete avals needed to AOT-lower the step for
        # cost/HLO analysis; off by default — it pays one extra XLA compile
        self._decode_attr_done = not bool(
            os.environ.get("LLMT_PROFILE_ATTR_DECODE")
        )

    # ------------------------------------------------------------ programs

    def _ctx(self):
        from llm_training_tpu.infer.engine import mesh_context

        return mesh_context(self.mesh, self.rules)

    def _build_programs(self) -> None:
        model = self.model
        sampling = self.config.sampling
        rope_length = self.config.max_model_len

        def prefill_chunk(variables, ids, seg, pos, pool_k, pool_v,
                          tables, length, last_pos, rng):
            state = PagedDecodeState(
                k=pool_k, v=pool_v, block_tables=tables, lengths=length,
                rope_length=rope_length,
            )
            out = model.apply(
                variables, input_ids=ids, segment_ids=seg,
                position_ids=pos, decode_state=state,
            )
            logits = jax.lax.dynamic_index_in_dim(
                out.logits[0], last_pos, axis=0, keepdims=False
            ).astype(jnp.float32)
            token, logprob = sample_tokens_with_logprob(
                logits[None], rng, sampling
            )
            state = out.decode_state
            return state.k, state.v, token[0], logprob[0]

        def decode_step(variables, tokens, pool_k, pool_v, tables, lengths, rng):
            state = PagedDecodeState(
                k=pool_k, v=pool_v, block_tables=tables, lengths=lengths,
                rope_length=rope_length,
            )
            out = model.apply(
                variables, input_ids=tokens[:, None],
                position_ids=lengths[:, None], decode_state=state,
            )
            logits = out.logits[:, -1].astype(jnp.float32)
            token, logprob = sample_tokens_with_logprob(logits, rng, sampling)
            state = out.decode_state
            return state.k, state.v, token, logprob

        self._prefill_jit = jax.jit(prefill_chunk, donate_argnums=(4, 5))
        self._decode_jit = jax.jit(decode_step, donate_argnums=(2, 3))

    def _next_rng(self):
        self._call += 1
        return jax.random.fold_in(self._rng, self._call)

    def _table_row(self, request: ServeRequest) -> np.ndarray:
        row = np.zeros((self.pages_per_request,), np.int32)
        row[: len(request.blocks)] = request.blocks
        return row

    # -------------------------------------------------------------- intake

    def submit(
        self,
        id: str,
        prompt: Sequence[int],
        max_new_tokens: int = 32,
        priority: int = 0,
        deadline_ms: float | None = None,
    ) -> list[dict]:
        """Queue one request; returns immediately-emittable events — a
        rejection completes synchronously, and enqueueing over the intake
        bound may shed a (possibly different) queued request with
        stop_reason='overloaded'. `deadline_ms` is a latency budget
        anchored at arrival; a non-positive one is already expired and
        terminates with stop_reason='deadline' on the spot."""
        if self._t0 is None:
            self._t0 = time.perf_counter()
        request = ServeRequest(
            # coerce every token NOW: a non-int prompt (e.g. a JSON string
            # that slipped through the CLI) must fail at submit — where the
            # caller's error handling lives — not steps later inside the
            # decode loop, taking every in-flight request with it
            id=str(id), prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens), priority=int(priority),
        )
        if deadline_ms is not None:
            request.deadline_s = request.arrival_s + float(deadline_ms) / 1000.0
        tracer = get_tracer()
        request.traced = tracer.sample_request()
        tracer.instant(
            "serve", "submit", ts=request.arrival_s, write=request.traced,
            request_id=request.id, prompt_len=len(request.prompt),
            max_new_tokens=request.max_new_tokens, priority=request.priority,
            **({"deadline_ms": float(deadline_ms)} if deadline_ms is not None else {}),
        )
        return self._ingest(request)

    def submit_resumed(self, entry: dict) -> list[dict]:
        """Resubmit one `replay_journal` entry after a relaunch: the
        journaled continuation folds in exactly like an eviction requeue
        (re-prefill of prompt + generated under the CURRENT weights), and
        the `emitted` watermark keeps already-streamed tokens from being
        re-sent. Deadlines re-anchor at the resumed arrival — the original
        clock died with the original process."""
        if self._t0 is None:
            self._t0 = time.perf_counter()
        request = ServeRequest(
            id=str(entry["id"]),
            prompt=[int(t) for t in entry["prompt"]],
            max_new_tokens=int(entry["max_new_tokens"]),
            priority=int(entry.get("priority", 0)),
        )
        request.generated = [int(t) for t in entry.get("generated", [])]
        # restore the per-token logprobs alongside the tokens; a journal
        # written before logprob collection pads with None (the rollout
        # collector treats such samples as unusable, never as zeros)
        logprobs = [
            None if lp is None else float(lp)
            for lp in (entry.get("logprobs") or [])
        ][: len(request.generated)]
        logprobs += [None] * (len(request.generated) - len(logprobs))
        request.logprobs = logprobs
        request.emitted = min(int(entry.get("emitted", 0)), len(request.generated))
        if entry.get("deadline_ms") is not None:
            request.deadline_s = (
                request.arrival_s + float(entry["deadline_ms"]) / 1000.0
            )
        tracer = get_tracer()
        request.traced = tracer.sample_request()
        tracer.instant(
            "serve", "submit", ts=request.arrival_s, write=request.traced,
            request_id=request.id, prompt_len=len(request.prompt),
            max_new_tokens=request.max_new_tokens, priority=request.priority,
            replayed=True, generated=len(request.generated),
        )
        self.replayed_requests += 1
        if len(request.generated) >= request.max_new_tokens:
            # the journal caught the final token but not the done record:
            # nothing left to decode — retire it here
            request.stop_reason = "max_tokens"
            request.advance_phase("done")
            self.scheduler.completed.append(request)
            return [self._done_event(request)]
        return self._ingest(request)

    def _ingest(self, request: ServeRequest) -> list[dict]:
        """Hand one constructed request to the scheduler and emit the
        terminals submission itself produced (rejection, shed victims)."""
        before = len(self.scheduler.completed)
        self.scheduler.submit(request)
        if not request.done and self.journal is not None:
            self.journal.accepted(request)
            if request.generated or request.emitted:
                # a replayed request's folded continuation must survive a
                # SECOND death immediately: acceptance alone records only
                # the prompt, and the rotation backup is deleted once
                # replay completes
                self.journal.progress(request)
        return [
            self._done_event(completed)
            for completed in self.scheduler.completed[before:]
        ]

    # ---------------------------------------------------------- resilience

    def attach_journal(self, journal, every: int = 1) -> None:
        """Record request lifetimes into `journal` (serve/journal.py);
        progress checkpoints are written every `every` engine steps (and
        always at drain)."""
        self.journal = journal
        self._journal_every = max(1, int(every))

    def _retire_finished(self) -> None:
        """Write the deferred `done` records for terminals the caller has
        had a chance to deliver (everything built before this step)."""
        if self.journal is None or not self._unretired:
            return
        retired, self._unretired = self._unretired, []
        for request in retired:
            self.journal.finished(request)

    def reload_weights(self, variables: Any) -> int:
        """Hot-swap the model weights between engine steps
        (docs/serving.md#reload): every running request is evicted through
        the standard fold-in requeue — its paged KV was computed under the
        OLD weights and must not be decoded against the new ones — then the
        new buffers are bound and `serve/weights_generation` bumps. In-
        flight streams continue token-identically to a fresh engine on the
        new weights fed prompt + tokens-so-far; nothing is dropped or
        re-streamed. `variables` must be restore_for_inference-shaped: the
        same tree/shapes/dtypes (and shardings under a mesh) the engine was
        built with. Returns the new generation."""
        old = jax.tree.structure(self.variables)
        new = jax.tree.structure(variables)
        if old != new:
            raise ValueError(
                "reload_weights: variable tree mismatch — the reload must "
                "be the same architecture restored the same way "
                f"(got {new}, engine holds {old})"
            )
        for old_leaf, new_leaf in zip(
            jax.tree.leaves(self.variables), jax.tree.leaves(variables)
        ):
            if (
                getattr(old_leaf, "shape", None) != getattr(new_leaf, "shape", None)
                or getattr(old_leaf, "dtype", None) != getattr(new_leaf, "dtype", None)
            ):
                raise ValueError(
                    "reload_weights: leaf shape/dtype mismatch "
                    f"({getattr(new_leaf, 'shape', None)}/"
                    f"{getattr(new_leaf, 'dtype', None)} vs engine's "
                    f"{getattr(old_leaf, 'shape', None)}/"
                    f"{getattr(old_leaf, 'dtype', None)})"
                )
        evicted = 0
        for request in list(self.scheduler.running.values()):
            self.scheduler.evict(request)
            evicted += 1
        self.variables = variables
        self.weights_generation += 1
        from llm_training_tpu.telemetry import get_registry

        get_registry().gauge("serve/weights_generation").set(
            float(self.weights_generation)
        )
        get_tracer().instant(
            "serve", "weights_reload", generation=self.weights_generation,
            evicted_for_reload=evicted,
        )
        logger.info(
            "weights reloaded: generation %d (%d in-flight request(s) "
            "folded for re-prefill)", self.weights_generation, evicted,
        )
        return self.weights_generation

    def drain(self) -> dict:
        """Evict-and-journal everything in flight — the graceful-shutdown
        tail (docs/serving.md#drain). Running requests fold their progress
        through the standard eviction requeue (freeing EVERY pool block, so
        a drained engine never leaks), then every queued request is
        checkpointed to the journal for a relaunch to `submit_resumed`. No
        terminal chunks are emitted: the relaunch owes them. Returns a
        summary for the drain trace event."""
        # the drain caller has emitted every returned event by now
        self._retire_finished()
        for request in list(self.scheduler.running.values()):
            self.scheduler.evict(request)
        journaled = 0
        for request in self.scheduler.waiting:
            if self.journal is not None:
                self.journal.progress(request)
                journaled += 1
        summary = {
            "journaled": journaled,
            "blocks_in_use": self.allocator.blocks_in_use,
            "step": self._step_index,
        }
        get_tracer().instant("serve", "drain", **summary)
        logger.warning(
            "drain: %d unfinished request(s) journaled for replay "
            "(%d pool blocks in use)", journaled, self.allocator.blocks_in_use,
        )
        return summary

    # ---------------------------------------------------------------- step

    def step(self) -> list[dict]:
        """One scheduler round: deadline expiry, admissions, shedding, at
        most one prefill chunk, one decode step over every decoding row.
        Returns the streamed events ({'type': 'token', ...} per new token,
        {'type': 'done', ...} per completion — deadline/overloaded
        terminations included)."""
        events: list[dict] = []
        tracer = get_tracer()
        self._step_index += 1
        # terminals and token chunks returned from the PREVIOUS step have
        # been delivered by now (the caller emits between steps): retire
        # finished ids and checkpoint progress/emitted watermarks before
        # this step can wedge or die. Journaling either at build time
        # would let a death between build and flush lose a terminal (or
        # skip re-streaming tokens the client never saw).
        self._retire_finished()
        if self.journal is not None and self._step_index % self._journal_every == 0:
            for request in self.scheduler.running.values():
                self.journal.progress(request)
        # chaos serve faults (docs/resilience.md#chaos): a wedged step and
        # a mid-stream SIGTERM are injected exactly where the real ones
        # land — the top of an engine step, heartbeat already owed
        chaos = get_chaos()
        if chaos is not None:
            chaos.maybe_serve_stall(self._step_index)
            chaos.maybe_serve_sigterm_mid_stream(self._step_index)
        with tracer.measure(
            "serve", "engine_step", step=self._step_index,
            running=len(self.scheduler.running),
            waiting=len(self.scheduler.waiting),
        ), self._ctx():
            before = len(self.scheduler.completed)
            # deadlines first: expired queued work never costs a FLOP and
            # an expired decode row frees its blocks before admission looks
            # at the pool
            self.scheduler.expire_deadlines()
            self.scheduler.admit()
            # the service-time EMA moves with every completion, so the
            # projected-TTFT shed decision is re-evaluated each step too
            self.scheduler.shed()
            # scheduler-side completions (capacity/deadline/overloaded) are
            # completions — the protocol owes each a done chunk like any
            # other
            for request in self.scheduler.completed[before:]:
                events.append(self._done_event(request))
            self.peak_running = max(self.peak_running, len(self.scheduler.running))
            plan = self.scheduler.next_prefill()
            if plan is not None:
                events.extend(self._run_prefill(*plan))
            rows = self.scheduler.decode_rows()
            if rows:
                events.extend(self._run_decode(rows))
        return events

    def _emit_token(
        self,
        request: ServeRequest,
        token: int,
        events: list[dict],
        logprob: float | None = None,
    ) -> None:
        now = time.perf_counter()
        request.generated.append(token)
        # parallel to `generated`: the chosen token's logprob under the
        # sampled distribution (rollout collection trains on these). None
        # only for tokens restored from a pre-logprob journal.
        request.logprobs.append(logprob)
        self.tokens_generated += 1
        if request.first_token_s is None:
            request.first_token_s = now
            get_tracer().instant(
                "serve", "first_token", ts=now, write=request.traced,
                request_id=request.id,
                # the same arrival-anchored value stats()/done events carry:
                # an evicted-then-resumed request's TTFT is measured from
                # its ORIGINAL arrival, never the requeue
                ttft_ms=round(1000.0 * (now - request.arrival_s), 3),
            )
        request.last_token_s = now
        # an evicted-then-resumed request regenerates nothing (its progress
        # rode along in the re-prefill), so every append past `emitted` is
        # genuinely new — emit it
        while request.emitted < len(request.generated):
            events.append({
                "type": "token", "id": request.id,
                "token": request.generated[request.emitted],
                "logprob": request.logprobs[request.emitted],
                # the weights generation this token was decoded under — a
                # mid-stream reload_weights is visible exactly where it
                # landed (docs/serving.md#reload)
                "generation": self.weights_generation,
            })
            request.emitted += 1
        eos = self.config.eos_token_id
        if eos is not None and token == eos:
            self.scheduler.finish(request, "eos")
            events.append(self._done_event(request))
        elif len(request.generated) >= request.max_new_tokens:
            self.scheduler.finish(request, "max_tokens")
            events.append(self._done_event(request))

    def _run_prefill(self, request: ServeRequest, chunk: list[int], start: int) -> list[dict]:
        events: list[dict] = []
        t_chunk = time.perf_counter()
        width = self.config.prefill_chunk
        ids = np.zeros((1, width), np.int32)
        seg = np.zeros((1, width), np.int32)
        ids[0, : len(chunk)] = chunk
        seg[0, : len(chunk)] = 1
        pos = np.minimum(
            start + np.arange(width), self.config.max_model_len - 1
        ).astype(np.int32)[None, :]
        tables = self._table_row(request)[None, :]
        final = start + len(chunk) >= len(request.prefill_tokens)
        self._pool_k, self._pool_v, token, logprob = self._prefill_jit(
            self.variables, jnp.asarray(ids), jnp.asarray(seg),
            jnp.asarray(pos), self._pool_k, self._pool_v,
            jnp.asarray(tables), jnp.asarray([start], jnp.int32),
            jnp.int32(len(chunk) - 1), self._next_rng(),
        )
        request.prefilled += len(chunk)
        request.cache_len += len(chunk)
        if final:
            host_token, host_logprob = jax.device_get((token, logprob))
            self._emit_token(
                request, int(host_token), events, logprob=float(host_logprob)
            )
        now = time.perf_counter()
        get_tracer().span(
            "serve", "prefill_chunk", t_chunk, now, write=request.traced,
            request_id=request.id, start=start, tokens=len(chunk), final=final,
        )
        if final and not request.done:
            # the first new token landed inside the prefill phase; decode
            # (one token per engine step) starts here
            request.advance_phase("decode", now)
        return events

    def _run_decode(self, rows: list[ServeRequest]) -> list[dict]:
        events: list[dict] = []
        # grow each row's blocks for this step's write; under pool pressure
        # this evicts lowest-priority requests (possibly out of `rows`)
        survivors = []
        for request in rows:
            if request.slot is not None and self.scheduler.ensure_decode_blocks(request):
                survivors.append(request)
        # a LATER row's block-pressure eviction can take an EARLIER
        # survivor (lower priority, mid-page so its own check passed) —
        # its slot is gone and its blocks may already belong to the
        # evictor, so it must not decode this step
        survivors = [r for r in survivors if r.slot is not None]
        if not survivors:
            return events
        batch = self.config.max_batch
        tokens = np.zeros((batch,), np.int32)
        lengths = np.zeros((batch,), np.int32)
        tables = np.zeros((batch, self.pages_per_request), np.int32)
        for request in survivors:
            tokens[request.slot] = request.generated[-1]
            lengths[request.slot] = request.cache_len
            tables[request.slot] = self._table_row(request)
        step_args = (
            self.variables, jnp.asarray(tokens), self._pool_k, self._pool_v,
            jnp.asarray(tables), jnp.asarray(lengths), self._next_rng(),
        )
        if not self._decode_attr_done:
            # before the donating call below: lowering only reads avals,
            # while the jit consumes the pool buffers
            self._decode_attr_done = True
            self._publish_decode_attribution(step_args)
        self._pool_k, self._pool_v, out, out_lp = self._decode_jit(*step_args)
        host, host_lp = jax.device_get((out, out_lp))
        host = np.asarray(host)
        host_lp = np.asarray(host_lp)
        for request in survivors:
            request.cache_len += 1
            self._emit_token(
                request, int(host[request.slot]), events,
                logprob=float(host_lp[request.slot]),
            )
        return events

    def _publish_decode_attribution(self, step_args) -> None:
        """AOT-lower the decode step against the first real batch's avals
        and publish its compute/comm split as attr/decode/* gauges
        (docs/observability.md#device-plane). The lowering pays one extra
        XLA compile — why LLMT_PROFILE_ATTR_DECODE gates this off by
        default; any failure degrades to a warning, never a dropped step."""
        try:
            from llm_training_tpu.telemetry.device import (
                compiled_attribution_gauges,
            )
            from llm_training_tpu.telemetry.registry import get_registry

            with self._ctx():
                compiled = self._decode_jit.lower(*step_args).compile()
            mesh_axes = None
            if self.mesh is not None:
                mesh_axes = dict(
                    zip(self.mesh.axis_names, self.mesh.devices.shape)
                )
            registry = get_registry()
            for name, value in compiled_attribution_gauges(
                compiled, mesh_axes
            ).items():
                registry.gauge(
                    "attr/decode/" + name.removeprefix("attr/")
                ).set(value)
        except Exception as e:  # noqa: BLE001 — attribution is best-effort
            logger.warning("decode-step attribution unavailable: %s", e)

    def _done_event(self, request: ServeRequest) -> dict:
        if request.stop_reason in ("eos", "max_tokens"):
            self._done_full += 1
        elif request.stop_reason in _SHED_REASONS:
            self._done_shed += 1
        else:
            self._done_failed += 1
        if self.journal is not None:
            self._unretired.append(request)
        event = {
            "type": "done", "id": request.id,
            "stop_reason": request.stop_reason,
            "tokens": list(request.generated),
            "logprobs": list(request.logprobs),
            "n_tokens": len(request.generated),
            "evictions": request.evictions,
            "generation": self.weights_generation,
        }
        if request.first_token_s is not None:
            event["ttft_ms"] = round(
                1000.0 * (request.first_token_s - request.arrival_s), 3
            )
        if request.last_token_s is not None and len(request.generated) > 1:
            event["tpot_ms"] = round(
                1000.0 * (request.last_token_s - request.first_token_s)
                / (len(request.generated) - 1), 3,
            )
        get_tracer().instant(
            "serve", "done", write=request.traced, request_id=request.id,
            stop_reason=request.stop_reason, n_tokens=len(request.generated),
            evictions=request.evictions,
            queue_wait_ms=round(1000.0 * request.queue_wait_s, 3),
            **({"ttft_ms": event["ttft_ms"]} if "ttft_ms" in event else {}),
        )
        return event

    # ----------------------------------------------------------------- run

    def run(self, requests: Sequence[dict], max_steps: int = 100_000) -> list[dict]:
        """Submit a closed request set and step until drained. Each request
        dict: {'id', 'prompt', 'max_new_tokens'?, 'priority'?}. Returns all
        events in emission order."""
        events: list[dict] = []
        for request in requests:
            events.extend(self.submit(**request))
        for _ in range(max_steps):
            if self.scheduler.idle:
                break
            events.extend(self.step())
        else:
            raise RuntimeError(f"serve loop not drained after {max_steps} steps")
        return events

    # --------------------------------------------------------------- stats

    def _completed_latencies(self) -> tuple[list, list, list[float], list[float]]:
        """(all terminals, full completions, ttft_ms, tpot_ms) over the
        requests finished so far — the ONE filter + latency math both
        `stats()` and `live_stats()` render, so the scraped live
        percentiles can never disagree with the end-of-run record. Pure
        host reads (list snapshot under the GIL) — safe from the
        exporter's scrape threads."""
        completed_all = list(self.scheduler.completed)
        completed = [
            r for r in completed_all if r.stop_reason in ("eos", "max_tokens")
        ]
        ttft = [
            1000.0 * (r.first_token_s - r.arrival_s)
            for r in completed if r.first_token_s is not None
        ]
        tpot = [
            1000.0 * (r.last_token_s - r.first_token_s) / (len(r.generated) - 1)
            for r in completed
            if r.last_token_s is not None and len(r.generated) > 1
        ]
        return completed_all, completed, ttft, tpot

    def live_stats(self) -> dict[str, float]:
        """Scrape-time gauges for the live-telemetry exporter
        (docs/observability.md#live-telemetry): queue depth, in-flight
        rows, and rolling completion/latency numbers. The latency scan is
        bounded to the newest `_LIVE_WINDOW` terminals — on a long-lived
        server `scheduler.completed` grows without bound, and a 2 Hz
        Prometheus scrape must not pay O(full request history) per scrape
        (rolling percentiles over recent completions are also the more
        honest live signal). Counts stay exact (len() is O(1); the
        failed tally rides the schedulers' terminal counters). Called
        from the exporter's handler threads — read-only over host state,
        never a jax call, so a scrape can never perturb or block the
        decode loop."""
        recent = self.scheduler.completed[-_LIVE_WINDOW:]
        completed = [
            r for r in recent if r.stop_reason in ("eos", "max_tokens")
        ]
        ttft = [
            1000.0 * (r.first_token_s - r.arrival_s)
            for r in completed if r.first_token_s is not None
        ]
        tpot = [
            1000.0 * (r.last_token_s - r.first_token_s) / (len(r.generated) - 1)
            for r in completed
            if r.last_token_s is not None and len(r.generated) > 1
        ]
        out = {
            "serve/queue_depth": float(len(self.scheduler.waiting)),
            "serve/running": float(len(self.scheduler.running)),
            "serve/engine_steps": float(self._step_index),
            "serve/requests_completed": float(self._done_full),
            "serve/requests_failed": float(self._done_failed),
            "serve/requests_shed": float(self._done_shed),
            "serve/tokens_generated": float(self.tokens_generated),
            "serve/weights_generation": float(self.weights_generation),
            "decode/cache_blocks_in_use": float(self.allocator.blocks_in_use),
        }
        if ttft:
            out["serve/ttft_p50_ms"] = float(np.percentile(ttft, 50))
            out["serve/ttft_p99_ms"] = float(np.percentile(ttft, 99))
        if tpot:
            out["serve/tpot_p50_ms"] = float(np.percentile(tpot, 50))
            out["serve/tpot_p99_ms"] = float(np.percentile(tpot, 99))
        return out

    def stats(self) -> dict[str, float]:
        """Engine/latency summary, published as `serve/*` gauges (merged
        into telemetry.jsonl by the CLI; `report` renders `== Serving ==`)."""
        from llm_training_tpu.telemetry import get_registry

        completed_all, completed, ttft, tpot = self._completed_latencies()
        wall = (time.perf_counter() - self._t0) if self._t0 is not None else 0.0
        n_chips = max(1, jax.device_count())
        tps = self.tokens_generated / wall if wall > 0 else 0.0
        # shed load (deadline/overloaded) is the engine protecting its SLO;
        # requests_failed is what remains — real errors (rejection etc.)
        shed = sum(
            1 for r in completed_all if r.stop_reason in _SHED_REASONS
        )
        stats = {
            "serve/requests_completed": float(len(completed)),
            "serve/requests_failed": float(
                len(completed_all) - len(completed) - shed
            ),
            "serve/requests_shed": float(shed),
            "serve/requests_evicted": float(self.scheduler.evictions),
            "serve/shed_total": float(self.scheduler.shed_total),
            "serve/deadline_total": float(self.scheduler.deadline_total),
            "serve/weights_generation": float(self.weights_generation),
            "serve/replayed_requests": float(self.replayed_requests),
            "serve/tokens_generated": float(self.tokens_generated),
            "serve/tokens_per_sec": tps,
            "serve/tokens_per_sec_per_chip": tps / n_chips,
            "serve/peak_running": float(self.peak_running),
            "decode/cache_bytes": float(pool_bytes(self._pool_k, self._pool_v)),
            "decode/cache_blocks_total": float(self.allocator.num_blocks - 1),
            "decode/cache_blocks_in_use": float(self.allocator.blocks_in_use),
            "decode/cache_peak_blocks_in_use": float(self.allocator.peak_in_use),
        }
        if ttft:
            stats["serve/ttft_p50_ms"] = float(np.percentile(ttft, 50))
            stats["serve/ttft_p99_ms"] = float(np.percentile(ttft, 99))
        if tpot:
            stats["serve/tpot_p50_ms"] = float(np.percentile(tpot, 50))
            stats["serve/tpot_p99_ms"] = float(np.percentile(tpot, 99))
        counts = get_tracer().counts()
        stats["trace/events_recorded"] = float(counts["recorded"])
        stats["trace/events_written"] = float(counts["written"])
        stats["trace/requests_sampled"] = float(counts["requests_sampled"])
        registry = get_registry()
        for key, value in stats.items():
            registry.gauge(key).set(value)
        logger.info(
            "serve: %d completed (%d evictions) | %.1f tokens/s (%.1f/chip)",
            len(completed), self.scheduler.evictions, tps, stats["serve/tokens_per_sec_per_chip"],
        )
        return stats
