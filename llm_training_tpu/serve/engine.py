"""Continuous-batching serving engine (docs/serving.md).

Two jitted programs over the SAME sharded decoder stack the trainer runs,
both against the paged pool (donated — the cache mutates in place in HBM):

- `prefill_chunk`: ONE request's next prompt chunk (batch 1, static chunk
  width) written into its own blocks; samples the first new token when the
  chunk completes the prompt;
- `decode_step`: one token for EVERY decoding slot (static `max_batch`
  rows) through the ragged paged-attention path — each row at its own
  length, no shared append index, no left padding. Idle slots carry the
  trash-block table and cost one garbage row.

The host loop (`step()`) executes what the `Scheduler` decides: admission
when free blocks suffice, one prefill chunk interleaved between decode
steps, eviction/requeue under block pressure, slot recycling on eos /
max-tokens. Per-request TTFT/TPOT and engine throughput publish as
`serve/*` gauges (rendered by `report`'s `== Serving ==` section).
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import BaseModel, ConfigDict, model_validator

from llm_training_tpu.infer.sampling import SamplingConfig, sample_tokens
from llm_training_tpu.models.base import PagedDecodeState
from llm_training_tpu.serve.paged_cache import (
    BlockAllocator,
    init_paged_pool,
    pool_bytes,
    resolve_block_size,
)
from llm_training_tpu.serve.scheduler import (
    Scheduler,
    SchedulerConfig,
    ServeRequest,
)
from llm_training_tpu.telemetry.trace import get_tracer

logger = logging.getLogger(__name__)


class ServeConfig(BaseModel):
    """Serving knobs (docs/serving.md#knobs)."""

    model_config = ConfigDict(extra="forbid")

    max_batch: int = 4  # decode slots (static decode-program batch)
    max_model_len: int = 256  # per-request cap: prompt + generation
    # tokens per KV block; None resolves via ops/pallas/tuning.py
    # (PAGED_BLOCK_K env > tuning table > 16)
    block_size: int | None = None
    # pool capacity in blocks (excl. the trash block); None sizes for
    # max_batch full-length requests — no block pressure by default
    num_blocks: int | None = None
    prefill_chunk: int = 32  # tokens per prefill-chunk program call
    cache_dtype: str | None = None
    seed: int = 0
    eos_token_id: int | None = None
    sampling: SamplingConfig = SamplingConfig()

    @model_validator(mode="after")
    def _validate(self) -> "ServeConfig":
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_model_len < 2:
            raise ValueError(
                f"max_model_len must be >= 2, got {self.max_model_len}"
            )
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}"
            )
        if self.block_size is not None and self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks is not None and self.num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {self.num_blocks}")
        return self


class ServingEngine:
    """Drives a restored model under continuous batching. Construction
    mirrors `InferenceEngine` (model, variables, optional mesh+rules);
    traffic goes through `submit()` + `step()` (or `run()` for a closed
    request set)."""

    def __init__(
        self,
        model: Any,
        variables: Any,
        config: ServeConfig | None = None,
        mesh: Any | None = None,
        rules: Any = (),
    ):
        from llm_training_tpu.infer.engine import supports_decoding

        if not supports_decoding(model):
            raise NotImplementedError(
                f"{type(model).__name__} does not support KV-cache decoding "
                "(no decode_state in its __call__) — see docs/inference.md"
            )
        self.model = model
        self.variables = variables
        self.mesh = mesh
        self.rules = rules
        self.config = config or ServeConfig()

        model_config = model.config
        self.block_size = resolve_block_size(
            model_config, self.config.max_model_len,
            self.config.block_size, self.config.cache_dtype,
        )
        self.pages_per_request = math.ceil(
            self.config.max_model_len / self.block_size
        )
        num_blocks = self.config.num_blocks
        if num_blocks is None:
            num_blocks = self.config.max_batch * self.pages_per_request
        with self._ctx():
            self._pool_k, self._pool_v = init_paged_pool(
                model_config, num_blocks + 1, self.block_size,
                mesh=self.mesh, rules=self.rules,
                cache_dtype=self.config.cache_dtype,
            )
        self.allocator = BlockAllocator(num_blocks + 1)
        self.scheduler = Scheduler(
            SchedulerConfig(
                max_batch=self.config.max_batch,
                max_model_len=self.config.max_model_len,
                block_size=self.block_size,
                prefill_chunk=self.config.prefill_chunk,
            ),
            self.allocator,
        )
        self._build_programs()
        self._rng = jax.random.key(self.config.seed)
        self._call = 0
        self._t0: float | None = None
        self._step_index = 0
        self.tokens_generated = 0
        self.peak_running = 0

    # ------------------------------------------------------------ programs

    def _ctx(self):
        from llm_training_tpu.infer.engine import mesh_context

        return mesh_context(self.mesh, self.rules)

    def _build_programs(self) -> None:
        model = self.model
        sampling = self.config.sampling
        rope_length = self.config.max_model_len

        def prefill_chunk(variables, ids, seg, pos, pool_k, pool_v,
                          tables, length, last_pos, rng):
            state = PagedDecodeState(
                k=pool_k, v=pool_v, block_tables=tables, lengths=length,
                rope_length=rope_length,
            )
            out = model.apply(
                variables, input_ids=ids, segment_ids=seg,
                position_ids=pos, decode_state=state,
            )
            logits = jax.lax.dynamic_index_in_dim(
                out.logits[0], last_pos, axis=0, keepdims=False
            ).astype(jnp.float32)
            token = sample_tokens(logits[None], rng, sampling)[0]
            state = out.decode_state
            return state.k, state.v, token

        def decode_step(variables, tokens, pool_k, pool_v, tables, lengths, rng):
            state = PagedDecodeState(
                k=pool_k, v=pool_v, block_tables=tables, lengths=lengths,
                rope_length=rope_length,
            )
            out = model.apply(
                variables, input_ids=tokens[:, None],
                position_ids=lengths[:, None], decode_state=state,
            )
            logits = out.logits[:, -1].astype(jnp.float32)
            state = out.decode_state
            return state.k, state.v, sample_tokens(logits, rng, sampling)

        self._prefill_jit = jax.jit(prefill_chunk, donate_argnums=(4, 5))
        self._decode_jit = jax.jit(decode_step, donate_argnums=(2, 3))

    def _next_rng(self):
        self._call += 1
        return jax.random.fold_in(self._rng, self._call)

    def _table_row(self, request: ServeRequest) -> np.ndarray:
        row = np.zeros((self.pages_per_request,), np.int32)
        row[: len(request.blocks)] = request.blocks
        return row

    # -------------------------------------------------------------- intake

    def submit(
        self,
        id: str,
        prompt: Sequence[int],
        max_new_tokens: int = 32,
        priority: int = 0,
    ) -> list[dict]:
        """Queue one request; returns immediately-emittable events (a
        rejection completes synchronously)."""
        if self._t0 is None:
            self._t0 = time.perf_counter()
        request = ServeRequest(
            # coerce every token NOW: a non-int prompt (e.g. a JSON string
            # that slipped through the CLI) must fail at submit — where the
            # caller's error handling lives — not steps later inside the
            # decode loop, taking every in-flight request with it
            id=str(id), prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens), priority=int(priority),
        )
        tracer = get_tracer()
        request.traced = tracer.sample_request()
        tracer.instant(
            "serve", "submit", ts=request.arrival_s, write=request.traced,
            request_id=request.id, prompt_len=len(request.prompt),
            max_new_tokens=request.max_new_tokens, priority=request.priority,
        )
        rejected = self.scheduler.submit(request)
        if rejected is not None:
            return [self._done_event(rejected)]
        return []

    # ---------------------------------------------------------------- step

    def step(self) -> list[dict]:
        """One scheduler round: admissions, at most one prefill chunk, one
        decode step over every decoding row. Returns the streamed events
        ({'type': 'token', ...} per new token, {'type': 'done', ...} per
        completion)."""
        events: list[dict] = []
        tracer = get_tracer()
        self._step_index += 1
        with tracer.measure(
            "serve", "engine_step", step=self._step_index,
            running=len(self.scheduler.running),
            waiting=len(self.scheduler.waiting),
        ), self._ctx():
            before = len(self.scheduler.completed)
            self.scheduler.admit()
            # admit() can terminate a head-of-queue request the pool can
            # NEVER hold (stop_reason='capacity') — that is a completion,
            # and the protocol owes it a done chunk like any other
            for request in self.scheduler.completed[before:]:
                events.append(self._done_event(request))
            self.peak_running = max(self.peak_running, len(self.scheduler.running))
            plan = self.scheduler.next_prefill()
            if plan is not None:
                events.extend(self._run_prefill(*plan))
            rows = self.scheduler.decode_rows()
            if rows:
                events.extend(self._run_decode(rows))
        return events

    def _emit_token(self, request: ServeRequest, token: int, events: list[dict]) -> None:
        now = time.perf_counter()
        request.generated.append(token)
        self.tokens_generated += 1
        if request.first_token_s is None:
            request.first_token_s = now
            get_tracer().instant(
                "serve", "first_token", ts=now, write=request.traced,
                request_id=request.id,
                # the same arrival-anchored value stats()/done events carry:
                # an evicted-then-resumed request's TTFT is measured from
                # its ORIGINAL arrival, never the requeue
                ttft_ms=round(1000.0 * (now - request.arrival_s), 3),
            )
        request.last_token_s = now
        # an evicted-then-resumed request regenerates nothing (its progress
        # rode along in the re-prefill), so every append past `emitted` is
        # genuinely new — emit it
        while request.emitted < len(request.generated):
            events.append({
                "type": "token", "id": request.id,
                "token": request.generated[request.emitted],
            })
            request.emitted += 1
        eos = self.config.eos_token_id
        if eos is not None and token == eos:
            self.scheduler.finish(request, "eos")
            events.append(self._done_event(request))
        elif len(request.generated) >= request.max_new_tokens:
            self.scheduler.finish(request, "max_tokens")
            events.append(self._done_event(request))

    def _run_prefill(self, request: ServeRequest, chunk: list[int], start: int) -> list[dict]:
        events: list[dict] = []
        t_chunk = time.perf_counter()
        width = self.config.prefill_chunk
        ids = np.zeros((1, width), np.int32)
        seg = np.zeros((1, width), np.int32)
        ids[0, : len(chunk)] = chunk
        seg[0, : len(chunk)] = 1
        pos = np.minimum(
            start + np.arange(width), self.config.max_model_len - 1
        ).astype(np.int32)[None, :]
        tables = self._table_row(request)[None, :]
        final = start + len(chunk) >= len(request.prefill_tokens)
        self._pool_k, self._pool_v, token = self._prefill_jit(
            self.variables, jnp.asarray(ids), jnp.asarray(seg),
            jnp.asarray(pos), self._pool_k, self._pool_v,
            jnp.asarray(tables), jnp.asarray([start], jnp.int32),
            jnp.int32(len(chunk) - 1), self._next_rng(),
        )
        request.prefilled += len(chunk)
        request.cache_len += len(chunk)
        if final:
            self._emit_token(request, int(jax.device_get(token)), events)
        now = time.perf_counter()
        get_tracer().span(
            "serve", "prefill_chunk", t_chunk, now, write=request.traced,
            request_id=request.id, start=start, tokens=len(chunk), final=final,
        )
        if final and not request.done:
            # the first new token landed inside the prefill phase; decode
            # (one token per engine step) starts here
            request.advance_phase("decode", now)
        return events

    def _run_decode(self, rows: list[ServeRequest]) -> list[dict]:
        events: list[dict] = []
        # grow each row's blocks for this step's write; under pool pressure
        # this evicts lowest-priority requests (possibly out of `rows`)
        survivors = []
        for request in rows:
            if request.slot is not None and self.scheduler.ensure_decode_blocks(request):
                survivors.append(request)
        # a LATER row's block-pressure eviction can take an EARLIER
        # survivor (lower priority, mid-page so its own check passed) —
        # its slot is gone and its blocks may already belong to the
        # evictor, so it must not decode this step
        survivors = [r for r in survivors if r.slot is not None]
        if not survivors:
            return events
        batch = self.config.max_batch
        tokens = np.zeros((batch,), np.int32)
        lengths = np.zeros((batch,), np.int32)
        tables = np.zeros((batch, self.pages_per_request), np.int32)
        for request in survivors:
            tokens[request.slot] = request.generated[-1]
            lengths[request.slot] = request.cache_len
            tables[request.slot] = self._table_row(request)
        self._pool_k, self._pool_v, out = self._decode_jit(
            self.variables, jnp.asarray(tokens), self._pool_k, self._pool_v,
            jnp.asarray(tables), jnp.asarray(lengths), self._next_rng(),
        )
        host = np.asarray(jax.device_get(out))
        for request in survivors:
            request.cache_len += 1
            self._emit_token(request, int(host[request.slot]), events)
        return events

    def _done_event(self, request: ServeRequest) -> dict:
        event = {
            "type": "done", "id": request.id,
            "stop_reason": request.stop_reason,
            "tokens": list(request.generated),
            "n_tokens": len(request.generated),
            "evictions": request.evictions,
        }
        if request.first_token_s is not None:
            event["ttft_ms"] = round(
                1000.0 * (request.first_token_s - request.arrival_s), 3
            )
        if request.last_token_s is not None and len(request.generated) > 1:
            event["tpot_ms"] = round(
                1000.0 * (request.last_token_s - request.first_token_s)
                / (len(request.generated) - 1), 3,
            )
        get_tracer().instant(
            "serve", "done", write=request.traced, request_id=request.id,
            stop_reason=request.stop_reason, n_tokens=len(request.generated),
            evictions=request.evictions,
            queue_wait_ms=round(1000.0 * request.queue_wait_s, 3),
            **({"ttft_ms": event["ttft_ms"]} if "ttft_ms" in event else {}),
        )
        return event

    # ----------------------------------------------------------------- run

    def run(self, requests: Sequence[dict], max_steps: int = 100_000) -> list[dict]:
        """Submit a closed request set and step until drained. Each request
        dict: {'id', 'prompt', 'max_new_tokens'?, 'priority'?}. Returns all
        events in emission order."""
        events: list[dict] = []
        for request in requests:
            events.extend(self.submit(**request))
        for _ in range(max_steps):
            if self.scheduler.idle:
                break
            events.extend(self.step())
        else:
            raise RuntimeError(f"serve loop not drained after {max_steps} steps")
        return events

    # --------------------------------------------------------------- stats

    def stats(self) -> dict[str, float]:
        """Engine/latency summary, published as `serve/*` gauges (merged
        into telemetry.jsonl by the CLI; `report` renders `== Serving ==`)."""
        from llm_training_tpu.telemetry import get_registry

        completed = [
            r for r in self.scheduler.completed
            if r.stop_reason in ("eos", "max_tokens")
        ]
        ttft = [
            1000.0 * (r.first_token_s - r.arrival_s)
            for r in completed if r.first_token_s is not None
        ]
        tpot = [
            1000.0 * (r.last_token_s - r.first_token_s) / (len(r.generated) - 1)
            for r in completed
            if r.last_token_s is not None and len(r.generated) > 1
        ]
        wall = (time.perf_counter() - self._t0) if self._t0 is not None else 0.0
        n_chips = max(1, jax.device_count())
        tps = self.tokens_generated / wall if wall > 0 else 0.0
        stats = {
            "serve/requests_completed": float(len(completed)),
            "serve/requests_failed": float(
                len(self.scheduler.completed) - len(completed)
            ),
            "serve/requests_evicted": float(self.scheduler.evictions),
            "serve/tokens_generated": float(self.tokens_generated),
            "serve/tokens_per_sec": tps,
            "serve/tokens_per_sec_per_chip": tps / n_chips,
            "serve/peak_running": float(self.peak_running),
            "decode/cache_bytes": float(pool_bytes(self._pool_k, self._pool_v)),
            "decode/cache_blocks_total": float(self.allocator.num_blocks - 1),
            "decode/cache_blocks_in_use": float(self.allocator.blocks_in_use),
            "decode/cache_peak_blocks_in_use": float(self.allocator.peak_in_use),
        }
        if ttft:
            stats["serve/ttft_p50_ms"] = float(np.percentile(ttft, 50))
            stats["serve/ttft_p99_ms"] = float(np.percentile(ttft, 99))
        if tpot:
            stats["serve/tpot_p50_ms"] = float(np.percentile(tpot, 50))
            stats["serve/tpot_p99_ms"] = float(np.percentile(tpot, 99))
        counts = get_tracer().counts()
        stats["trace/events_recorded"] = float(counts["recorded"])
        stats["trace/events_written"] = float(counts["written"])
        stats["trace/requests_sampled"] = float(counts["requests_sampled"])
        registry = get_registry()
        for key, value in stats.items():
            registry.gauge(key).set(value)
        logger.info(
            "serve: %d completed (%d evictions) | %.1f tokens/s (%.1f/chip)",
            len(completed), self.scheduler.evictions, tps, stats["serve/tokens_per_sec_per_chip"],
        )
        return stats
