"""Paged KV-cache pool + host-side block allocator (docs/serving.md).

The pool is the device half: `[num_layers, num_blocks, block_size,
kv_heads, head_dim]` k/v buffers built from the SAME training rule table
`infer/cache.py` uses (kv heads shard over 'tensor'; the block axis stays
replicated — each data-parallel serving replica owns its whole pool).
Physical block 0 is a reserved TRASH block: idle decode slots and padded
chunk positions write there, so a garbage row can never touch a live
request's cache.

The `BlockAllocator` is the host half: a free list handing fixed-size
blocks to requests and taking them back on completion/eviction, publishing
pool occupancy as `decode/cache_blocks_total` / `decode/cache_blocks_in_use`
/ `decode/cache_peak_blocks_in_use` gauges so telemetry.jsonl and `report`
show block pressure (and the serve-smoke gate can assert leak-freedom).

The block size is the paged-decode kernel's tile knob and resolves through
`ops/pallas/tuning.py` (config > PAGED_BLOCK_K env > tuning table > 16)
when the serve config leaves it unset.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

# jax — and everything that drags it in: the infer.cache helpers AND the
# `llm_training_tpu.ops` package (whose __init__ loads every kernel) —
# loads lazily inside the pool constructors so the allocator stays
# importable from jax-free host processes (loadgen / bench parents), the
# package docstring's contract
if TYPE_CHECKING:
    import jax.numpy as jnp
    from jax.sharding import Mesh

# pool layout: [num_layers, num_blocks, block_size, num_kv_heads, head_dim]
POOL_LOGICAL_AXES = ("layers", None, None, "kv_heads", None)

TRASH_BLOCK = 0  # physical block 0 is never allocated


def resolve_block_size(
    model_config, max_model_len: int, block_size: int | None = None,
    cache_dtype: str | None = None,
) -> int:
    """The pool's tokens-per-block, via the tuning layer (kind='paged')."""
    from llm_training_tpu.infer.cache import cache_dims, resolve_cache_dtype
    from llm_training_tpu.ops.pallas.tuning import resolve_paged_block_size

    _, _, head_dim = cache_dims(model_config)
    choice = resolve_paged_block_size(
        max_model_len=max_model_len, head_dim=head_dim,
        dtype=resolve_cache_dtype(model_config, cache_dtype),
        block_size=block_size,
    )
    return choice.block_k


def init_paged_pool(
    model_config,
    num_blocks: int,
    block_size: int,
    mesh: Mesh | None = None,
    rules=None,
    cache_dtype: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fresh all-zeros (k, v) pool, created ALREADY sharded under a mesh
    (kv heads over 'tensor', like the dense cache). Publishes the pool
    footprint as the `decode/cache_bytes` gauge."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from llm_training_tpu.infer.cache import (
        _divisible_spec,
        cache_dims,
        resolve_cache_dtype,
    )

    num_layers, kv_heads, head_dim = cache_dims(model_config)
    dtype = resolve_cache_dtype(model_config, cache_dtype)
    shape = (num_layers, num_blocks, block_size, kv_heads, head_dim)

    def build():
        return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)

    if mesh is None:
        k, v = build()
    else:
        spec = NamedSharding(
            mesh, _divisible_spec(shape, POOL_LOGICAL_AXES, mesh, rules or ())
        )
        k, v = jax.jit(build, out_shardings=(spec, spec))()
    _publish_pool_gauges(k, v, num_blocks)
    return k, v


def pool_bytes(k: jnp.ndarray, v: jnp.ndarray) -> int:
    return sum(leaf.size * leaf.dtype.itemsize for leaf in (k, v))


def _publish_pool_gauges(k, v, num_blocks: int) -> None:
    from llm_training_tpu.telemetry import get_registry

    registry = get_registry()
    registry.gauge("decode/cache_bytes").set(pool_bytes(k, v))
    registry.gauge("decode/cache_blocks_total").set(num_blocks - 1)  # minus trash


class BlockAllocator:
    """Host-side free list over the pool's physical blocks (block 0
    reserved as trash). All-or-nothing `alloc`, idempotence-free `free`
    (double-free is a bug and raises), occupancy gauges on every change."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (1 usable + trash), got {num_blocks}"
            )
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, TRASH_BLOCK, -1))  # pop() -> low ids first
        self._in_use: set[int] = set()
        self.peak_in_use = 0
        self._publish()

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return len(self._in_use)

    def alloc(self, n: int) -> list[int] | None:
        """n blocks, or None (nothing allocated) when fewer are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._in_use.update(blocks)
        self.peak_in_use = max(self.peak_in_use, len(self._in_use))
        self._publish()
        return blocks

    def free(self, blocks: list[int]) -> None:
        for block in blocks:
            if block not in self._in_use:
                raise ValueError(f"free of unallocated block {block}")
            self._in_use.remove(block)
            self._free.append(block)
        self._publish()

    def _publish(self) -> None:
        from llm_training_tpu.telemetry import get_registry

        registry = get_registry()
        registry.gauge("decode/cache_blocks_in_use").set(len(self._in_use))
        registry.gauge("decode/cache_peak_blocks_in_use").set(self.peak_in_use)
