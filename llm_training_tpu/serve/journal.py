"""Request journal: accepted-but-unfinished serving work that survives the
process (docs/serving.md#resilience).

The serving tier's durability contract is *at-least-once execution with
exactly-once termination*: once the engine accepts a request, the client
is owed exactly one terminal chunk — even across a graceful drain (SIGTERM
→ exit 75) or a watchdog SIGABRT that `supervise` turns into a relaunch.
The journal is how the relaunch knows what it owes:

- `accepted` records land when the engine takes a request (id, prompt,
  budget, priority, deadline);
- `progress` records checkpoint the greedy continuation state (generated
  tokens + how many were already streamed) — written on a configurable
  step cadence, on eviction-style folding, and always at drain;
- `done` records retire an id the moment its terminal chunk is emitted.

`replay_journal` folds the log: per id the LAST state wins (dedupe — a
client reusing an id after its predecessor finished starts fresh), ids
with a `done` record are dropped, and what remains is resubmittable
exactly like an eviction requeue — progress folded into the prompt, the
`emitted` watermark carried over so replayed decoding never re-streams a
token the client already has. Greedy decode then makes the continuation
token-identical to the run that was interrupted.

Torn tails (a record half-written when the process died) and malformed
lines are skipped: a journal that survived a SIGKILL must still replay.

This module is **jax-free** (graftlint-enforced, like the scheduler): the
journal is pure host-side bookkeeping and must be readable by supervisors
and tests that never touch a backend.
"""

from __future__ import annotations

import json
import logging
import threading
from pathlib import Path

logger = logging.getLogger(__name__)


class RequestJournal:
    """Append-only jsonl writer for one serve process's request lifetimes.

    Every record is flushed as written: the journal's whole point is being
    readable after an abrupt death, and serve-step cadence is nowhere near
    syscall-bound. Writes are lock-serialized — the serve CLI journals
    deliveries from its stdin reader THREAD (so a line a hard death
    catches between read and submit still replays) while the engine
    journals progress from the step loop."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._file = open(self.path, "a")  # guarded by: _lock
        self._lock = threading.Lock()
        # last progress state written per id, so an unchanged request does
        # not grow the journal every step
        self._written: dict[str, tuple[int, int]] = {}  # guarded by: _lock

    # ------------------------------------------------------------ records

    def delivered(
        self,
        id: str,
        prompt: list[int],
        max_new_tokens: int,
        priority: int = 0,
        deadline_ms: float | None = None,
    ) -> None:
        """Record acceptance from raw protocol fields — the stdin reader's
        entry point, taken BEFORE the request ever reaches the engine so
        the delivered-but-not-yet-submitted window (a queue a SIGABRT
        would vaporize) is covered."""
        record = {
            "event": "accepted",
            "id": str(id),
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "priority": int(priority),
        }
        if deadline_ms is not None:
            record["deadline_ms"] = float(deadline_ms)
        with self._lock:
            self._written[record["id"]] = (-1, -1)  # force the first progress
            self._append(record)

    def accepted(self, request) -> None:
        self.delivered(
            request.id, request.prompt, request.max_new_tokens,
            priority=request.priority,
            deadline_ms=(
                # the absolute perf_counter deadline is meaningless in
                # another process; persist the original relative budget
                # (replay re-anchors at its own arrival)
                round(1000.0 * (request.deadline_s - request.arrival_s), 3)
                if request.deadline_s is not None else None
            ),
        )

    def progress(self, request) -> None:
        """Checkpoint the continuation state. Records are DELTA-encoded
        against the last one written for this id (`generated` within one
        acceptance only ever appends), so a long-lived stream journals
        O(tokens) total instead of O(tokens^2) at the default every-step
        cadence; `replay_journal` re-concatenates."""
        state = (len(request.generated), request.emitted)
        with self._lock:
            prev = self._written.get(request.id)
            if prev == state:
                return
            start = 0 if prev is None or prev[0] < 0 else prev[0]
            self._written[request.id] = state
            # logprobs ride the same delta window as `generated` (the
            # engine appends both together, so they share length); a
            # request without the attribute (router-side bookkeeping)
            # journals tokens only
            logprobs = getattr(request, "logprobs", None)
            record = {
                "event": "progress",
                "id": request.id,
                "generated_from": start,
                "generated": list(request.generated[start:]),
                "emitted": request.emitted,
            }
            if logprobs is not None:
                record["logprobs"] = [
                    None if lp is None else round(float(lp), 6)
                    for lp in logprobs[start:]
                ]
            self._append(record)

    def finished(self, request) -> None:
        with self._lock:
            self._written.pop(request.id, None)
            self._append({
                "event": "done",
                "id": request.id,
                "stop_reason": request.stop_reason,
            })

    def note(self, record: dict) -> None:
        """Append an auxiliary event record (e.g. the router's assignment /
        hedge / scale markers). `replay_journal` ignores unknown event
        types, so notes ride the same durable stream without affecting the
        fold — they exist for post-mortem forensics and tests."""
        with self._lock:
            self._append(dict(record))

    def _append(self, record: dict) -> None:
        """Write one record (caller holds the lock)."""
        try:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        except (OSError, ValueError):
            logger.exception("request journal write failed (record dropped)")

    def close(self) -> None:
        with self._lock:
            try:
                self._file.close()
            except OSError:
                logger.exception("request journal close failed")


def replay_journal(path: str | Path) -> list[dict]:
    """Fold a journal into the resubmittable remainder: one entry per
    accepted-but-unfinished id ({id, prompt, generated, emitted,
    max_new_tokens, priority, deadline_ms?}), in original acceptance
    order. Duplicate ids dedupe to the LAST acceptance; ids with a `done`
    after their last acceptance are dropped; torn/malformed lines are
    skipped."""
    path = Path(path)
    try:
        text = path.read_text()
    except OSError:
        return []
    entries: dict[str, dict] = {}
    order: list[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail from an abrupt death
        if not isinstance(record, dict):
            continue
        rid = record.get("id")
        event = record.get("event")
        if not isinstance(rid, str):
            continue
        if event == "accepted":
            try:
                entry = {
                    "id": rid,
                    "prompt": [int(t) for t in record["prompt"]],
                    "generated": [],
                    "logprobs": [],
                    "emitted": 0,
                    "max_new_tokens": int(record["max_new_tokens"]),
                    "priority": int(record.get("priority", 0)),
                }
            except (KeyError, TypeError, ValueError):
                continue
            if record.get("deadline_ms") is not None:
                entry["deadline_ms"] = float(record["deadline_ms"])
            if rid in entries:
                order.remove(rid)  # client reused the id: last wins
            entries[rid] = entry
            order.append(rid)
        elif event == "progress" and rid in entries:
            try:
                start = int(record.get("generated_from", 0))
                tokens = [int(t) for t in record["generated"]]
                current = entries[rid]["generated"]
                if start > len(current):
                    # a dropped record left a gap: keep the shorter known
                    # prefix — replay may re-stream, it must never invent
                    continue
                entries[rid]["generated"] = current[:start] + tokens
                # fold the parallel logprob delta; a record without one
                # (pre-logprob journal) pads with None so the entry's
                # logprobs stay aligned with generated
                raw_lps = record.get("logprobs")
                if raw_lps is None:
                    lps = [None] * len(tokens)
                else:
                    lps = [
                        None if lp is None else float(lp) for lp in raw_lps
                    ][: len(tokens)]
                    lps += [None] * (len(tokens) - len(lps))
                entries[rid]["logprobs"] = (
                    entries[rid]["logprobs"][:start] + lps
                )
                entries[rid]["emitted"] = int(record["emitted"])
            except (KeyError, TypeError, ValueError):
                continue
        elif event == "done" and rid in entries:
            del entries[rid]
            order.remove(rid)
    return [entries[rid] for rid in order]
