"""Serving subsystem (docs/serving.md): continuous batching over a paged
KV cache.

The serving tier ROADMAP item 2 names: a block-pool KV cache with
per-request block tables (`paged_cache.py` + `models/base.PagedDecodeState`),
a ragged paged-decode attention path (`ops/paged_attention.py`, Pallas
kernel in `ops/pallas/paged_attention.py`), a request scheduler with
admission / chunked-prefill interleaving / eviction (`scheduler.py`), and
the jitted continuous-batching engine (`engine.py`) — behind the streaming
`serve` CLI subcommand and `scripts/serve_loadgen.py`. The resilience
layer (docs/serving.md#resilience) adds deadlines + load shedding in the
scheduler, the `RequestJournal` durability log (`journal.py`), hot weight
reload, and graceful drain / supervised replay in the CLI. The fleet
resilience tier (docs/serving.md#router) adds `router.py` + the `route`
CLI: health-aware routing over N serve replicas with failover replay,
hedged retries, and SLO-driven elasticity.

Scheduler, allocator, and journal import eagerly (host-only, no jax); the
engine is lazy, mirroring `llm_training_tpu.infer`.
"""

from llm_training_tpu.serve.journal import RequestJournal, replay_journal
from llm_training_tpu.serve.paged_cache import BlockAllocator, init_paged_pool
from llm_training_tpu.serve.router import (
    ReplicaHandle,
    RoutedRequest,
    Router,
    fold_replica_journals,
    namespaced_id,
)
from llm_training_tpu.serve.scheduler import (
    Scheduler,
    SchedulerConfig,
    ServeRequest,
)

__all__ = [
    "BlockAllocator",
    "ReplicaHandle",
    "RequestJournal",
    "RoutedRequest",
    "Router",
    "Scheduler",
    "SchedulerConfig",
    "ServeConfig",
    "ServeRequest",
    "ServingEngine",
    "fold_replica_journals",
    "init_paged_pool",
    "namespaced_id",
    "replay_journal",
]

_LAZY = {
    "ServeConfig": "llm_training_tpu.serve.engine",
    "ServingEngine": "llm_training_tpu.serve.engine",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
