"""Health-aware router tier over N ``serve`` replicas.

The router speaks the same JSONL request protocol as ``serve`` on its own
stdin/stdout and fans requests out over a fleet of supervised ``serve``
children.  It consumes the observability substrate built in PRs 14/16/17
instead of inventing its own:

* **Routing / eviction** — least-loaded admission using the
  :class:`~llm_training_tpu.telemetry.fleet.FleetAggregator`'s per-replica
  queue/TTFT series; replicas flip out of rotation the moment their
  ``/healthz`` goes red or their discovery card goes stale (red flips before
  the watchdog SIGABRT, so the router reacts *before* the crash).
* **Failover replay** — exactly-once terminals across replica death.  Every
  request→replica assignment is journaled; when a replica dies mid-stream its
  in-flight requests are replayed (prompt + ``emitted`` watermark folded in,
  per the ``submit_resumed`` semantics) onto a live replica without
  re-streaming delivered tokens.  Request ids are namespaced per replica so
  ``replay_journal``'s fold never merges two replicas' ``req-0``.
* **Hedged retries** — when a request's projected TTFT on its assigned
  replica breaches its deadline and another replica has free slots, the
  request is re-enqueued on the second replica; first token wins and the
  loser is suppressed (never two terminals).
* **SLO-driven elasticity** — sustained TTFT burn (PR 14 SLO monitor) spawns
  another ``serve`` child; sustained idleness drains and retires one.  Every
  scale event is a ``cat="router"`` trace instant plus ``router/*`` gauges.

Chaos hooks ``LLMT_CHAOS_ROUTER_KILL_REPLICA`` (SIGKILL the replica serving
the Nth forwarded token) and ``LLMT_CHAOS_ROUTER_BLACKHOLE`` (accept the Nth
assignment but never submit it, so only hedging can finish it) are the fault
injectors for the smoke gate.

This module is jax-free (graftlint ``JAX_FREE_CONTRACTS``) and the
:class:`Router` is thread-shared (racecheck ``THREAD_SHARED_CONTRACTS``,
``LOCK_ORDER`` slot "router" — above "fleet"/"journal").
"""

from __future__ import annotations

import json
import logging
import os
import queue
import shutil
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from llm_training_tpu.serve.journal import RequestJournal, replay_journal

logger = logging.getLogger(__name__)

# Stop reasons that terminate a stream, mirrored from the serve engine.
TERMINAL_REASONS = ("eos", "max_tokens", "deadline", "overloaded", "rejected", "capacity")
# Stop reasons that count as completed (vs failed) for SLO purposes.
COMPLETED_REASONS = ("eos", "max_tokens")

ROUTER_JOURNAL = "router-journal.jsonl"
ROUTER_JOURNAL_REPLAYING = "router-journal.replaying.jsonl"


def namespaced_id(rid: str, client_id: str) -> str:
    """Namespace a client request id under a replica id.

    Two replicas can both be carrying a ``req-0`` (e.g. a replay of replica
    A's ``req-0`` onto replica B while B already had its own); folding their
    journals without namespacing would merge them.  ``::`` never appears in
    loadgen/client ids.
    """
    return f"{rid}::{client_id}"


def split_namespaced_id(nsid: str) -> tuple[str, str]:
    """Inverse of :func:`namespaced_id`. Returns ``(rid, client_id)``."""
    rid, _, client_id = nsid.partition("::")
    return rid, client_id


def fold_replica_journals(journals: dict[str, Path | str]) -> list[dict]:
    """Fold several replicas' serve journals into one namespaced entry list.

    Each journal is folded *independently* via
    :func:`~llm_training_tpu.serve.journal.replay_journal` (last acceptance
    wins per id, done drops the id, torn tails are skipped) and only then are
    the surviving entries merged, with ids namespaced per replica.  Entries
    gain ``source_replica`` and ``client_id`` annotations so the router can
    map them back to client streams.
    """
    folded: list[dict] = []
    for rid, path in journals.items():
        for entry in replay_journal(str(path)):
            out = dict(entry)
            out["client_id"] = entry["id"]
            out["id"] = namespaced_id(rid, entry["id"])
            out["source_replica"] = rid
            folded.append(out)
    return folded


class RoutedRequest:
    """Per-client-request state held by the router.

    Duck-typed for :class:`RequestJournal` (``id``/``prompt``/``generated``/
    ``emitted``/``stop_reason``/``max_new_tokens``/``priority``/
    ``deadline_ms``).  ``generated`` holds every token *forwarded to the
    client* and ``emitted == len(generated)`` always (the router never buffers
    between generated and emitted; per-leg caches live in ``legs``).

    A *leg* is one submission of this request to one replica (the primary
    assignment, a hedge, or a failover replay).  ``legs`` maps replica id →
    ``{"base": int, "tokens": list, "done": dict | None, "open": bool}``
    where ``base`` is ``emitted`` at the moment the leg was submitted and
    ``tokens`` are all tokens received from that leg (absolute position of
    ``tokens[i]`` is ``base + i``; greedy decode makes overlapping legs agree
    position-for-position).
    """

    __slots__ = (
        "id",
        "prompt",
        "max_new_tokens",
        "priority",
        "deadline_ms",
        "arrival_s",
        "generated",
        "emitted",
        "stop_reason",
        "winner",
        "primary",
        "replays",
        "legs",
        "first_token_s",
        "generation",
    )

    def __init__(
        self,
        id: str,
        prompt: list[int],
        max_new_tokens: int,
        priority: int = 0,
        deadline_ms: float | None = None,
        arrival_s: float = 0.0,
    ) -> None:
        self.id = id
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.deadline_ms = deadline_ms
        self.arrival_s = arrival_s
        self.generated: list[int] = []
        self.emitted = 0
        self.stop_reason: str | None = None
        self.winner: str | None = None
        self.primary: str | None = None
        self.replays = 0
        self.legs: dict[str, dict] = {}
        self.first_token_s: float | None = None
        self.generation = 0


class ReplicaHandle:
    """One supervised ``serve`` child plus its stdout reader thread.

    Every attribute is read-only after ``__init__`` (racecheck: the reader
    thread only *reads* ``proc``/``events``; all mutation flows through the
    thread-safe ``queue.Queue``).  The reader forwards each JSON line from
    the child's stdout as ``("chunk", rid, obj)`` onto the shared event
    queue, skipping non-JSON lines (serve logs to stdout), and posts
    ``("eof", rid, None)`` exactly once when the pipe closes.
    """

    def __init__(
        self,
        rid: str,
        proc: subprocess.Popen,
        events: "queue.Queue[tuple[str, str, object]]",
        run_dir: Path,
        port: int,
        started_s: float,
    ) -> None:
        self.rid = rid
        self.proc = proc
        self.events = events
        self.run_dir = Path(run_dir)
        self.journal_path = self.run_dir / "serve-journal.jsonl"
        self.port = port
        self.started_s = started_s
        self._thread = threading.Thread(
            target=self._read_loop, name=f"router-read-{rid}", daemon=True
        )
        self._thread.start()

    def _read_loop(self) -> None:
        stdout = self.proc.stdout
        if stdout is not None:
            for line in stdout:
                line = line.strip()
                if not line or not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue  # serve logs to stdout; skip non-protocol lines
                self.events.put(("chunk", self.rid, obj))
        self.events.put(("eof", self.rid, None))

    def submit(self, record: dict) -> bool:
        """Write one JSONL record to the child's stdin. Main loop only."""
        stdin = self.proc.stdin
        if stdin is None:
            return False
        try:
            stdin.write(json.dumps(record) + "\n")
            stdin.flush()
            return True
        except (BrokenPipeError, OSError, ValueError):
            return False

    def close_stdin(self) -> None:
        stdin = self.proc.stdin
        if stdin is not None:
            try:
                stdin.close()
            except OSError:
                pass

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        if self.alive():
            try:
                self.proc.kill()
            except OSError:
                pass

    def join_reader(self, timeout: float = 5.0) -> None:
        self._thread.join(timeout=timeout)


class Router:
    """Thread-shared routing core: assignment, legs, failover, elasticity.

    Shared between the main loop and the exporter's ``extra_fn`` /
    ``status_fn`` callbacks (HTTP server thread), hence every post-init
    mutable attribute is guarded by ``_lock``.  Journal appends happen under
    the router lock (LOCK_ORDER: router before journal); chaos hooks and all
    stdout printing happen strictly *outside* it, in the runtime.
    """

    def __init__(
        self,
        journal: RequestJournal | None = None,
        clock=time.monotonic,
        hedge_ttft_ms: float = 0.0,
        min_replicas: int = 1,
        max_replicas: int = 1,
        scale_cooldown_s: float = 30.0,
        idle_retire_s: float = 0.0,
    ) -> None:
        self.journal = journal
        self.clock = clock
        self.hedge_ttft_ms = float(hedge_ttft_ms)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_cooldown_s = float(scale_cooldown_s)
        self.idle_retire_s = float(idle_retire_s)
        self._lock = threading.Lock()
        self._replicas: dict[str, ReplicaHandle] = {}  # guarded by: _lock
        self._requests: dict[str, RoutedRequest] = {}  # guarded by: _lock
        self._finished: set[str] = set()  # guarded by: _lock
        self._pending: list[RoutedRequest] = []  # guarded by: _lock
        self._health: dict[str, dict] = {}  # guarded by: _lock
        self._evicted: set[str] = set()  # guarded by: _lock
        self._retiring: set[str] = set()  # guarded by: _lock
        self._assigned_since_scrape: dict[str, int] = {}  # guarded by: _lock
        self._counters: dict[str, int] = {}  # guarded by: _lock
        self._next_ordinal = 0  # guarded by: _lock
        self._target = int(min_replicas)  # guarded by: _lock
        self._last_scale_s = -1e18  # guarded by: _lock
        self._last_breaches = 0  # guarded by: _lock
        self._last_traffic_s = 0.0  # guarded by: _lock
        self._peak_inflight = 0  # guarded by: _lock
        self._assign_seq = 0  # guarded by: _lock

    # -- internal helpers (callers hold _lock) ------------------------------

    # guarded by: _lock
    def _bump(self, name: str, delta: int = 1) -> None:
        self._counters[name] = self._counters.get(name, 0) + delta

    # guarded by: _lock
    def _note(self, record: dict) -> None:
        if self.journal is not None:
            self.journal.note(record)

    # guarded by: _lock
    def _inflight(self) -> int:
        return sum(1 for r in self._requests.values() if r.stop_reason is None)

    # guarded by: _lock
    def _flush_winner(self, req: RoutedRequest, rid: str) -> list[dict]:
        """Forward any cached tokens from the winning leg past the watermark."""
        leg = req.legs[rid]
        events: list[dict] = []
        while req.emitted < leg["base"] + len(leg["tokens"]):
            tok = leg["tokens"][req.emitted - leg["base"]]
            req.generated.append(tok)
            req.emitted += 1
            events.append(
                {
                    "type": "token",
                    "id": req.id,
                    "token": tok,
                    "generation": req.generation,
                }
            )
        if events and self.journal is not None:
            self.journal.progress(req)
        return events

    # guarded by: _lock
    def _finish(self, req: RoutedRequest, rid: str, done: dict) -> dict:
        """Mark terminal, rewrite the done chunk to router coordinates."""
        req.stop_reason = str(done.get("stop_reason", "eos"))
        out = dict(done)
        out["type"] = "done"
        out["id"] = req.id
        out["tokens"] = list(req.generated)
        out["n_tokens"] = len(req.generated)
        out["replica"] = rid
        out["replays"] = req.replays
        if req.first_token_s is not None:
            out["ttft_ms"] = (req.first_token_s - req.arrival_s) * 1000.0
        if self.journal is not None:
            self.journal.finished(req)
        if req.stop_reason in COMPLETED_REASONS:
            self._bump("requests_completed")
        else:
            self._bump("requests_failed")
        self._finished.add(req.id)
        del self._requests[req.id]
        return out

    # -- replica lifecycle --------------------------------------------------

    def next_ordinal(self) -> int:
        """Ordinals are never reused within a router incarnation."""
        with self._lock:
            n = self._next_ordinal
            self._next_ordinal += 1
            return n

    def register_replica(self, handle: ReplicaHandle) -> None:
        with self._lock:
            self._replicas[handle.rid] = handle
            self._assigned_since_scrape[handle.rid] = 0
            self._note({"event": "replica_up", "replica": handle.rid, "port": handle.port})

    def replica(self, rid: str) -> ReplicaHandle | None:
        with self._lock:
            return self._replicas.get(rid)

    def replicas(self) -> list[ReplicaHandle]:
        with self._lock:
            return list(self._replicas.values())

    def mark_retiring(self, rid: str) -> None:
        with self._lock:
            self._retiring.add(rid)
            self._note({"event": "replica_retiring", "replica": rid})

    def retire_replica(self, rid: str) -> None:
        """Clean removal (rc==0 after drain): no in-flight legs expected."""
        with self._lock:
            self._replicas.pop(rid, None)
            self._retiring.discard(rid)
            self._evicted.discard(rid)
            self._health.pop(rid, None)
            self._assigned_since_scrape.pop(rid, None)
            self._note({"event": "replica_retired", "replica": rid})

    def fail_replica(self, rid: str, folded: list[dict] | None = None) -> dict:
        """Replica died. Adopt hedge legs or journal extensions; orphan the rest.

        ``folded`` is the dead replica's journal folded via
        :func:`fold_replica_journals` (already namespaced).  Returns
        ``{"events": [...], "orphans": [RoutedRequest, ...]}`` — events are
        recovered token/done chunks to print, orphans need resubmission.
        """
        by_client: dict[str, dict] = {}
        for entry in folded or []:
            by_client[entry.get("client_id", entry["id"])] = entry
        events: list[dict] = []
        orphans: list[RoutedRequest] = []
        with self._lock:
            self._replicas.pop(rid, None)
            self._retiring.discard(rid)
            self._evicted.discard(rid)
            self._health.pop(rid, None)
            self._assigned_since_scrape.pop(rid, None)
            self._bump("failovers")
            self._note({"event": "replica_failed", "replica": rid})
            for req in list(self._requests.values()):
                leg = req.legs.get(rid)
                if leg is None:
                    continue
                leg["open"] = False
                if req.stop_reason is not None:
                    continue
                # Another leg may still be carrying this request.
                others = [
                    (orid, oleg)
                    for orid, oleg in req.legs.items()
                    if orid != rid and (oleg["open"] or oleg["done"] is not None)
                ]
                if req.winner is not None and req.winner != rid and others:
                    continue  # the winner is elsewhere and still covered
                adopted = False
                if others:
                    # Prefer a finished leg, then maximum token coverage.
                    others.sort(
                        key=lambda kv: (
                            kv[1]["done"] is not None,
                            kv[1]["base"] + len(kv[1]["tokens"]),
                        ),
                        reverse=True,
                    )
                    orid, oleg = others[0]
                    req.winner = orid
                    events.extend(self._flush_winner(req, orid))
                    if oleg["done"] is not None:
                        events.append(self._finish(req, orid, oleg["done"]))
                    adopted = True
                    self._bump("leg_adoptions")
                if adopted:
                    continue
                # Orphaned: fold in the dead replica's journal watermark if it
                # prefix-extends what the client has already seen.
                req.winner = None
                req.primary = None  # replay's next assignment is a fresh primary
                entry = by_client.get(req.id)
                if entry is not None:
                    jgen = list(entry.get("generated", ()))
                    if (
                        len(jgen) > len(req.generated)
                        and jgen[: len(req.generated)] == req.generated
                    ):
                        for tok in jgen[len(req.generated) :]:
                            req.generated.append(tok)
                            req.emitted += 1
                            events.append(
                                {
                                    "type": "token",
                                    "id": req.id,
                                    "token": tok,
                                    "generation": req.generation,
                                }
                            )
                            self._bump("recovered_tokens")
                        if self.journal is not None:
                            self.journal.progress(req)
                orphans.append(req)
        return {"events": events, "orphans": orphans}

    # -- health / fleet -----------------------------------------------------

    def update_fleet(self, snapshot: dict) -> list[str]:
        """Fold an aggregator snapshot into health state. Returns new evictions."""
        entries = snapshot.get("replicas", {}) or {}
        by_port: dict[int, dict] = {}
        for entry in entries.values():
            try:
                by_port[int(entry.get("port", -1))] = entry
            except (TypeError, ValueError):
                continue
        newly_evicted: list[str] = []
        with self._lock:
            for rid, handle in self._replicas.items():
                entry = by_port.get(handle.port)
                if entry is None:
                    continue
                metrics = entry.get("metrics") or {}
                bad = bool(entry.get("stale")) or not entry.get("healthy", True)
                self._health[rid] = {
                    "healthy": not bad,
                    "stale": bool(entry.get("stale")),
                    "queue_depth": float(metrics.get("llmt_serve_queue_depth", 0.0)),
                    "running": float(metrics.get("llmt_serve_running", 0.0)),
                    "ttft_p99_ms": float(metrics.get("llmt_serve_ttft_p99_ms", 0.0)),
                }
                self._assigned_since_scrape[rid] = 0
                if bad and rid not in self._evicted:
                    self._evicted.add(rid)
                    self._bump("evictions")
                    self._note({"event": "replica_evicted", "replica": rid})
                    newly_evicted.append(rid)
                elif not bad and rid in self._evicted:
                    self._evicted.discard(rid)
                    self._note({"event": "replica_restored", "replica": rid})
        return newly_evicted

    # guarded by: _lock
    def _load(self, rid: str) -> float:
        health = self._health.get(rid, {})
        return (
            float(health.get("queue_depth", 0.0))
            + float(health.get("running", 0.0))
            + float(self._assigned_since_scrape.get(rid, 0))
        )

    # -- admission ----------------------------------------------------------

    def assign(self, req: RoutedRequest, exclude: tuple[str, ...] = ()) -> tuple[str, int] | None:
        """Least-loaded assignment; opens a leg. Returns (rid, assign ordinal)."""
        with self._lock:
            candidates = [
                rid
                for rid in self._replicas
                if rid not in self._evicted
                and rid not in self._retiring
                and rid not in exclude
                and rid not in req.legs
            ]
            if not candidates:
                return None
            rid = min(candidates, key=self._load)
            self._assigned_since_scrape[rid] = self._assigned_since_scrape.get(rid, 0) + 1
            req.legs[rid] = {"base": req.emitted, "tokens": [], "done": None, "open": True}
            if req.primary is None:
                req.primary = rid
            if req.id not in self._requests:
                self._requests[req.id] = req
                self._bump("requests_total")
                inflight = self._inflight()
                if inflight > self._peak_inflight:
                    self._peak_inflight = inflight
            self._assign_seq += 1
            seq = self._assign_seq
            self._last_traffic_s = self.clock()
            self._note(
                {
                    "event": "assigned",
                    "id": req.id,
                    "replica": rid,
                    "emitted": req.emitted,
                    "seq": seq,
                }
            )
            return rid, seq

    def park(self, req: RoutedRequest) -> None:
        with self._lock:
            if req.id not in self._requests:
                self._requests[req.id] = req
                self._bump("requests_total")
            self._pending.append(req)

    def take_pending(self) -> list[RoutedRequest]:
        with self._lock:
            pending, self._pending = self._pending, []
            return pending

    def intake(self, record: dict) -> RoutedRequest | None:
        """Build a RoutedRequest from a client JSONL record; dedupe terminals."""
        rid = str(record.get("id", ""))
        with self._lock:
            if rid in self._finished or rid in self._requests:
                self._bump("duplicate_requests")
                return None
        req = RoutedRequest(
            id=rid,
            prompt=record.get("prompt", []),
            max_new_tokens=int(record.get("max_new_tokens", 32)),
            priority=int(record.get("priority", 0)),
            deadline_ms=record.get("deadline_ms"),
            arrival_s=self.clock(),
        )
        if self.journal is not None:
            self.journal.delivered(
                req.id,
                req.prompt,
                req.max_new_tokens,
                priority=req.priority,
                deadline_ms=req.deadline_ms,
            )
        return req

    def resume(self, entry: dict) -> RoutedRequest:
        """Rebuild a RoutedRequest from a folded router-journal entry."""
        req = RoutedRequest(
            id=entry["id"],
            prompt=entry.get("prompt", []),
            max_new_tokens=int(entry.get("max_new_tokens", 32)),
            priority=int(entry.get("priority", 0)),
            deadline_ms=entry.get("deadline_ms"),
            arrival_s=self.clock(),
        )
        req.generated = list(entry.get("generated", ()))
        req.emitted = len(req.generated)
        req.replays = 1
        with self._lock:
            self._requests[req.id] = req
            self._bump("requests_total")
            self._bump("resumed")
        if self.journal is not None:
            self.journal.delivered(
                req.id,
                req.prompt,
                req.max_new_tokens,
                priority=req.priority,
                deadline_ms=req.deadline_ms,
            )
            with self._lock:
                self.journal.progress(req)
        return req

    # -- stream events ------------------------------------------------------

    def record_token(self, rid: str, ev: dict) -> list[dict]:
        """Fold a token chunk from replica ``rid``. Returns events to print."""
        client_id = ev.get("client_id") or split_namespaced_id(str(ev.get("id", "")))[1]
        with self._lock:
            req = self._requests.get(client_id)
            if req is None or req.stop_reason is not None:
                self._bump("suppressed_chunks")
                return []
            leg = req.legs.get(rid)
            if leg is None or not leg["open"]:
                # unknown leg, or one fail_replica already closed — the
                # journal fold is the authority for a dead replica's tail
                self._bump("suppressed_chunks")
                return []
            leg["tokens"].append(ev.get("token"))
            req.generation = max(req.generation, int(ev.get("generation", 0)))
            if req.winner is None and leg["base"] + len(leg["tokens"]) > req.emitted:
                req.winner = rid
                if leg.get("hedge"):
                    self._bump("hedge_wins")
            if rid != req.winner:
                self._bump("suppressed_chunks")
                return []
            if req.first_token_s is None:
                req.first_token_s = self.clock()
            self._last_traffic_s = self.clock()
            return self._flush_winner(req, rid)

    def record_done(self, rid: str, ev: dict) -> list[dict]:
        """Fold a done chunk. At most one terminal per client id, ever."""
        client_id = ev.get("client_id") or split_namespaced_id(str(ev.get("id", "")))[1]
        with self._lock:
            if client_id in self._finished:
                self._bump("duplicate_terminals_suppressed")
                return []
            req = self._requests.get(client_id)
            if req is None or req.stop_reason is not None:
                self._bump("duplicate_terminals_suppressed")
                return []
            leg = req.legs.get(rid)
            if leg is None or not leg["open"]:
                # a done from a leg fail_replica closed must not finish an
                # orphan the runtime is about to resubmit — one terminal,
                # one authority
                self._bump("duplicate_terminals_suppressed")
                return []
            leg["done"] = ev
            leg["open"] = False
            if req.winner is not None and req.winner != rid:
                self._bump("suppressed_chunks")
                return []
            req.winner = rid
            events = self._flush_winner(req, rid)
            if req.first_token_s is None and req.generated:
                req.first_token_s = self.clock()
            events.append(self._finish(req, rid, ev))
            self._last_traffic_s = self.clock()
            return events

    def synthesize_done(self, req: RoutedRequest, stop_reason: str) -> list[dict]:
        """Terminal produced by the router itself (e.g. replay budget spent)."""
        with self._lock:
            if req.id in self._finished or req.id not in self._requests:
                self._bump("duplicate_terminals_suppressed")
                return []
            done = {
                "type": "done",
                "stop_reason": stop_reason,
                "generation": req.generation,
            }
            return [self._finish(req, "router", done)]

    # -- hedging ------------------------------------------------------------

    def maybe_hedge(self, now: float) -> list[tuple[RoutedRequest, str]]:
        """Open hedge legs for requests whose projected TTFT breaches budget.

        Returns ``[(req, hedge_rid), ...]``; the runtime submits the legs
        (chaos + stdin writes stay outside the router lock).
        """
        hedged: list[tuple[RoutedRequest, str]] = []
        with self._lock:
            for req in self._requests.values():
                if req.stop_reason is not None or req.first_token_s is not None:
                    continue
                open_legs = [r for r, leg in req.legs.items() if leg["open"]]
                if len(open_legs) != 1:
                    continue
                budget_ms = req.deadline_ms if req.deadline_ms else self.hedge_ttft_ms
                if not budget_ms or budget_ms <= 0:
                    continue
                elapsed_ms = (now - req.arrival_s) * 1000.0
                primary = open_legs[0]
                projected = max(
                    elapsed_ms,
                    float(self._health.get(primary, {}).get("ttft_p99_ms", 0.0)),
                )
                if projected <= budget_ms:
                    continue
                candidates = [
                    rid
                    for rid in self._replicas
                    if rid not in self._evicted
                    and rid not in self._retiring
                    and rid not in req.legs
                    and float(self._health.get(rid, {}).get("queue_depth", 1.0)) == 0.0
                ]
                if not candidates:
                    continue
                rid = min(candidates, key=self._load)
                self._assigned_since_scrape[rid] = self._assigned_since_scrape.get(rid, 0) + 1
                req.legs[rid] = {
                    "base": req.emitted,
                    "tokens": [],
                    "done": None,
                    "open": True,
                    "hedge": True,
                }
                self._bump("hedges")
                self._note(
                    {"event": "hedged", "id": req.id, "replica": rid, "emitted": req.emitted}
                )
                hedged.append((req, rid))
        return hedged

    # -- elasticity ---------------------------------------------------------

    def scale_decision(self, now: float, breaches: int) -> tuple[str, str | None] | None:
        """SLO-burn scale-out / idle scale-in. Returns ("out", None),
        ("in", rid) or None."""
        with self._lock:
            if now - self._last_scale_s < self.scale_cooldown_s:
                return None
            live = len(self._replicas) - len(self._retiring)
            if breaches > self._last_breaches and live < self.max_replicas:
                self._last_breaches = breaches
                self._last_scale_s = now
                self._target = live + 1
                self._bump("scale_out_total")
                return ("out", None)
            self._last_breaches = breaches
            if (
                self.idle_retire_s > 0
                and live > self.min_replicas
                and self._inflight() == 0
                and not self._pending
                and now - self._last_traffic_s >= self.idle_retire_s
            ):
                candidates = [
                    rid for rid in self._replicas if rid not in self._retiring
                ]
                if not candidates:
                    return None
                rid = max(candidates)  # retire the youngest ordinal
                self._retiring.add(rid)
                self._last_scale_s = now
                self._target = live - 1
                self._bump("scale_in_total")
                self._note({"event": "replica_retiring", "replica": rid})
                return ("in", rid)
            return None

    def set_target(self, target: int) -> None:
        with self._lock:
            self._target = int(target)

    def target(self) -> int:
        with self._lock:
            return self._target

    def bump(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._bump(name, delta)

    def note(self, record: dict) -> None:
        with self._lock:
            self._note(record)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight()

    def request_ids_inflight(self) -> list[str]:
        with self._lock:
            return [r.id for r in self._requests.values() if r.stop_reason is None]

    # -- observability ------------------------------------------------------

    def live_stats(self) -> dict:
        """``router/*`` gauges for the exporter's ``extra_fn``."""
        with self._lock:
            stats = {
                "router/replicas": float(len(self._replicas)),
                "router/replicas_target": float(self._target),
                "router/queue_depth": float(len(self._pending)),
                "router/inflight": float(self._inflight()),
                "router/peak_inflight": float(self._peak_inflight),
                "router/evicted": float(len(self._evicted)),
            }
            for name in (
                "requests_total",
                "requests_completed",
                "requests_failed",
                "duplicate_requests",
                "replays",
                "recovered_tokens",
                "hedges",
                "hedge_wins",
                "duplicate_terminals_suppressed",
                "suppressed_chunks",
                "failovers",
                "evictions",
                "leg_adoptions",
                "scale_out_total",
                "scale_in_total",
                "blackholed",
                "resumed",
            ):
                stats[f"router/{name}"] = float(self._counters.get(name, 0))
            return stats

    def stats(self) -> dict:
        stats = {k.replace("router/", "", 1): v for k, v in self.live_stats().items()}
        return stats


# --------------------------------------------------------------------------
# Runtime: the `route` CLI subcommand.  Everything below runs on the main
# thread (plus the stdin reader and per-replica stdout readers, which only
# touch thread-safe queues); chaos hooks and stdout printing live here,
# strictly outside the Router lock.
# --------------------------------------------------------------------------

_EOF = object()


def _publish_router_telemetry(run_dir: Path, stats: dict) -> None:
    """Jax-free clone of the CLI's run-telemetry publish: overlay router
    gauges onto the last telemetry.jsonl record so `report` sees them."""
    path = Path(run_dir) / "telemetry.jsonl"
    record: dict = {}
    if path.exists():
        try:
            with path.open("r", encoding="utf-8") as handle:
                for line in handle:
                    line = line.strip()
                    if line:
                        try:
                            record = json.loads(line)
                        except json.JSONDecodeError:
                            continue
        except OSError:
            record = {}
    record.setdefault("step", 0)
    for key, value in stats.items():
        if isinstance(value, (int, float)):
            record[f"router/{key}"] = float(value)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("a", encoding="utf-8") as handle:
        handle.write(json.dumps(record) + "\n")


def _clean_replica_root(child_run_dir: Path) -> None:
    """The router is the sole replay authority: a respawned replica must
    never find a stale serve journal and self-replay (that would duplicate
    the router's own failover replay)."""
    for name in ("serve-journal.jsonl", "serve-journal.replaying.jsonl"):
        try:
            (child_run_dir / name).unlink()
        except OSError:
            pass


def _seed_checkpoints(seed_run_dir: Path | None, child_run_dir: Path) -> None:
    if seed_run_dir is None:
        return
    src = Path(seed_run_dir) / "checkpoints"
    dst = child_run_dir / "checkpoints"
    if src.is_dir() and not dst.exists():
        try:
            shutil.copytree(src, dst)
        except OSError:
            logger.warning("could not seed checkpoints into %s", dst)


def _provision_replica(
    router: Router,
    args,
    overrides: list[str],
    fleet_dir: Path,
    events: "queue.Queue[tuple[str, str, object]]",
) -> ReplicaHandle | None:
    """Spawn one `serve` child with an isolated run root + exporter port."""
    from llm_training_tpu.cli.config import load_config
    from llm_training_tpu.cli.main import _jsonl_run_dir_jaxfree
    from llm_training_tpu.telemetry.exporter import find_free_port

    ordinal = router.next_ordinal()
    rid = f"r{ordinal}"
    root = Path(args.replica_run_root) / rid
    child_overrides = [*overrides, f"run_root={root}"]
    child_run_dir = Path(_jsonl_run_dir_jaxfree(load_config(args.config, child_overrides)))
    child_run_dir.mkdir(parents=True, exist_ok=True)
    _clean_replica_root(child_run_dir)
    _seed_checkpoints(args.seed_run_dir, child_run_dir)
    port = find_free_port()
    env = {
        key: value
        for key, value in os.environ.items()
        if not key.startswith("LLMT_CHAOS_ROUTER_")
    }
    env["LLMT_METRICS_PORT"] = str(port)
    env["LLMT_FLEET_DIR"] = str(fleet_dir)
    argv = [sys.executable, "-m", "llm_training_tpu", "serve", "--config", args.config]
    if args.ckpt_path:
        argv += ["--ckpt-path", args.ckpt_path]
    argv += [a for a in args.serve_args if a != "--"]
    argv += [f"run_root={root}"]
    try:
        proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=sys.stderr,
            text=True,
            env=env,
        )
    except OSError as exc:
        logger.error("failed to spawn replica %s: %s", rid, exc)
        return None
    handle = ReplicaHandle(
        rid=rid,
        proc=proc,
        events=events,
        run_dir=child_run_dir,
        port=port,
        started_s=time.monotonic(),
    )
    router.register_replica(handle)
    logger.info("replica %s up: pid=%d port=%d run_dir=%s", rid, proc.pid, port, child_run_dir)
    return handle


def _leg_record(req: RoutedRequest, rid: str, clock=time.monotonic) -> dict:
    """The JSONL record submitted to a replica for one leg of a request.

    Delivered tokens are folded into the prompt (the `submit_resumed`
    watermark semantics) so replays and hedges never re-stream them; ids are
    namespaced per replica so journal folds never collide."""
    record = {
        "id": namespaced_id(rid, req.id),
        "prompt": list(req.prompt) + list(req.generated),
        "max_new_tokens": max(1, req.max_new_tokens - len(req.generated)),
        "priority": req.priority,
    }
    if req.deadline_ms is not None:
        elapsed_ms = (clock() - req.arrival_s) * 1000.0
        record["deadline_ms"] = max(1.0, float(req.deadline_ms) - elapsed_ms)
    return record


def route_main(args) -> int:
    from llm_training_tpu.cli.config import load_config
    from llm_training_tpu.cli.main import _jsonl_run_dir_jaxfree
    from llm_training_tpu.resilience.chaos import (
        config_from_env,
        get_chaos,
        install_chaos,
        uninstall_chaos,
    )
    from llm_training_tpu.resilience.shutdown import GracefulShutdown
    from llm_training_tpu.telemetry.exporter import (
        MetricsExporter,
        find_free_port,
        resolve_metrics_port,
    )
    from llm_training_tpu.telemetry.fleet import FleetAggregator, resolve_scrape_interval
    from llm_training_tpu.telemetry.registry import get_registry
    from llm_training_tpu.telemetry.slo import build_slo_monitor
    from llm_training_tpu.telemetry.trace import get_tracer

    logging.basicConfig(
        stream=sys.stderr,  # stdout is the JSONL protocol
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        force=True,
    )

    overrides = [a for a in args.serve_args if "=" in a and not a.startswith("-")]
    config = load_config(args.config, overrides)
    run_dir = Path(_jsonl_run_dir_jaxfree(config))
    run_dir.mkdir(parents=True, exist_ok=True)
    if args.replica_run_root is None:
        args.replica_run_root = str(run_dir / "replicas")
    if args.seed_run_dir is None and (run_dir / "checkpoints").is_dir():
        args.seed_run_dir = str(run_dir)
    fleet_dir = Path(os.environ.get("LLMT_FLEET_DIR") or (run_dir / "router-fleet"))
    fleet_dir.mkdir(parents=True, exist_ok=True)
    os.environ["LLMT_FLEET_DIR"] = str(fleet_dir)

    min_replicas = max(1, int(args.replicas))
    max_replicas = max(min_replicas, int(args.max_replicas or min_replicas))
    scrape_interval = (
        float(args.scrape_interval_s)
        if args.scrape_interval_s is not None
        else resolve_scrape_interval()
    )

    registry = get_registry()
    chaos = install_chaos(config_from_env(), registry=registry)
    if chaos is not None:
        logger.info("chaos active: %s", chaos.config)
    shutdown = GracefulShutdown().install()
    tracer = get_tracer()
    tracer.attach_sink(run_dir / "trace.jsonl")

    # -- router journal: rotate + fold + resume (exactly-once across router
    # restarts, mirroring serve's own journal discipline) -------------------
    journal_path = run_dir / ROUTER_JOURNAL
    replaying_path = run_dir / ROUTER_JOURNAL_REPLAYING
    resumed_entries: list[dict] = []
    if journal_path.exists():
        shutil.move(str(journal_path), str(replaying_path))
    if replaying_path.exists():
        resumed_entries = replay_journal(str(replaying_path))
    journal = RequestJournal(str(journal_path))

    router = Router(
        journal=journal,
        hedge_ttft_ms=args.hedge_ttft_ms,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        scale_cooldown_s=args.scale_cooldown_s,
        idle_retire_s=args.idle_retire_s,
    )
    router.set_target(min_replicas)

    slo = build_slo_monitor(registry=registry, run_dir=run_dir)
    aggregator = FleetAggregator(fleet_dir=fleet_dir, interval_s=scrape_interval)
    aggregator.start(port=None)
    exporter = MetricsExporter(
        port=resolve_metrics_port() or find_free_port(),
        registry=registry,
        slo=slo,
        role="router",
        extra_fn=router.live_stats,
    )
    exporter.start()

    events: "queue.Queue[tuple[str, str, object]]" = queue.Queue()
    lines: "queue.Queue[object]" = queue.Queue()

    def read_stdin() -> None:
        try:
            for line in sys.stdin:
                lines.put(line)
        finally:
            lines.put(_EOF)

    threading.Thread(target=read_stdin, name="router-stdin", daemon=True).start()

    replica_stats: dict[str, dict] = {}
    tokens_forwarded = 0
    rc = 0

    def emit(event: dict) -> None:
        print(json.dumps(event), flush=True)

    def dispatch(req: RoutedRequest, exclude: tuple[str, ...] = ()) -> None:
        assigned = router.assign(req, exclude=exclude)
        if assigned is None:
            router.park(req)
            return
        rid, seq = assigned
        active_chaos = get_chaos()
        if active_chaos is not None and active_chaos.maybe_router_blackhole(seq):
            router.bump("blackholed")
            router.note({"event": "blackholed", "id": req.id, "replica": rid})
            tracer.instant("router", "blackhole", id=req.id, replica=rid)
            return  # leg stays open; only hedging/failover can finish this
        handle = router.replica(rid)
        if handle is None or not handle.submit(_leg_record(req, rid)):
            result = router.fail_replica(rid, folded=_fold_dead(rid, handle))
            _absorb_failover(rid, result)

    def _fold_dead(rid: str, handle: ReplicaHandle | None) -> list[dict]:
        if handle is None:
            return []
        try:
            return fold_replica_journals({rid: handle.journal_path})
        except OSError:
            return []

    def _absorb_failover(rid: str, result: dict) -> None:
        nonlocal tokens_forwarded
        for ev in result["events"]:
            emit(ev)
            if ev.get("type") == "token":
                tokens_forwarded += 1
            elif ev.get("type") == "done":
                _observe_done(ev)
        tracer.instant("router", "failover", replica=rid, orphans=len(result["orphans"]))
        for req in result["orphans"]:
            if len(req.generated) >= req.max_new_tokens:
                for ev in router.synthesize_done(req, "max_tokens"):
                    emit(ev)
                    _observe_done(ev)
                continue
            req.replays += 1
            router.bump("replays")
            router.note({"event": "replayed", "id": req.id, "emitted": req.emitted})
            dispatch(req)

    def _observe_done(ev: dict) -> None:
        if slo is None:
            return
        ok = ev.get("stop_reason") in COMPLETED_REASONS
        slo.observe_request(ttft_ms=ev.get("ttft_ms"), tpot_ms=ev.get("tpot_ms"), ok=ok)

    def _broadcast(record: dict) -> None:
        for handle in router.replicas():
            handle.submit(record)

    def _handle_chunk(rid: str, obj: dict) -> None:
        nonlocal tokens_forwarded
        kind = obj.get("type")
        if kind == "token":
            for ev in router.record_token(rid, obj):
                emit(ev)
                tokens_forwarded += 1
                active_chaos = get_chaos()
                if active_chaos is not None and active_chaos.maybe_router_kill_replica(
                    tokens_forwarded
                ):
                    handle = router.replica(rid)
                    if handle is not None and handle.alive():
                        tracer.instant("router", "chaos_kill_replica", replica=rid)
                        try:
                            os.kill(handle.proc.pid, signal.SIGKILL)
                        except OSError:
                            pass
        elif kind == "done":
            for ev in router.record_done(rid, obj):
                emit(ev)
                if ev.get("type") == "token":
                    tokens_forwarded += 1
                else:
                    _observe_done(ev)
        elif kind == "stats":
            replica_stats[rid] = obj.get("stats", {})
        elif kind == "error":
            out = dict(obj)
            nsid = str(obj.get("id", ""))
            if "::" in nsid:
                out["id"] = split_namespaced_id(nsid)[1]
            out["replica"] = rid
            emit(out)

    def _handle_eof(rid: str) -> None:
        handle = router.replica(rid)
        if handle is None:
            return
        try:
            returncode = handle.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            handle.kill()
            returncode = handle.proc.wait()
        card = fleet_dir / f"replica-{handle.proc.pid}.json"
        if returncode == 0:
            router.retire_replica(rid)
            tracer.instant("router", "replica_retired", replica=rid)
            logger.info("replica %s retired cleanly", rid)
        else:
            logger.warning("replica %s died rc=%s; failing over", rid, returncode)
            try:
                card.unlink()
            except OSError:
                pass
            result = router.fail_replica(rid, folded=_fold_dead(rid, handle))
            _absorb_failover(rid, result)
            live = len(router.replicas())
            if not closing and live < router.target():
                tracer.instant("router", "replace_replica", replica=rid)
                _provision_replica(router, args, overrides, fleet_dir, events)

    # -- bring up the initial fleet ----------------------------------------
    for _ in range(min_replicas):
        _provision_replica(router, args, overrides, fleet_dir, events)

    for entry in resumed_entries:
        req = router.resume(entry)
        logger.info(
            "resumed %s at emitted=%d after router restart", req.id, req.emitted
        )
        if len(req.generated) >= req.max_new_tokens:
            for ev in router.synthesize_done(req, "max_tokens"):
                emit(ev)
        else:
            dispatch(req)
    if replaying_path.exists():
        replaying_path.unlink()

    open_stdin = True
    closing = False
    drain_deadline: float | None = None
    last_sweeps = -1
    last_hedge_check = 0.0

    try:
        while True:
            now = time.monotonic()
            if shutdown.requested and drain_deadline is None:
                drain_deadline = now + args.drain_timeout_s
                logger.info("shutdown requested: draining for up to %.1fs", args.drain_timeout_s)
            if drain_deadline is not None and now > drain_deadline:
                rc = 75
                break

            # stdin intake
            while open_stdin:
                try:
                    line = lines.get_nowait()
                except queue.Empty:
                    break
                if line is _EOF:
                    open_stdin = False
                    break
                text = str(line).strip()
                if not text:
                    continue
                try:
                    record = json.loads(text)
                except json.JSONDecodeError:
                    logger.warning("skipping malformed request line")
                    continue
                if "type" in record:
                    _broadcast(record)  # control plane: reload / profile
                    continue
                req = router.intake(record)
                if req is not None:
                    dispatch(req)

            # replica events
            try:
                kind, rid, obj = events.get(timeout=0.05)
            except queue.Empty:
                kind = None
            while kind is not None:
                if kind == "chunk":
                    _handle_chunk(rid, obj)
                elif kind == "eof":
                    _handle_eof(rid)
                try:
                    kind, rid, obj = events.get_nowait()
                except queue.Empty:
                    kind = None

            # fleet health: evictions on red/stale, once per fresh sweep
            snapshot = aggregator.snapshot()
            if snapshot.get("sweeps", 0) != last_sweeps:
                last_sweeps = snapshot.get("sweeps", 0)
                for rid_evicted in router.update_fleet(snapshot):
                    tracer.instant("router", "replica_evicted", replica=rid_evicted)
                    logger.warning("evicted %s from rotation (red/stale)", rid_evicted)

            # retry parked requests
            pending = router.take_pending()
            for req in pending:
                dispatch(req)

            # hedging
            if now - last_hedge_check >= 0.05:
                last_hedge_check = now
                for req, hedge_rid in router.maybe_hedge(now):
                    handle = router.replica(hedge_rid)
                    tracer.instant("router", "hedge", id=req.id, replica=hedge_rid)
                    if handle is not None:
                        handle.submit(_leg_record(req, hedge_rid))

            # elasticity
            if not closing:
                breaches = slo.breach_count() if slo is not None else 0
                decision = router.scale_decision(now, breaches)
                if decision is not None:
                    direction, target_rid = decision
                    if direction == "out":
                        tracer.instant("router", "scale_out", target=router.target())
                        logger.info("SLO burn: scaling out to %d replicas", router.target())
                        _provision_replica(router, args, overrides, fleet_dir, events)
                    else:
                        tracer.instant(
                            "router", "scale_in", replica=target_rid, target=router.target()
                        )
                        logger.info("idle: draining and retiring %s", target_rid)
                        handle = router.replica(target_rid)
                        if handle is not None:
                            handle.close_stdin()

            if not open_stdin and router.inflight() == 0 and not closing:
                closing = True
                for handle in router.replicas():
                    handle.close_stdin()
            if closing and not router.replicas():
                break
    finally:
        # terminal sweep: SIGTERM (preserving their journals) then reap
        for handle in router.replicas():
            if drain_deadline is not None and rc == 75:
                try:
                    handle.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            else:
                handle.close_stdin()
        deadline = time.monotonic() + 10.0
        for handle in router.replicas():
            timeout = max(0.1, deadline - time.monotonic())
            try:
                handle.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                handle.kill()
            handle.join_reader(timeout=1.0)
        # drain any trailing chunks (final stats / dones raced with close)
        while True:
            try:
                kind, rid, obj = events.get_nowait()
            except queue.Empty:
                break
            if kind == "chunk":
                _handle_chunk(rid, obj)

        stats = router.stats()
        stats["tokens_forwarded"] = tokens_forwarded
        stats["replica_stats"] = replica_stats
        emit({"type": "stats", "stats": stats})
        _publish_router_telemetry(run_dir, stats)

        journal.close()
        if rc == 0:
            try:
                journal_path.unlink()
            except OSError:
                pass
        exporter.stop()
        aggregator.stop()
        tracer.detach_sink()
        uninstall_chaos()
        shutdown.uninstall()
    return rc
