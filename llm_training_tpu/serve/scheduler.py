"""Continuous-batching request scheduler (docs/serving.md). Pure host
logic — no jax — so policy is unit-testable without a model.

Per engine step the scheduler decides three things:

- **admission**: the head of the waiting queue joins when a decode slot is
  free AND the pool has blocks for its whole (re)prefill plus one decode
  block of headroom — all-or-nothing, so a half-admitted request can never
  deadlock the pool;
- **chunked prefill**: at most ONE fixed-width prompt chunk per step, so a
  long prompt streams into its blocks across steps while every in-flight
  decode row keeps producing a token per step (the interleave that keeps
  TTFT of short requests flat under long-prompt traffic);
- **eviction**: when a decode row needs its next block and the pool is
  dry, the LOWEST-priority running request (ties: youngest arrival) is
  evicted — blocks freed, request requeued at the FRONT of the waiting
  queue with its progress folded into the prompt (`prompt + generated`),
  so on re-admission it re-prefills and CONTINUES; greedy decode makes the
  continuation token-identical to an uninterrupted run.

Two admission-control policies ride the same machinery
(docs/serving.md#resilience):

- **deadlines**: a request may carry `deadline_ms` (a latency budget
  anchored at arrival). `expire_deadlines` — called at the top of every
  engine step — terminates past-deadline work with
  `stop_reason='deadline'` wherever it sits: still queued (never cost a
  FLOP) or mid-decode (blocks freed, the tokens already streamed stand as
  the partial result);
- **load shedding**: the waiting queue is bounded (`max_queue`) and,
  when a service-time estimate exists, projected TTFT is capped
  (`shed_ttft_ms`). Over either threshold the LOWEST-priority queued
  request (ties: youngest arrival — the eviction order) is shed with
  `stop_reason='overloaded'`: an honest immediate terminal instead of a
  queue that grows without bound while every resident deadline burns.
  Intake itself never blocks.


Slots recycle on eos / max-tokens: blocks return to the pool and the row
becomes admissible immediately (the "slot stranding" the dense
`InferenceEngine` batch could not avoid).

Every lifecycle transition additionally emits a trace span
(docs/observability.md#tracing): a request moves queue → prefill → decode
(→ back to queue on eviction) and each phase it leaves becomes one span on
its Perfetto track, so queue-wait and eviction-loss are derivable per
request. The tracer is jax-free (`telemetry/trace.py` — same graftlint
contract as this module), so the import costs this host-only policy layer
nothing.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field

from llm_training_tpu.telemetry.trace import get_tracer


@dataclass
class ServeRequest:
    """One generation request plus its scheduler-owned runtime state."""

    id: str
    prompt: list[int]
    max_new_tokens: int
    priority: int = 0  # higher = more important (evicted last)
    arrival_s: float = field(default_factory=time.perf_counter)
    # absolute (arrival-anchored, perf_counter clock) completion deadline;
    # None = no deadline. Set from the protocol's relative `deadline_ms`.
    deadline_s: float | None = None

    # runtime (scheduler-owned)
    generated: list[int] = field(default_factory=list)
    # chosen-token logprob per generated token (parallel to `generated`;
    # the engine appends both together). None marks a token whose logprob
    # is unknown — e.g. restored from a pre-logprob journal. RL rollout
    # collection (rl/rollout.py) trains on these; eviction preserves them
    # with `generated` so a fold-in requeue loses nothing.
    logprobs: list[float | None] = field(default_factory=list)
    emitted: int = 0  # tokens already streamed (an evict/resume never re-emits)
    slot: int | None = None
    blocks: list[int] = field(default_factory=list)
    prefill_tokens: list[int] = field(default_factory=list)  # this residency's prefill
    prefilled: int = 0  # prefill_tokens positions already written
    cache_len: int = 0  # tokens whose KV is in the pool
    first_token_s: float | None = None
    last_token_s: float | None = None
    evictions: int = 0
    stop_reason: str | None = None
    # tracing (docs/observability.md#tracing): whether this request's
    # events reach the trace.jsonl sink (sampling — the ring records all),
    # the lifecycle phase currently open, when it opened, and the total
    # time spent waiting in the queue (initial + post-eviction)
    traced: bool = True
    phase: str = "queue"
    phase_start_s: float | None = None
    queue_wait_s: float = 0.0

    def advance_phase(self, new_phase: str, now: float | None = None) -> None:
        """Close the open lifecycle phase as a trace span and enter
        `new_phase`. Phases tile the request's residency wall-clock
        exactly: each span starts where the previous one ended."""
        if now is None:
            now = time.perf_counter()
        start = self.phase_start_s if self.phase_start_s is not None else self.arrival_s
        get_tracer().span(
            "serve", self.phase, start, now, write=self.traced,
            request_id=self.id, residency=self.evictions,
        )
        if self.phase == "queue":
            self.queue_wait_s += max(0.0, now - start)
        self.phase = new_phase
        self.phase_start_s = now

    @property
    def done(self) -> bool:
        return self.stop_reason is not None

    @property
    def decoding(self) -> bool:
        """Prefill complete for the current residency — the row produces
        one token per decode step."""
        return (
            self.slot is not None
            and not self.done
            and self.prefilled >= len(self.prefill_tokens)
        )


@dataclass
class SchedulerConfig:
    max_batch: int  # decode slots (the decode program's static batch)
    max_model_len: int  # per-request cap: len(prompt) + max_new_tokens
    block_size: int
    prefill_chunk: int  # tokens per prefill-chunk program call
    # intake bound: queued (not running) requests past this are shed with
    # stop_reason='overloaded'; None = unbounded (the pre-resilience
    # behavior)
    max_queue: int | None = None
    # projected-TTFT bound: when the tail of the queue projects past this
    # many milliseconds to its first token (estimated from completed
    # requests' service times), shed until it doesn't; None disables
    shed_ttft_ms: float | None = None


class Scheduler:
    """Owns the waiting queue, the slot map, and the block accounting
    policy; the `ServingEngine` executes what `admit`/`next_prefill`/
    `ensure_decode_blocks` decide."""

    def __init__(self, config: SchedulerConfig, allocator):
        if config.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.config = config
        self.allocator = allocator
        self.waiting: deque[ServeRequest] = deque()
        self.running: dict[int, ServeRequest] = {}  # slot -> request
        self._free_slots = list(range(config.max_batch - 1, -1, -1))
        self.completed: list[ServeRequest] = []
        self.evictions = 0
        self.shed_total = 0  # 'overloaded' terminations (load shedding)
        self.deadline_total = 0  # 'deadline' terminations (queue + decode)
        # EMA of completed requests' residency seconds (arrival -> done),
        # the service-time estimate behind projected-TTFT shedding; None
        # until the first completion (no estimate -> no TTFT shedding)
        self._service_ema_s: float | None = None

    # ------------------------------------------------------------ intake

    def submit(self, request: ServeRequest) -> ServeRequest | None:
        """Queue a request; returns it REJECTED (stop_reason='rejected')
        instead when it can never fit max_model_len. Enqueueing may shed
        (`stop_reason='overloaded'`) — the victim is the lowest-priority
        QUEUED request, not necessarily this one — so callers must emit
        terminals for everything newly in `completed`, not just the return
        value."""
        total = len(request.prompt) + request.max_new_tokens
        if len(request.prompt) == 0 or request.max_new_tokens < 1:
            request.stop_reason = "rejected"
        elif total > self.config.max_model_len:
            request.stop_reason = "rejected"
        if request.done:
            self.completed.append(request)
            return request
        self.waiting.append(request)
        if not self._free_slots:
            # saturated: nothing will drain this queue before the next
            # decode completes, so the intake bound applies NOW (an honest
            # synchronous 'overloaded'). With a slot free, the next step's
            # admit -> shed pass decides — a burst that fits the free slots
            # must not be shed on arrival order alone.
            self.shed()
        return None

    @property
    def idle(self) -> bool:
        return not self.waiting and not self.running

    def _blocks_for(self, tokens: int) -> int:
        return math.ceil(tokens / self.config.block_size)

    # ------------------------------------------- deadlines + load shedding

    def expire_deadlines(self, now: float | None = None) -> None:
        """Terminate past-deadline requests with stop_reason='deadline' —
        queued ones before they cost a prefill FLOP, decoding ones with
        their blocks freed and the already-streamed tokens standing as the
        partial result. Callers emit terminals via the `completed` diff."""
        if now is None:
            now = time.perf_counter()

        def expired(request: ServeRequest) -> bool:
            return request.deadline_s is not None and now >= request.deadline_s

        for request in [r for r in self.waiting if expired(r)]:
            self.waiting.remove(request)
            self._terminate_queued(request, "deadline", now)
        for request in [r for r in self.running.values() if expired(r)]:
            self.finish(request, "deadline")
            self.deadline_total += 1
            get_tracer().instant(
                "serve", "deadline_expired", write=request.traced,
                request_id=request.id, phase="decode",
                n_tokens=len(request.generated),
            )

    def shed(self) -> None:
        """Shed lowest-priority queued work (stop_reason='overloaded')
        while the queue is over `max_queue` or its tail projects past
        `shed_ttft_ms` to a first token. Reuses the eviction-priority
        order, so under overload the queue keeps exactly the requests
        eviction would have kept."""
        while self.waiting and self._over_intake_limits():
            victim = min(
                self.waiting, key=lambda r: (r.priority, -r.arrival_s)
            )
            self.waiting.remove(victim)
            self._terminate_queued(victim, "overloaded")

    def _over_intake_limits(self) -> bool:
        cfg = self.config
        if cfg.max_queue is not None and len(self.waiting) > cfg.max_queue:
            return True
        projected = self.projected_ttft_ms(len(self.waiting) - 1)
        return (
            cfg.shed_ttft_ms is not None
            and projected is not None
            and projected > cfg.shed_ttft_ms
        )

    def projected_ttft_ms(self, queue_position: int) -> float | None:
        """Estimated milliseconds to first token for the request at
        `queue_position` (0 = head of the waiting queue): each max_batch-
        sized wave ahead of it costs ~one EMA service time. A coarse,
        monotone-in-depth estimate — None until a completion has seeded
        the EMA."""
        if self._service_ema_s is None or queue_position < 0:
            return None
        waves = queue_position // self.config.max_batch + 1
        return 1000.0 * waves * self._service_ema_s

    def _terminate_queued(
        self, request: ServeRequest, stop_reason: str,
        now: float | None = None,
    ) -> None:
        """Complete a never-admitted (or no-longer-resident) request from
        the queue: no slot or blocks to release."""
        request.stop_reason = stop_reason
        request.advance_phase("done", now)
        self.completed.append(request)
        if stop_reason == "overloaded":
            self.shed_total += 1
        elif stop_reason == "deadline":
            self.deadline_total += 1
        get_tracer().instant(
            "serve", "shed" if stop_reason == "overloaded" else "deadline_expired",
            write=request.traced, request_id=request.id, phase="queue",
            queue_depth=len(self.waiting), priority=request.priority,
        )

    # --------------------------------------------------------- admission

    def admit(self) -> list[ServeRequest]:
        """Admit waiting requests while a slot is free and the pool covers
        each one's (re)prefill + one decode-step write. A head-of-queue
        request the pool can NEVER satisfy (even with everything else
        drained) fails with stop_reason='capacity' rather than starving
        the queue behind it."""
        admitted = []
        while self.waiting and self._free_slots:
            request = self.waiting[0]
            resident = request.prompt + request.generated
            needed = self._blocks_for(len(resident) + 1)
            blocks = self.allocator.alloc(needed)
            if blocks is None:
                if not self.running and not admitted:
                    # nothing left to drain — this request cannot ever fit
                    self.waiting.popleft()
                    request.stop_reason = "capacity"
                    request.advance_phase("done")
                    self.completed.append(request)
                    continue
                break
            self.waiting.popleft()
            request.slot = self._free_slots.pop()
            request.blocks = blocks
            request.prefill_tokens = resident
            request.prefilled = 0
            request.cache_len = 0
            request.advance_phase("prefill")
            self.running[request.slot] = request
            admitted.append(request)
        return admitted

    # ----------------------------------------------------------- prefill

    def next_prefill(self) -> tuple[ServeRequest, list[int], int] | None:
        """(request, chunk_tokens, chunk_start) for the oldest running
        request with prompt left to prefill, or None."""
        pending = [
            r for r in self.running.values()
            if r.prefilled < len(r.prefill_tokens)
        ]
        if not pending:
            return None
        request = min(pending, key=lambda r: r.arrival_s)
        start = request.prefilled
        chunk = request.prefill_tokens[start:start + self.config.prefill_chunk]
        return request, chunk, start

    # ------------------------------------------------------------ decode

    def decode_rows(self) -> list[ServeRequest]:
        return [r for r in self.running.values() if r.decoding]

    def ensure_decode_blocks(self, request: ServeRequest) -> bool:
        """Guarantee the row's next token has a cache slot, evicting under
        block pressure. False when the request itself got evicted."""
        while self._blocks_for(request.cache_len + 1) > len(request.blocks):
            grown = self.allocator.alloc(1)
            if grown is not None:
                request.blocks.extend(grown)
                return True
            victim = self._eviction_victim()
            self.evict(victim)
            if victim is request:
                return False
        return True

    def _eviction_victim(self) -> ServeRequest:
        return min(
            self.running.values(), key=lambda r: (r.priority, -r.arrival_s)
        )

    def evict(self, request: ServeRequest) -> None:
        """Free the request's residency and requeue it (front) with its
        progress folded in; already-streamed tokens are never re-emitted."""
        lost_cache = request.cache_len
        request.advance_phase("queue")
        get_tracer().instant(
            "serve", "evicted", write=request.traced, request_id=request.id,
            lost_cache_tokens=lost_cache, generated=len(request.generated),
        )
        self._release(request)
        request.evictions += 1
        self.evictions += 1
        request.prefill_tokens = []
        request.prefilled = 0
        request.cache_len = 0
        self.waiting.appendleft(request)

    # -------------------------------------------------------- completion

    def finish(self, request: ServeRequest, stop_reason: str) -> None:
        request.advance_phase("done")
        self._release(request)
        request.stop_reason = stop_reason
        self.completed.append(request)
        if stop_reason in ("eos", "max_tokens"):
            # successful completions seed the service-time estimate behind
            # projected-TTFT shedding (beta 0.8: a few requests converge it,
            # one outlier doesn't own it)
            service_s = max(0.0, time.perf_counter() - request.arrival_s)
            if self._service_ema_s is None:
                self._service_ema_s = service_s
            else:
                self._service_ema_s = 0.8 * self._service_ema_s + 0.2 * service_s

    def _release(self, request: ServeRequest) -> None:
        del self.running[request.slot]
        self._free_slots.append(request.slot)
        self.allocator.free(request.blocks)
        request.slot = None
        request.blocks = []
