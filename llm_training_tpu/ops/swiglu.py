"""SwiGLU activation.

Capability parity: reference `src/llm_training/ops/swiglu_op.py:5-29`
(separate and fused-weight variants) and the Triton `silu_mul` of
`ops/liger_kernel/swiglu_op.py`. On TPU, `silu(gate) * up` fuses into the
adjacent projections under XLA, so the "fused kernel" is the default path.
"""

import jax
import jax.numpy as jnp


def silu_mul(gate: jnp.ndarray, up: jnp.ndarray) -> jnp.ndarray:
    """silu(gate) * up — the SwiGLU elementwise core."""
    return jax.nn.silu(gate) * up


def swiglu(x: jnp.ndarray, w_gate_up: jnp.ndarray) -> jnp.ndarray:
    """Fused-weight SwiGLU: x @ [w_gate | w_up] then silu(gate) * up.

    `w_gate_up` is `[embed, 2 * intermediate]` with gate in the first half,
    matching the Phi-3 fused `gate_up_proj` layout
    (reference `models/phi3/phi3_model.py:421`).
    """
    gate_up = x @ w_gate_up
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return silu_mul(gate, up)
