"""Pallas TPU ragged paged-decode attention.

The serving-tier kernel (docs/serving.md, "Ragged Paged Attention" in
PAPERS.md): each decode row attends over ITS OWN cache length, gathering
K/V pages through its block table — no shared append index, no left
padding, no FLOPs on another row's history. This is the designated
successor to the dense `DecodeState` decode path's XLA einsum attention
(`models/llama/model.py:_cached_attention`), whose whole-cache attention
bills every row for the longest row's capacity.

Design (one page per kv grid step, flash-style online softmax):

  grid (batch, kv_heads, max_pages_per_request), pages innermost
  ("arbitrary"); the block table and per-row lengths ride as SCALAR
  PREFETCH operands, so each page's BlockSpec index map resolves the
  PHYSICAL pool block to stream — the gather happens in the DMA engine,
  not in compute. Pages past a row's length clamp onto the last valid
  page (the already-resident block), so Pallas elides their DMA and
  `pl.when` skips their compute: a row at length L costs ceil(L/page)
  page visits regardless of the pool size or its neighbours' lengths.

The page size IS this kernel's kv tile (the [group, page_size] score tile
per q-head group), registered with `ops/pallas/tuning.py` under
kind="paged" (page axis in sublanes, head_dim in lanes — hence 8-aligned,
not 128). `interpret=True` runs the kernel on CPU for tier-1 tests,
following the `flash_attention.py` pattern; the XLA gather fallback lives
in `ops/paged_attention.py`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128

# see flash_attention.py: resolve whichever side of the
# TPUCompilerParams -> CompilerParams rename this jax carries
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _decode_kernel(
    tables,  # scalar prefetch: [B, P] physical block per (row, logical page)
    lens,    # scalar prefetch: [B] tokens already written (incl. this one)
    q_ref,   # [1, 1, G, D] this row's q for one kv head's group
    k_ref,   # [1, page, 1, D] one pool page for this kv head
    v_ref,   # [1, page, 1, D]
    o_ref,   # [1, 1, G, D]
    m_ref,   # VMEM [G, lanes] running row max
    l_ref,   # VMEM [G, lanes] running denominator
    acc_ref,  # VMEM [G, D] running numerator
    *,
    page_size: int,
    scale: float,
    sliding_window: int | None,
    logits_soft_cap: float | None,
    num_pages: int,
):
    b, j = pl.program_id(0), pl.program_id(2)
    # q position of the decoded token == its (0-based) cache slot; the
    # caller appends k/v BEFORE attention, so valid kv slots are 0..q_pos
    q_pos = lens[b] - 1

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # pages whose first slot is past q_pos hold nothing this row can see
    @pl.when(j * page_size <= q_pos)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)   # [G, D]
        k = k_ref[0, :, 0].astype(jnp.float32)  # [page, D]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, page]
        if logits_soft_cap is not None:
            s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
        kv_pos = j * page_size + lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        mask = kv_pos <= q_pos
        if sliding_window is not None:
            mask &= (q_pos - kv_pos) < sliding_window
        s = jnp.where(mask, s, _MASK_VALUE)
        m_prev = m_ref[:, :1]                       # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)  # [G, page]
        v = v_ref[0, :, 0].astype(jnp.float32)        # [page, D]
        acc_ref[...] = acc_ref[...] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_pages - 1)
    def _finish():
        l = l_ref[:, :1]
        # a fully-masked row (a sliding window that excludes everything)
        # emits exactly 0 — the _xla_attention invariant
        o_ref[0, 0] = (
            acc_ref[...] / jnp.where(l == 0.0, 1.0, l)
        ).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One ragged decode step: q `[B, Hq, D]` (one token per row) against
    each row's paged cache. `k_pages`/`v_pages` `[N, page, Hkv, D]` are the
    pool, `block_tables [B, P]` maps logical page -> pool block, and
    `lengths [B]` counts tokens written INCLUDING this step's (the caller
    appends before attending). Rows a scheduler left idle should carry
    length 1 and a trash-block table — they compute one garbage token the
    caller ignores. Returns `[B, Hq, D]`."""
    batch, num_q_heads, head_dim = q.shape
    _, page_size, num_kv_heads, _ = k_pages.shape
    num_pages = block_tables.shape[1]
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"num_q_heads ({num_q_heads}) not divisible by num_kv_heads "
            f"({num_kv_heads})"
        )
    group = num_q_heads // num_kv_heads
    if scale is None:
        scale = head_dim**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # q heads are kv-major (head h*G+g serves kv head h) — the same layout
    # _xla_attention's GQA reshape uses
    qg = q.reshape(batch, num_kv_heads, group, head_dim)
    tables = block_tables.astype(jnp.int32)
    lens = lengths.astype(jnp.int32)

    def page_idx(b, h, j, tables, lens):
        # pages past the row's last valid page repeat the last valid one:
        # their DMA is elided and their compute is pl.when-skipped
        jc = jnp.minimum(j, jnp.maximum(lens[b] - 1, 0) // page_size)
        return (tables[b, jc], 0, h, 0)

    out = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            page_size=page_size,
            scale=scale,
            sliding_window=sliding_window,
            logits_soft_cap=logits_soft_cap,
            num_pages=num_pages,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, num_kv_heads, num_pages),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, group, head_dim),
                    lambda b, h, j, tables, lens: (b, h, 0, 0),
                ),
                pl.BlockSpec((1, page_size, 1, head_dim), page_idx),
                pl.BlockSpec((1, page_size, 1, head_dim), page_idx),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, group, head_dim),
                lambda b, h, j, tables, lens: (b, h, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((group, _LANES), jnp.float32),
                pltpu.VMEM((group, _LANES), jnp.float32),
                pltpu.VMEM((group, head_dim), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, num_kv_heads, group, head_dim), q.dtype
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(tables, lens, qg, k_pages, v_pages)
    return out.reshape(batch, num_q_heads, head_dim)
