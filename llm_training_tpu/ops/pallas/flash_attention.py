"""Pallas TPU flash attention with segment-id packing.

TPU-native replacement for the reference's flash-attn CUDA dispatch
(`ops/attention_op.py:538-654`): causal, GQA, sliding window, soft-cap, and
packed varlen via segment ids instead of unpad/cu_seqlens. The reference's
block-diagonal packed mask (`attention_op.py:305-314`) becomes a block-level
segment-id comparison inside the kernel; its `_upad_input`/`pad_input`
round-trip (`attention_op.py:415-485`) has no analogue — packed rows stay
dense and static-shaped, which is what XLA wants anyway.

Design (standard flash attention 2 tiling, TPU-shaped):
  forward: grid (batch*q_heads, q_blocks, kv_blocks), kv innermost
    ("arbitrary"), online-softmax state (m, l, acc) carried in VMEM scratch
    across kv iterations; returns O and the row logsumexp for the backward.
  backward dQ: same grid; recomputes P from (Q, K, LSE), accumulates
    dQ = sum_j dS_ij K_j in scratch.
  backward dK/dV: grid (batch*kv_heads, kv_blocks, gqa_group, q_blocks) —
    the GQA group axis is folded into the kernel grid so dK/dV accumulate
    over the query heads sharing a kv head without an XLA-level reduction.

Causal/sliding-window block skipping: fully-masked (q_block, kv_block) tiles
are skipped with `pl.when`, so causal attention does ~half the FLOPs and a
sliding-window run is linear in window size — the reason flash-attn varlen
wins in the reference, reproduced at the tile level.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llm_training_tpu.ops.pallas.tuning import (
    SOURCE_ORDER,
    BlockChoice,
    bwd_env_override,
    fit_block,
    record_block_choice,
    resolve_block_sizes,
)

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; the r04/r05
# bench machine and this CPU container sit on opposite sides of the rename,
# so resolve whichever exists (the 17 flash tests were dead-on-arrival in
# the CPU container on the missing new name alone)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

# block sizes are resolved at CALL time by ops/pallas/tuning.py (explicit
# arg > FLASH_BLOCK_* env > config/tuning table > 1024 default) — never at
# import, so tests and the offline sweep can override without re-importing.
# The old import-time constants lived here; see tuning.DEFAULT_BLOCK for
# the v5e rationale behind the 1024x1024 fallback.


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _kv_bh_map(num_q_heads: int, num_kv_heads: int):
    """Flat q batch-head index -> flat kv batch-head index (GQA)."""
    group = num_q_heads // num_kv_heads

    def kv_bh(bh_idx):
        return (bh_idx // num_q_heads) * num_kv_heads + (bh_idx % num_q_heads) // group

    return kv_bh


def _q_bh_map(num_q_heads: int, num_kv_heads: int):
    """Flat kv batch-head index + group member -> flat q batch-head index."""
    group = num_q_heads // num_kv_heads

    def q_bh(bhk, g):
        return (bhk // num_kv_heads) * num_q_heads + (bhk % num_kv_heads) * group + g

    return q_bh


def _kv_clamp(
    block_q: int,
    block_k: int,
    q_offset: int,
    causal: bool,
    sliding_window: int | None,
    num_kv_blocks: int,
):
    """j -> clamped kv-block index for q-block i: position-skipped tiles map
    to the nearest VISITED kv block, so their BlockSpec index repeats and
    Pallas elides the k/v DMA entirely (the tile still dispatches, but
    `pl.when` skips its compute). At long causal sequences ~half the grid is
    skipped tiles; without the clamp each still streamed a k/v block."""
    if not causal and sliding_window is None:
        return lambda i, j: j

    def clamp(i, j):
        lo, hi = 0, num_kv_blocks - 1  # unset bounds stay array-wide
        if causal:
            # visit needs k_lo <= q_hi: j <= (q_hi) // block_k
            hi = (i * block_q + q_offset + block_q - 1) // block_k
        if sliding_window is not None:
            # visit needs q_lo - k_hi < w: j*bk + bk - 1 > q_lo - w
            lo = (
                i * block_q + q_offset - sliding_window - block_k + 1
            ) // block_k + 1
        # rows with an empty visited range (or a range outside the array)
        # may point anywhere in bounds — their compute is skipped regardless
        return jnp.clip(jnp.clip(j, lo, hi), 0, num_kv_blocks - 1)

    return clamp


def _q_clamp(
    block_q: int,
    block_k: int,
    q_offset: int,
    causal: bool,
    sliding_window: int | None,
    num_q_blocks: int,
):
    """i -> clamped q-block index for kv-block j (the dkv kernel's mirror of
    `_kv_clamp`)."""
    if not causal and sliding_window is None:
        return lambda j, i: i

    def clamp(j, i):
        lo, hi = 0, num_q_blocks - 1  # unset bounds stay array-wide
        if causal:
            # visit needs q_hi >= k_lo: i >= ceil((j*bk - off - bq + 1)/bq)
            lo = -((q_offset + block_q - 1 - j * block_k) // block_q)
        if sliding_window is not None:
            # visit needs q_lo - k_hi < w: i <= (k_hi + w - 1 - off) // bq
            hi = (
                j * block_k + block_k - 2 + sliding_window - q_offset
            ) // block_q
        return jnp.clip(jnp.clip(i, lo, hi), 0, num_q_blocks - 1)

    return clamp


def _segment_block_bounds(
    seg_q: jnp.ndarray, seg_kv: jnp.ndarray, block_q: int, block_k: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, nq] int32 (lo, hi): the kv-block index range whose segment ids can
    intersect each q block. Feeds the kernels as SCALAR-PREFETCH operands so
    the BlockSpec index maps can clamp segment-skipped tiles onto an
    already-resident kv block — extending the DMA elision from
    position-skipped tiles to runtime packing. [min, max] of the
    intersecting set is a superset for ANY id pattern (conservative: a
    wrongly-included tile only streams, never mis-computes; the in-kernel
    masks stay authoritative). Blocks that are all padding (id 0) are
    treated as intersecting nothing."""
    batch = seg_q.shape[0]
    big = jnp.int32(2**30)
    qb = seg_q.reshape(batch, -1, block_q)
    kb = seg_kv.reshape(batch, -1, block_k)
    qmin = jnp.where(qb == 0, big, qb).min(-1)
    qmax = qb.max(-1)
    kmin = jnp.where(kb == 0, big, kb).min(-1)
    kmax = jnp.where(kb.max(-1) == 0, -1, kb.max(-1))
    nk = kb.shape[1]
    inter = (
        (qmin[..., None] <= kmax[:, None, :])
        & (kmin[:, None, :] <= qmax[..., None])
        & (qmax[..., None] > 0)
    )  # [B, nq, nk]
    any_j = inter.any(-1)
    lo = jnp.where(any_j, jnp.argmax(inter, axis=-1), 0)
    hi = jnp.where(any_j, nk - 1 - jnp.argmax(inter[..., ::-1], axis=-1), 0)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _bounded_idx(pos_clamp, heads_divisor: int):
    """Shared BlockSpec index clamp: static position clamp (`pos_clamp`),
    then the runtime segment clamp from the prefetched [B, n] bounds — tiles
    the kernel will visit are inside both ranges, so their index stays the
    identity; skipped tiles repeat an already-resident block and Pallas
    elides the DMA. `max(hi, lo)` guards the empty-range rows (their compute
    is skipped regardless). Args at call time: (b, a, x, lo, hi) where `a`
    indexes the bounds row and `x` is the streamed-axis grid index."""

    def idx(b, a, x, lo, hi):
        xx = pos_clamp(a, x)
        batch_i = b // heads_divisor
        return jnp.clip(xx, lo[batch_i, a], jnp.maximum(hi[batch_i, a], lo[batch_i, a]))

    return idx


def _resolve_flat_blocks(
    kind: str,
    sq: int,
    skv: int,
    head_dim: int,
    dtype,
    causal: bool,
    sliding_window: int | None,
    block_q: int | None,
    block_k: int | None,
) -> tuple[int, int]:
    """Fill unset block knobs for a flat-kernel call via the tuning layer,
    then fit the RESOLVED (non-explicit) knobs to the actual sequence
    lengths — a table/default block that doesn't divide the input degrades
    to the nearest dividing tile; an explicit block that doesn't divide
    still raises through `_check_block_divisibility` (caller bug)."""
    explicit_q, explicit_k = block_q is not None, block_k is not None
    if explicit_q and explicit_k:
        return block_q, block_k
    choice = resolve_block_sizes(
        kind, seq_len=max(sq, skv), head_dim=head_dim, dtype=dtype,
        causal=causal, sliding_window=sliding_window,
        block_q=block_q, block_k=block_k,
    )
    bq, bk = choice.block_q, choice.block_k
    if not explicit_q and sq % _LANES == 0:
        bq = fit_block(bq, sq)
    if not explicit_k and skv % _LANES == 0:
        bk = fit_block(bk, skv)
    # record the post-fit tiles (what actually compiles), not the raw pick
    record_block_choice(kind, BlockChoice(bq, bk, choice.source))
    return bq, bk


def _check_block_divisibility(sq: int, skv: int, block_q: int, block_k: int) -> None:
    # the kernels floor the grid; a non-dividing block would silently drop
    # trailing rows/columns (callers pad — the public wrapper and ring both do)
    if sq % block_q or skv % block_k:
        raise ValueError(
            f"sequence lengths ({sq}, {skv}) must be multiples of the blocks "
            f"({block_q}, {block_k}); pad inputs or pick dividing blocks"
        )


def _seg_mask(seg_q, seg_kv):
    """(block_q, block_k) segment mask (True = attend): same packed document,
    and the q row is not padding (seg 0)."""
    return (seg_q[:, None] == seg_kv[None, :]) & (seg_q[:, None] > 0)


def _seg_overlap(seg_q, seg_kv):
    """Scalar predicate: the q tile's segment-id range intersects the kv
    tile's, and the q tile is not all padding. Packed documents occupy
    consecutive rows, so disjoint ranges ⇒ fully-masked tile ⇒ skip it —
    this makes packed attention cost the sum of per-document squares instead
    of the full quadratic (the varlen win of the reference's flash-attn
    dispatch, `attention_op.py:538-654`, at tile granularity). Range
    intersection is conservative (interleaved ids only cost a visit, never a
    wrong skip), and padding zeros only widen the ranges."""
    q_max = jnp.max(seg_q)
    return (
        (jnp.min(seg_q) <= jnp.max(seg_kv))
        & (jnp.min(seg_kv) <= q_max)
        & (q_max > 0)
    )


def _seg_uniform(seg_q, seg_kv):
    """Scalar predicate: both blocks hold one identical non-padding segment,
    so the segment mask is all-True and can be skipped. Four cheap vector
    reduces per tile buy skipping the (block_q, block_k) broadcast compare +
    select on the common case (unpacked data, or packed tiles away from
    document boundaries)."""
    q_min = jnp.min(seg_q)
    return (
        (q_min == jnp.max(seg_q))
        & (q_min == jnp.min(seg_kv))
        & (q_min == jnp.max(seg_kv))
        & (q_min > 0)
    )


def _masked_dispatch(visit, interior, uniform, body):
    """Run `body(with_pos, with_seg)` under the cheapest applicable mask
    variant. All four specializations are compiled; exactly one executes per
    tile (scalar-predicated branches, not lane masking)."""
    pl.when(visit & interior & uniform)(lambda: body(False, False))
    pl.when(visit & interior & ~uniform)(lambda: body(False, True))
    pl.when(visit & ~interior & uniform)(lambda: body(True, False))
    pl.when(visit & ~interior & ~uniform)(lambda: body(True, True))


def _pos_mask(
    i,
    j,
    block_q: int,
    block_k: int,
    q_offset: int,
    causal: bool,
    sliding_window: int | None,
):
    """(block_q, block_k) position mask for tile (i, j) — built only on
    boundary tiles (see `_pos_interior`); interior tiles skip the iota and
    compare VPU work entirely, which is most of a flash tile's non-MXU cost."""
    q_pos = (
        i * block_q
        + q_offset
        + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    )
    k_pos = j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = jnp.ones((block_q, block_k), jnp.bool_)
    if causal:
        mask &= k_pos <= q_pos
    if sliding_window is not None:
        mask &= q_pos - k_pos < sliding_window
    return mask


def _pos_interior(
    i,
    j,
    block_q: int,
    block_k: int,
    q_offset: int,
    causal: bool,
    sliding_window: int | None,
):
    """Scalar predicate: every (q, k) position pair in tile (i, j) satisfies
    the causal/window constraints, so only the segment mask applies."""
    interior = jnp.bool_(True)
    q_lo = i * block_q + q_offset
    q_hi = q_lo + block_q - 1
    k_lo = j * block_k
    k_hi = k_lo + block_k - 1
    if causal:
        interior &= k_hi <= q_lo
    if sliding_window is not None:
        interior &= q_hi - k_lo < sliding_window
    return interior


def _should_visit(
    i,
    j,
    block_q: int,
    block_k: int,
    q_offset: int,
    causal: bool,
    sliding_window: int | None,
):
    """Tile-level skip predicate: False when tile (i, j) is fully masked by
    position alone (segments can only mask further)."""
    visit = jnp.bool_(True)
    q_lo = i * block_q + q_offset
    q_hi = q_lo + block_q - 1
    k_lo = j * block_k
    k_hi = k_lo + block_k - 1
    if causal:
        visit &= k_lo <= q_hi
    if sliding_window is not None:
        visit &= q_lo - k_hi < sliding_window
    return visit


def _scores(q, k, scale: float, logits_soft_cap: float | None):
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if scale != 1.0:  # callers fold scale into q; this is the generic path
        s = s * scale
    if logits_soft_cap is not None:
        s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
    return s


def _fwd_kernel(
    seg_lo_ref,  # scalar-prefetch [B, nq]: kv-block bounds per q block
    seg_hi_ref,
    q_seg_ref,
    kv_seg_ref,
    q_ref,
    k_ref,
    v_ref,
    *rest,
    scale: float,
    causal: bool,
    sliding_window: int | None,
    logits_soft_cap: float | None,
    q_offset: int,
    block_q: int,
    block_k: int,
    num_q_heads: int,
    has_sinks: bool = False,
):
    if has_sinks:
        sinks_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        sinks_ref = None
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    @pl.when(j == 0)
    def _init():
        if sinks_ref is None:
            m_scr[:] = jnp.full_like(m_scr, _MASK_VALUE)
            l_scr[:] = jnp.zeros_like(l_scr)
        else:
            # gpt-oss attention sink: the softmax denominator starts life
            # holding exp(sink - sink) == 1 at running max == sink; the
            # standard online-softmax rescaling keeps it exact from there.
            # The sink contributes no value, so acc stays zero-initialized.
            # (This program's head is selected by the sink BlockSpec index
            # map — a dynamic lane index would not lower on Mosaic.)
            sink = sinks_ref[0, 0, 0]
            m_scr[:] = jnp.full_like(m_scr, sink)
            l_scr[:] = jnp.ones_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _visit(with_pos_mask: bool, with_seg_mask: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]

        s = _scores(q, k, scale, logits_soft_cap)
        mask = None
        if with_seg_mask:
            mask = _seg_mask(q_seg_ref[0, 0], kv_seg_ref[0, 0])
        if with_pos_mask:
            pos = _pos_mask(i, j, block_q, block_k, q_offset, causal, sliding_window)
            mask = pos if mask is None else mask & pos

        # masked entries must be numerically inert BEFORE the running max: a
        # masked outlier logit ~88 above the row's true max would otherwise
        # lock m_new and underflow every valid probability (0/0 at flush).
        # The uniform branch has no masked entries, so its raw max is exact
        # and it skips both selects.
        if mask is not None:
            s = jnp.where(mask, s, _MASK_VALUE)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if mask is not None:
            # explicit zeroing keeps fully-masked rows exactly at l == 0 so
            # padding rows emit O = 0, LSE = -inf (exp(MASK - MASK) == 1)
            p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        m_scr[:, :1] = m_new
        l_scr[:, :1] = l_new
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    # the kv BlockSpec index map redirects segment-skipped tiles onto an
    # already-resident kv block (DMA elision), so the STREAMED seg block may
    # not be block j's. The skip decision must therefore come from the
    # ORIGINAL grid index: j inside the prefetched bounds ⇔ no redirection
    # happened ⇔ the streamed data is block j's and _seg_overlap/_seg_uniform
    # are evaluated on the right ids.
    batch_i = pl.program_id(0) // num_q_heads
    in_bounds = (j >= seg_lo_ref[batch_i, i]) & (j <= seg_hi_ref[batch_i, i])
    visit = (
        _should_visit(i, j, block_q, block_k, q_offset, causal, sliding_window)
        & in_bounds
        & _seg_overlap(q_seg_ref[0, 0], kv_seg_ref[0, 0])
    )
    interior = _pos_interior(i, j, block_q, block_k, q_offset, causal, sliding_window)
    uniform = _seg_uniform(q_seg_ref[0, 0], kv_seg_ref[0, 0])
    _masked_dispatch(visit, interior, uniform, _visit)

    @pl.when(j == nk - 1)
    def _flush():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(l_safe), -jnp.inf)
        lse_ref[0, 0] = lse[:, 0]


def _dq_kernel(
    seg_lo_ref,  # scalar-prefetch [B, nq]: kv-block bounds per q block
    seg_hi_ref,
    q_seg_ref,
    kv_seg_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,
    dq_scr,
    *,
    scale: float,
    causal: bool,
    sliding_window: int | None,
    logits_soft_cap: float | None,
    q_offset: int,
    block_q: int,
    block_k: int,
    num_q_heads: int,
):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _visit(with_pos_mask: bool, with_seg_mask: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]

        s = _scores(q, k, scale, logits_soft_cap)
        mask = None
        if with_seg_mask:
            mask = _seg_mask(q_seg_ref[0, 0], kv_seg_ref[0, 0])
        if with_pos_mask:
            pos = _pos_mask(i, j, block_q, block_k, q_offset, causal, sliding_window)
            mask = pos if mask is None else mask & pos
        # lse == -inf on fully-padded rows would give exp(inf); the uniform
        # (maskless) branch only runs when every q row is non-padding, so
        # those rows always carry a finite lse there
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        if logits_soft_cap is not None:
            ds = ds * (1.0 - (s / logits_soft_cap) ** 2)
        if scale != 1.0:
            ds = ds * scale
        dq_scr[:] += jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    # see _fwd_kernel: skip decisions must come from the ORIGINAL grid index,
    # not from the streamed (possibly redirected) seg block
    batch_i = pl.program_id(0) // num_q_heads
    in_bounds = (j >= seg_lo_ref[batch_i, i]) & (j <= seg_hi_ref[batch_i, i])
    visit = (
        _should_visit(i, j, block_q, block_k, q_offset, causal, sliding_window)
        & in_bounds
        & _seg_overlap(q_seg_ref[0, 0], kv_seg_ref[0, 0])
    )
    interior = _pos_interior(i, j, block_q, block_k, q_offset, causal, sliding_window)
    uniform = _seg_uniform(q_seg_ref[0, 0], kv_seg_ref[0, 0])
    _masked_dispatch(visit, interior, uniform, _visit)

    @pl.when(j == nk - 1)
    def _flush():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    seg_lo_ref,  # scalar-prefetch [B, nk] (q-block bounds per KV block)
    seg_hi_ref,
    q_seg_ref,
    kv_seg_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    scale: float,
    causal: bool,
    sliding_window: int | None,
    logits_soft_cap: float | None,
    q_offset: int,
    block_q: int,
    block_k: int,
    num_kv_heads: int,
):
    j = pl.program_id(1)
    g = pl.program_id(2)
    i = pl.program_id(3)
    ng = pl.num_programs(2)
    nq = pl.num_programs(3)

    @pl.when((g == 0) & (i == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _visit(with_pos_mask: bool, with_seg_mask: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]

        s = _scores(q, k, scale, logits_soft_cap)
        mask = None
        if with_seg_mask:
            mask = _seg_mask(q_seg_ref[0, 0], kv_seg_ref[0, 0])
        if with_pos_mask:
            pos = _pos_mask(i, j, block_q, block_k, q_offset, causal, sliding_window)
            mask = pos if mask is None else mask & pos
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        # dV_j += P^T dO ; contraction over the q rows (dim 0 of both)
        dv_scr[:] += lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        if logits_soft_cap is not None:
            ds = ds * (1.0 - (s / logits_soft_cap) ** 2)
        if scale != 1.0:
            ds = ds * scale
        dk_scr[:] += lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # see _fwd_kernel: skip decisions must come from the ORIGINAL grid index,
    # not from the streamed (possibly redirected) seg block. Here the bounds
    # are q-block ranges per kv block, so the gate runs on i.
    batch_i = pl.program_id(0) // num_kv_heads
    in_bounds = (i >= seg_lo_ref[batch_i, j]) & (i <= seg_hi_ref[batch_i, j])
    visit = (
        _should_visit(i, j, block_q, block_k, q_offset, causal, sliding_window)
        & in_bounds
        & _seg_overlap(q_seg_ref[0, 0], kv_seg_ref[0, 0])
    )
    interior = _pos_interior(i, j, block_q, block_k, q_offset, causal, sliding_window)
    uniform = _seg_uniform(q_seg_ref[0, 0], kv_seg_ref[0, 0])
    _masked_dispatch(visit, interior, uniform, _visit)

    @pl.when((g == ng - 1) & (i == nq - 1))
    def _flush():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def flash_fwd_flat(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seg_q: jnp.ndarray,
    seg_kv: jnp.ndarray,
    *,
    num_q_heads: int,
    num_kv_heads: int,
    scale: float,
    causal: bool,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    q_offset: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
    sinks: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Forward kernel over flat padded inputs: q [B*Hq, Sq, D], k/v
    [B*Hkv, Skv, D], seg_q [B, Sq], seg_kv [B, Skv]. Returns
    (o [B*Hq, Sq, D], lse [B*Hq, Sq] fp32). `sinks` [num_q_heads] fp32
    seeds each row's softmax denominator (gpt-oss; lse then includes the
    sink mass). Building block for both the public wrapper and ring
    attention (which re-runs the backward with the globally-combined
    lse)."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    block_q, block_k = _resolve_flat_blocks(
        "fwd", sq, skv, d, q.dtype, causal, sliding_window, block_q, block_k
    )
    _check_block_divisibility(sq, skv, block_q, block_k)
    nq, nk = sq // block_q, skv // block_k
    hyper = dict(
        scale=scale, causal=causal, sliding_window=sliding_window,
        logits_soft_cap=logits_soft_cap, q_offset=q_offset,
        block_q=block_q, block_k=block_k, num_q_heads=num_q_heads,
        has_sinks=sinks is not None,
    )
    kv_bh = _kv_bh_map(num_q_heads, num_kv_heads)
    kv_c = _kv_clamp(block_q, block_k, q_offset, causal, sliding_window, nk)
    seg_lo, seg_hi = _segment_block_bounds(seg_q, seg_kv, block_q, block_k)

    kv_idx = _bounded_idx(kv_c, num_q_heads)

    in_specs = [
        pl.BlockSpec((1, 1, block_q), lambda b, i, j, lo, hi: (b // num_q_heads, 0, i)),
        pl.BlockSpec(
            (1, 1, block_k),
            lambda b, i, j, lo, hi: (b // num_q_heads, 0, kv_idx(b, i, j, lo, hi)),
        ),
        pl.BlockSpec((1, block_q, d), lambda b, i, j, lo, hi: (b, i, 0)),
        pl.BlockSpec(
            (1, block_k, d),
            lambda b, i, j, lo, hi: (kv_bh(b), kv_idx(b, i, j, lo, hi), 0),
        ),
        pl.BlockSpec(
            (1, block_k, d),
            lambda b, i, j, lo, hi: (kv_bh(b), kv_idx(b, i, j, lo, hi), 0),
        ),
    ]
    inputs = [seg_q[:, None], seg_kv[:, None], q, k, v]
    if sinks is not None:
        # one lane-width row per head; the index map picks this program's
        # head so the kernel reads a STATIC [0, 0, 0] scalar
        in_specs.append(
            pl.BlockSpec((1, 1, _LANES), lambda b, i, j, lo, hi: (b % num_q_heads, 0, 0))
        )
        inputs.append(jnp.broadcast_to(
            sinks.astype(jnp.float32)[:, None, None], (num_q_heads, 1, _LANES)
        ))

    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, **hyper),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nq, nk),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j, lo, hi: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j, lo, hi: (b, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seg_lo, seg_hi, *inputs)
    # remat tags: under `recompute_granularity='selective'` the model policy
    # saves exactly these two (save_only_these_names), so the backward pass
    # reads O/LSE instead of re-running this kernel — attention is the one
    # block whose recompute costs as much as its forward
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse[:, 0], "flash_lse")
    return o, lse


def flash_bwd_flat(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    seg_q: jnp.ndarray,
    seg_kv: jnp.ndarray,
    do: jnp.ndarray,
    lse: jnp.ndarray,
    delta: jnp.ndarray,
    *,
    num_q_heads: int,
    num_kv_heads: int,
    scale: float,
    causal: bool,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    q_offset: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Backward kernels over flat padded inputs. `lse`/`delta` are [B*Hq, Sq]
    fp32 — for ring attention they are the globally-combined values, which is
    exactly what makes per-chunk dQ/dK/dV contributions sum to the full-
    sequence gradient.

    `block_q`/`block_k` are the BACKWARD tiles (tuning kind "bwd") — the
    dq/dkv kernels carry different scratch footprints than the forward, so
    their optimal blocks are tuned independently."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    block_q, block_k = _resolve_flat_blocks(
        "bwd", sq, skv, d, q.dtype, causal, sliding_window, block_q, block_k
    )
    _check_block_divisibility(sq, skv, block_q, block_k)
    nq, nk = sq // block_q, skv // block_k
    bh_kv = k.shape[0]
    group = num_q_heads // num_kv_heads
    hyper = dict(
        scale=scale, causal=causal, sliding_window=sliding_window,
        logits_soft_cap=logits_soft_cap, q_offset=q_offset,
        block_q=block_q, block_k=block_k,
    )
    kv_bh = _kv_bh_map(num_q_heads, num_kv_heads)
    q_bh = _q_bh_map(num_q_heads, num_kv_heads)
    kv_c = _kv_clamp(block_q, block_k, q_offset, causal, sliding_window, nk)
    q_c = _q_clamp(block_q, block_k, q_offset, causal, sliding_window, nq)
    # kv-block bounds per q block (dq) and q-block bounds per kv block (dkv):
    # the same runtime DMA elision the forward does, mirrored for the dkv
    # kernel's transposed grid
    seg_lo, seg_hi = _segment_block_bounds(seg_q, seg_kv, block_q, block_k)
    qblk_lo, qblk_hi = _segment_block_bounds(seg_kv, seg_q, block_k, block_q)

    kv_idx = _bounded_idx(kv_c, num_q_heads)
    q_idx = _bounded_idx(q_c, num_kv_heads)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, num_q_heads=num_q_heads, **hyper),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, block_q), lambda b, i, j, lo, hi: (b // num_q_heads, 0, i)),
                pl.BlockSpec(
                    (1, 1, block_k),
                    lambda b, i, j, lo, hi: (b // num_q_heads, 0, kv_idx(b, i, j, lo, hi)),
                ),
                pl.BlockSpec((1, block_q, d), lambda b, i, j, lo, hi: (b, i, 0)),
                pl.BlockSpec(
                    (1, block_k, d),
                    lambda b, i, j, lo, hi: (kv_bh(b), kv_idx(b, i, j, lo, hi), 0),
                ),
                pl.BlockSpec(
                    (1, block_k, d),
                    lambda b, i, j, lo, hi: (kv_bh(b), kv_idx(b, i, j, lo, hi), 0),
                ),
                pl.BlockSpec((1, block_q, d), lambda b, i, j, lo, hi: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j, lo, hi: (b, 0, i)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j, lo, hi: (b, 0, i)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j, lo, hi: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seg_lo, seg_hi, seg_q[:, None], seg_kv[:, None], q, k, v, do, lse[:, None], delta[:, None])

    # q-side refs are indexed by (kv batch-head, group member): the GQA
    # reduction over the q heads sharing one kv head happens in scratch
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, num_kv_heads=num_kv_heads, **hyper),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh_kv, nk, group, nq),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q),
                    lambda b, j, g, i, lo, hi: (b // num_kv_heads, 0, q_idx(b, j, i, lo, hi)),
                ),
                pl.BlockSpec(
                    (1, 1, block_k), lambda b, j, g, i, lo, hi: (b // num_kv_heads, 0, j)
                ),
                pl.BlockSpec(
                    (1, block_q, d),
                    lambda b, j, g, i, lo, hi: (q_bh(b, g), q_idx(b, j, i, lo, hi), 0),
                ),
                pl.BlockSpec((1, block_k, d), lambda b, j, g, i, lo, hi: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, g, i, lo, hi: (b, j, 0)),
                pl.BlockSpec(
                    (1, block_q, d),
                    lambda b, j, g, i, lo, hi: (q_bh(b, g), q_idx(b, j, i, lo, hi), 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_q),
                    lambda b, j, g, i, lo, hi: (q_bh(b, g), 0, q_idx(b, j, i, lo, hi)),
                ),
                pl.BlockSpec(
                    (1, 1, block_q),
                    lambda b, j, g, i, lo, hi: (q_bh(b, g), 0, q_idx(b, j, i, lo, hi)),
                ),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j, g, i, lo, hi: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, g, i, lo, hi: (b, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(qblk_lo, qblk_hi, seg_q[:, None], seg_kv[:, None], q, k, v, do, lse[:, None], delta[:, None])
    return dq, dk, dv


def _make_attention(
    *,
    num_q_heads: int,
    num_kv_heads: int,
    scale: float,
    causal: bool,
    sliding_window: int | None,
    logits_soft_cap: float | None,
    q_offset: int,
    block_q: int,
    block_k: int,
    bwd_block_q: int,
    bwd_block_k: int,
    interpret: bool,
    bwd_source: str = "call",
):
    """Build the custom-VJP flash attention over padded flat inputs.

    `block_q/block_k` tile the forward kernel; `bwd_block_q/bwd_block_k`
    tile the dq/dkv kernels (independent knobs — the backward's scratch
    footprints want different VMEM trade-offs). `bwd_source` is only
    telemetry provenance for the bwd-tile gauges."""
    hyper = dict(
        num_q_heads=num_q_heads,
        num_kv_heads=num_kv_heads,
        scale=scale,
        causal=causal,
        sliding_window=sliding_window,
        logits_soft_cap=logits_soft_cap,
        q_offset=q_offset,
        interpret=interpret,
    )
    fwd_blocks = dict(block_q=block_q, block_k=block_k)
    bwd_blocks = dict(block_q=bwd_block_q, block_k=bwd_block_k)

    @jax.custom_vjp
    def attention(q, k, v, seg_q, seg_kv, sinks):
        o, _ = flash_fwd_flat(q, k, v, seg_q, seg_kv, sinks=sinks, **hyper, **fwd_blocks)
        return o

    def attention_fwd(q, k, v, seg_q, seg_kv, sinks):
        o, lse = flash_fwd_flat(q, k, v, seg_q, seg_kv, sinks=sinks, **hyper, **fwd_blocks)
        return o, (q, k, v, seg_q, seg_kv, sinks, o, lse)

    def attention_bwd(res, do):
        q, k, v, seg_q, seg_kv, sinks, o, lse = res
        # record the bwd tiles HERE, not in the wrapper: this rule only
        # traces when a backward exists in the program, so forward-only
        # traces (eval/validation) never report bwd gauges for kernels
        # they never compile
        record_block_choice(
            "bwd", BlockChoice(bwd_block_q, bwd_block_k, bwd_source)
        )
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
        # the dQ/dK/dV kernels are sink-agnostic: with the sink mass folded
        # into lse, p = exp(s - lse) already sums to < 1 per row and
        # delta == sum_k p_k dP_k still holds (the sink's value is zero)
        dq, dk, dv = flash_bwd_flat(
            q, k, v, seg_q, seg_kv, do, lse, delta, **hyper, **bwd_blocks
        )
        if sinks is None:
            d_sinks = None
        else:
            # d/ds of the sink-softmax: -p_sink * delta per row, summed per
            # head; p_sink = exp(sink - lse)
            bh = lse.shape[0]
            num_q_heads = hyper["num_q_heads"]
            sinks_bh = jnp.tile(sinks.astype(jnp.float32), bh // num_q_heads)
            ds_rows = -jnp.exp(sinks_bh[:, None] - lse) * delta  # [B*H, S]
            d_sinks = (
                ds_rows.reshape(-1, num_q_heads, lse.shape[-1])
                .sum(axis=(0, 2))
                .astype(sinks.dtype)
            )
        return dq, dk, dv, None, None, d_sinks

    attention.defvjp(attention_fwd, attention_bwd)
    return attention


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray | None = None,
    q_segment_ids: jnp.ndarray | None = None,
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    q_offset: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    bwd_block_q: int | None = None,
    bwd_block_k: int | None = None,
    interpret: bool | None = None,
    sinks: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Flash attention over packed sequences.

    q: [batch, q_len, num_q_heads, head_dim]; k/v: [batch, kv_len,
    num_kv_heads, head_dim]; segment ids as in
    `llm_training_tpu.ops.attention.dot_product_attention` (0 = padding).
    Runs compiled on TPU, interpreted elsewhere (tests).

    Block sizes left as None resolve at call time through
    `ops/pallas/tuning.py` (env > tuning table > default), independently
    for the forward (`block_q/block_k`) and backward
    (`bwd_block_q/bwd_block_k`) kernels.
    """
    batch, q_len, num_q_heads, head_dim = q.shape
    kv_len, num_kv_heads = k.shape[1], k.shape[2]
    if num_q_heads % num_kv_heads != 0:
        raise ValueError(
            f"num_q_heads ({num_q_heads}) not divisible by num_kv_heads ({num_kv_heads})"
        )
    if scale is None:
        scale = head_dim**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    orig_dtype = q.dtype
    # fold the softmax scale into q: one multiply per q element replaces one
    # per SCORE element in every kernel (fwd + both bwd recomputes) — the
    # kernels are VPU-bound, so per-score passes are the scarce resource.
    # Gradients stay exact: autodiff chains dq through this multiply, and
    # dk = ds_unscaled · (q·scale) == (ds_unscaled·scale) · q inside the
    # kernel. The tiny bf16 rounding shift is the standard pre-scaled-q
    # formulation (flash-attn does the same).
    if scale != 1.0:
        q = q * jnp.asarray(scale, q.dtype)
        scale = 1.0

    if q_segment_ids is None:
        if segment_ids is not None and q_len != kv_len:
            raise ValueError(
                "q_segment_ids is required when segment_ids is given and "
                f"q_len ({q_len}) != kv_len ({kv_len})"
            )
        q_segment_ids = (
            segment_ids
            if segment_ids is not None
            else jnp.ones((batch, q_len), jnp.int32)
        )
    if segment_ids is None:
        segment_ids = jnp.ones((batch, kv_len), jnp.int32)
    q_segment_ids = q_segment_ids.astype(jnp.int32)
    segment_ids = segment_ids.astype(jnp.int32)

    # resolve fwd/bwd tile sizes at call time (explicit arg > FLASH_BLOCK_*
    # env > tuning table > default). Backward knobs resolve PER KNOB:
    # explicit bwd_block_* arg > bwd-specific FLASH_BLOCK_{Q,K}_BWD env >
    # the same-knob explicit fwd tile (the pre-tuning-layer contract every
    # sweep/microbench call site relies on — a tile you pin tiles BOTH
    # passes, and a stale table entry can never retile a pinned knob) >
    # the shared env/table/default chain for knobs the caller never pinned.
    explicit_bwd_q, explicit_bwd_k = bwd_block_q is not None, bwd_block_k is not None
    fwd_choice = resolve_block_sizes(
        "fwd", seq_len=max(q_len, kv_len), head_dim=head_dim, dtype=q.dtype,
        causal=causal, sliding_window=sliding_window,
        block_q=block_q, block_k=block_k,
    )
    spec = []
    for name, bwd_arg, fwd_arg, fwd_val in (
        ("block_q", bwd_block_q, block_q, fwd_choice.block_q),
        ("block_k", bwd_block_k, block_k, fwd_choice.block_k),
    ):
        if bwd_arg is not None:
            value, src = int(bwd_arg), "call"
        else:
            env_value = bwd_env_override(name)
            if env_value is not None:
                value, src = env_value, "env"
            elif fwd_arg is not None:
                value, src = fwd_val, "call"  # inherited pinned fwd tile
            else:
                value, src = None, None  # shared chain below
        if value is not None and (value < _LANES or value % _LANES):
            raise ValueError(
                f"bwd {name} must be a positive multiple of {_LANES}, got {value}"
            )
        spec.append((value, src))
    if any(value is None for value, _ in spec):
        shared = resolve_block_sizes(
            "bwd", seq_len=max(q_len, kv_len), head_dim=head_dim, dtype=q.dtype,
            causal=causal, sliding_window=sliding_window,
        )
        chain = ((shared.block_q, shared.source_q), (shared.block_k, shared.source_k))
        spec = [pinned if pinned[0] is not None else fallthrough
                for pinned, fallthrough in zip(spec, chain)]
    (bq, src_q), (bk, src_k) = spec
    bwd_choice = BlockChoice(bq, bk, min((src_q, src_k), key=SOURCE_ORDER.index))

    # pad sequence dims to block multiples and head_dim to the lane width;
    # padded tokens get segment id 0, so they are masked not attended.
    # head_dim needs NO padding when the blocks cover it exactly and it is
    # sublane-aligned (64 = Llama-style head dim): Mosaic accepts full-array
    # blocks, and skipping the pad saves ~25% attention time vs 64->128
    # zero-padding (measured on v5e)
    block_q = min(fwd_choice.block_q, _round_up(q_len, _LANES))
    block_k = min(fwd_choice.block_k, _round_up(kv_len, _LANES))
    sq_pad = _round_up(q_len, block_q) - q_len
    skv_pad = _round_up(kv_len, block_k) - kv_len
    # the padded lengths are multiples of the FWD blocks; non-explicit bwd
    # tiles (env/table-resolved, or inherited from the fwd pair) degrade to
    # the nearest dividing block, while explicitly-passed bwd_block_* stay
    # strict (flash_bwd_flat raises on non-divisibility — caller bug)
    if not explicit_bwd_q:
        bwd_block_q = fit_block(bwd_choice.block_q, q_len + sq_pad)
    if not explicit_bwd_k:
        bwd_block_k = fit_block(bwd_choice.block_k, kv_len + skv_pad)
    # record the tiles the kernels will actually compile with (post
    # clamp/fit), not the raw resolution; the bwd gauges are recorded
    # inside the VJP's bwd rule so forward-only traces don't report them
    record_block_choice("fwd", BlockChoice(block_q, block_k, fwd_choice.source))
    d_pad = (
        0
        if head_dim == 64 or head_dim % _LANES == 0
        else _round_up(head_dim, _LANES) - head_dim
    )
    if sq_pad or d_pad:
        q = jnp.pad(q, ((0, 0), (0, sq_pad), (0, 0), (0, d_pad)))
        q_segment_ids = jnp.pad(q_segment_ids, ((0, 0), (0, sq_pad)))
    if skv_pad or d_pad:
        k = jnp.pad(k, ((0, 0), (0, skv_pad), (0, 0), (0, d_pad)))
        v = jnp.pad(v, ((0, 0), (0, skv_pad), (0, 0), (0, d_pad)))
        segment_ids = jnp.pad(segment_ids, ((0, 0), (0, skv_pad)))

    # [B, S, H, D] -> flat [B*H, S, D]
    qf = q.transpose(0, 2, 1, 3).reshape(batch * num_q_heads, q_len + sq_pad, -1)
    kf = k.transpose(0, 2, 1, 3).reshape(batch * num_kv_heads, kv_len + skv_pad, -1)
    vf = v.transpose(0, 2, 1, 3).reshape(batch * num_kv_heads, kv_len + skv_pad, -1)

    attention = _make_attention(
        num_q_heads=num_q_heads,
        num_kv_heads=num_kv_heads,
        scale=scale,
        causal=causal,
        sliding_window=sliding_window,
        logits_soft_cap=logits_soft_cap,
        q_offset=q_offset,
        block_q=block_q,
        block_k=block_k,
        bwd_block_q=bwd_block_q,
        bwd_block_k=bwd_block_k,
        interpret=interpret,
        bwd_source=bwd_choice.source,
    )
    of = attention(qf, kf, vf, q_segment_ids, segment_ids, sinks)

    o = of.reshape(batch, num_q_heads, q_len + sq_pad, -1).transpose(0, 2, 1, 3)
    return o[:, :q_len, :, :head_dim].astype(orig_dtype)
