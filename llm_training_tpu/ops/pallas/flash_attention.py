"""Pallas TPU flash attention with segment-id packing.

TPU-native replacement for the reference's flash-attn CUDA dispatch
(`ops/attention_op.py:538-654`): causal, GQA, sliding window, soft-cap, and
packed varlen via segment ids instead of unpad/cu_seqlens.

Placeholder: the kernel lands with the Pallas kernel milestone; callers fall
back to the XLA path via NotImplementedError until then.
"""

from __future__ import annotations

import jax.numpy as jnp


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray | None = None,
    q_segment_ids: jnp.ndarray | None = None,
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    q_offset: int = 0,
) -> jnp.ndarray:
    raise NotImplementedError("pallas flash attention kernel not yet implemented")
