"""Pallas TPU kernels for the hot ops.

TPU-native replacement for the reference's external native kernels
(flash-attn CUDA, liger-kernel Triton — see SURVEY.md §2.9). Each kernel has
an XLA fallback in `llm_training_tpu.ops`; dispatch is via the `impl=`
arguments on the op entry points.
"""
