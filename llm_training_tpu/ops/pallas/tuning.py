"""Block-size autotuning for the Pallas flash-attention kernels.

"Scalable Training of Language Models using JAX pjit and TPUv4" (PAPERS.md)
makes the point this module operationalizes: TPU kernel throughput is won
or lost in per-shape block/layout choices. The flash kernels used to read
one import-time ``FLASH_BLOCK_Q/K = 1024`` default shared by the forward
and both backward kernels — but the backward kernels carry different
scratch footprints (dq: one [block_q, d] accumulator; dkv: two [block_k, d]
accumulators over a 4-D grid), so their VMEM-optimal tiles are generally
not the forward's.

This module resolves `(block_q, block_k)` **at call time**, separately for
the forward (`kind="fwd"`) and backward (`kind="bwd"`) kernels, in priority
order:

1. **call** — explicit `block_q=`/`block_k=` arguments win unconditionally
   (tests, microbenchmarks, the sweep itself);
2. **env** — `FLASH_BLOCK_Q` / `FLASH_BLOCK_K` (both kinds) and
   `FLASH_BLOCK_Q_BWD` / `FLASH_BLOCK_K_BWD` (backward only), read per
   call so a sweep or test can override without re-importing anything;
3. **table** — the persisted tuning table (JSON under `config/tuning/`,
   written by `scripts/tune_flash_blocks.py`), keyed by
   `(kind, seq_len, head_dim, dtype, causal, sliding_window)`; an exact
   key wins, else the nearest `seq_len` among entries matching every other
   field (block choice varies slowly and monotonically with seq);
4. **default** — 1024x1024, the v5e measurement at seq 2048 the old
   constant encoded.

Every resolution is recorded into the active telemetry registry
(`flash/<kind>/block_q|block_k` gauges + a `flash/tuning_table_hit/<source>`
counter), so `telemetry.jsonl` shows which blocks each compiled step
actually ran with — resolution happens at trace time, which is exactly
once per compiled program.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from pathlib import Path

DEFAULT_BLOCK = 1024
_LANES = 128
_SUBLANES = 8

# env knobs (read at CALL time, never at import)
ENV_FWD = {"block_q": "FLASH_BLOCK_Q", "block_k": "FLASH_BLOCK_K"}
ENV_BWD = {"block_q": "FLASH_BLOCK_Q_BWD", "block_k": "FLASH_BLOCK_K_BWD"}
# the ragged paged-decode kernel (ops/pallas/paged_attention.py): block_k is
# the KV-pool page size — one page IS the kernel's kv tile, so page size is
# this kernel family's tile knob; block_q is reserved (decode q_len == 1)
ENV_PAGED = {"block_q": "PAGED_BLOCK_Q", "block_k": "PAGED_BLOCK_K"}
ENV_TABLE = "FLASH_TUNING_TABLE"

# the paged kernel's page axis sits in the SUBLANE dimension of its
# [group, page] score tile (lanes carry head_dim), so its knobs align to 8,
# not 128 — and serving pools want small pages (16-64 tokens) anyway
_KIND_ALIGN = {"fwd": _LANES, "bwd": _LANES, "paged": _SUBLANES}
_KIND_DEFAULT = {
    "fwd": (DEFAULT_BLOCK, DEFAULT_BLOCK),
    "bwd": (DEFAULT_BLOCK, DEFAULT_BLOCK),
    "paged": (_SUBLANES, 16),
}

_REPO_ROOT = Path(__file__).resolve().parents[3]
DEFAULT_TABLE_PATH = _REPO_ROOT / "config" / "tuning" / "flash_blocks.json"

_table_lock = threading.Lock()
_table_cache: dict[str, dict | None] = {}


SOURCE_ORDER = ("call", "env", "table", "default")  # most specific first


@dataclasses.dataclass(frozen=True)
class BlockChoice:
    """A resolved (block_q, block_k) pair plus where it came from
    (`source` in {"call", "env", "table", "default"} — the most specific
    origin that contributed a knob). `source_q`/`source_k` carry the
    per-knob origin when the resolver produced them (None on fabricated
    choices)."""

    block_q: int
    block_k: int
    source: str
    source_q: str | None = None
    source_k: str | None = None


def dtype_tag(dtype) -> str:
    """Canonical short dtype tag for table keys (bf16/f32/f16/...)."""
    import numpy as np

    name = np.dtype(dtype).name if not isinstance(dtype, str) else str(dtype)
    return {
        "bfloat16": "bf16",
        "float32": "f32",
        "float16": "f16",
        "float64": "f64",
    }.get(name, name)


def table_key(
    kind: str,
    seq_len: int,
    head_dim: int,
    dtype,
    causal: bool,
    sliding_window: int | None,
) -> str:
    """Stable string key for one tuned shape. `sliding_window=None` -> 0."""
    return (
        f"{kind}/seq{int(seq_len)}/d{int(head_dim)}/{dtype_tag(dtype)}/"
        f"causal{int(bool(causal))}/win{int(sliding_window or 0)}"
    )


def _parse_key(key: str) -> dict | None:
    try:
        kind, seq, d, dt, causal, win = key.split("/")
        return {
            "kind": kind,
            "seq_len": int(seq.removeprefix("seq")),
            "head_dim": int(d.removeprefix("d")),
            "dtype": dt,
            "causal": causal == "causal1",
            "win": int(win.removeprefix("win")),
        }
    except (ValueError, AttributeError):
        return None


def table_path() -> Path:
    """Active tuning-table path (env override, else the committed table)."""
    return Path(os.environ.get(ENV_TABLE) or DEFAULT_TABLE_PATH)


def load_table(path: str | Path | None = None) -> dict | None:
    """Load (and cache) the tuning table; None when absent/unreadable — a
    missing table must never fail a training run, it only loses tuning."""
    p = Path(path) if path is not None else table_path()
    key = str(p)
    with _table_lock:
        if key in _table_cache:
            return _table_cache[key]
    try:
        table = json.loads(p.read_text())
        if not isinstance(table.get("entries"), dict):
            table = None
    except (OSError, json.JSONDecodeError, AttributeError):
        table = None
    with _table_lock:
        _table_cache[key] = table
    return table


def clear_table_cache() -> None:
    """Drop cached tables (tests and the sweep rewrite the file in place)."""
    with _table_lock:
        _table_cache.clear()


def _entry_blocks(entry, align: int = _LANES) -> tuple[int, int] | None:
    """Blocks from one table entry, or None when the entry is malformed
    (not a dict, missing/non-int blocks, or not aligned to `align` — the
    lane width for the flash kinds, the sublane width for paged). A bad
    entry must degrade exactly like a corrupt table — skipped, never a
    trace-time crash in a training run (env/call-sourced values raising IS
    correct: those are deliberate per-run intent, this file is ambient
    state)."""
    try:
        bq, bk = int(entry["block_q"]), int(entry["block_k"])
    except (KeyError, TypeError, ValueError):
        return None
    if bq < align or bq % align or bk < align or bk % align:
        return None
    return bq, bk


def _entry_applies(entry: dict) -> bool:
    """cpu-interpret sweep entries are plumbing placeholders — interpreter
    wall-clock says nothing about Mosaic tiles, so they must never drive a
    compiled TPU run (and hardware entries must not drive interpret-mode
    block choice either). Entries without a backend tag apply anywhere."""
    backend = entry.get("backend")
    if not backend:
        return True
    import jax  # deferred: table lookups only happen on kernel call paths

    on_tpu = jax.default_backend() == "tpu"
    is_interpret = "interpret" in str(backend)
    return is_interpret != on_tpu


def _table_lookup(
    kind: str,
    seq_len: int,
    head_dim: int,
    dtype,
    causal: bool,
    sliding_window: int | None,
) -> tuple[int, int] | None:
    table = load_table()
    if table is None:
        return None
    align = _KIND_ALIGN.get(kind, _LANES)
    entries = table["entries"]
    exact = entries.get(table_key(kind, seq_len, head_dim, dtype, causal, sliding_window))
    if exact is not None:
        blocks = _entry_blocks(exact, align)
        if blocks is not None and _entry_applies(exact):
            return blocks
    # nearest-seq fallback among entries matching every other field: ties go
    # to the SMALLER seq (its blocks certainly fit VMEM at the query shape)
    want = {
        "kind": kind,
        "head_dim": int(head_dim),
        "dtype": dtype_tag(dtype),
        "causal": bool(causal),
        "win": int(sliding_window or 0),
    }
    best = None
    for key, entry in entries.items():
        parsed = _parse_key(key)
        blocks = _entry_blocks(entry, align)
        if parsed is None or blocks is None or not _entry_applies(entry):
            continue
        if {k: parsed[k] for k in want} != want:
            continue
        rank = (abs(parsed["seq_len"] - seq_len), parsed["seq_len"])
        if best is None or rank < best[0]:
            best = (rank, blocks)
    if best is None:
        return None
    return best[1]


def _env_int(name: str) -> int | None:
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an int, got {raw!r}") from None


def resolve_block_sizes(
    kind: str,
    *,
    seq_len: int,
    head_dim: int,
    dtype,
    causal: bool,
    sliding_window: int | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
) -> BlockChoice:
    """Resolve `(block_q, block_k)` for one kernel kind at one shape.

    Priority per knob: explicit arg > env > tuning table > the kind's
    default. The reported `source` is the most specific origin that
    contributed either knob (call > env > table > default). `kind="paged"`
    resolves the ragged paged-decode kernel's knobs: block_k is the KV-pool
    page size (the kernel's kv tile), sublane-aligned (8) instead of
    lane-aligned; block_q is reserved (decode q_len == 1).
    """
    if kind not in ("fwd", "bwd", "paged"):
        raise ValueError(f"kind must be 'fwd', 'bwd' or 'paged', got {kind!r}")
    env = {"fwd": ENV_FWD, "bwd": ENV_BWD, "paged": ENV_PAGED}[kind]

    def knob(explicit: int | None, env_name: str, fallback_env: str | None):
        if explicit is not None:
            return int(explicit), "call"
        value = _env_int(env_name)
        # bwd falls back to the shared FLASH_BLOCK_* knobs when no
        # bwd-specific override is set (the pre-tuning-layer semantics)
        if value is None and fallback_env is not None:
            value = _env_int(fallback_env)
        if value is not None:
            return value, "env"
        return None, None

    fb_q = ENV_FWD["block_q"] if kind == "bwd" else None
    fb_k = ENV_FWD["block_k"] if kind == "bwd" else None
    bq, q_src = knob(block_q, env["block_q"], fb_q)
    bk, k_src = knob(block_k, env["block_k"], fb_k)

    if q_src is None or k_src is None:
        hit = _table_lookup(kind, seq_len, head_dim, dtype, causal, sliding_window)
        default_q, default_k = _KIND_DEFAULT[kind]
        if q_src is None:
            bq, q_src = (hit[0], "table") if hit else (default_q, "default")
        if k_src is None:
            bk, k_src = (hit[1], "table") if hit else (default_k, "default")

    align = _KIND_ALIGN[kind]
    for name, value in (("block_q", bq), ("block_k", bk)):
        if value < align or value % align:
            raise ValueError(
                f"{kind} {name} must be a positive multiple of {align}, got {value}"
            )
    source = min((q_src, k_src), key=SOURCE_ORDER.index)
    return BlockChoice(
        block_q=bq, block_k=bk, source=source, source_q=q_src, source_k=k_src
    )


def bwd_env_override(knob: str) -> int | None:
    """The bwd-SPECIFIC env knob (`FLASH_BLOCK_{Q,K}_BWD`), WITHOUT the
    shared `FLASH_BLOCK_*` fallback — for callers that interleave
    explicit-fwd-tile inheritance between the bwd-specific env and the
    shared resolution chain (see `flash_attention`)."""
    return _env_int(ENV_BWD[knob])


def fit_block(requested: int, length: int) -> int:
    """Largest lane-multiple block <= `requested` that divides `length`
    (itself assumed lane-aligned). The flat kernels require exact
    divisibility; 128 always divides a lane-aligned length, so this never
    fails — a tuned/override block that doesn't divide a padded sequence
    degrades to the nearest dividing tile instead of crashing the trace."""
    if length % _LANES:
        raise ValueError(f"length {length} is not a multiple of {_LANES}")
    block = min(int(requested), length)
    block -= block % _LANES
    while length % block:
        block -= _LANES
    return block


def record_block_choice(kind: str, choice: BlockChoice) -> None:
    """Publish the resolved blocks into the active telemetry registry so
    telemetry.jsonl records what each compiled step actually ran with."""
    try:
        from llm_training_tpu.telemetry import get_registry
    except ImportError:  # telemetry is optional for standalone kernel use
        return
    registry = get_registry()
    registry.gauge(f"flash/{kind}/block_q").set(choice.block_q)
    registry.gauge(f"flash/{kind}/block_k").set(choice.block_k)
    registry.counter(f"flash/tuning_table_hit/{choice.source}").inc()


def resolve_paged_block_size(
    *,
    max_model_len: int,
    head_dim: int,
    dtype,
    block_size: int | None = None,
) -> BlockChoice:
    """Resolve the serving pool's KV block (page) size — the paged-decode
    kernel's tile knob (`block_k` of the "paged" kind): explicit config >
    PAGED_BLOCK_K env > tuning table > 16. Recorded into telemetry like
    every other kernel tile resolution."""
    choice = resolve_block_sizes(
        "paged", seq_len=max_model_len, head_dim=head_dim, dtype=dtype,
        causal=True, block_k=block_size,
    )
    record_block_choice("paged", choice)
    return choice
