"""Paged KV-cache attention: scatter-append + ragged attention dispatch.

The serving counterpart of the dense cached-attention path in the shared
decoder stacks (docs/serving.md). The cache is a POOL of fixed-size blocks
(`[num_blocks, block_size, kv_heads, head_dim]` per layer) owned by
`serve/paged_cache.py`; each row addresses it through a block table and
its own length — so this module does per-row scatter writes and per-row
ragged reads where the dense path does one `dynamic_update_slice` at a
shared index.

Two attention paths behind one call:

- single-token decode on TPU (or `impl='pallas'`): the Pallas ragged
  paged-decode kernel (`ops/pallas/paged_attention.py`) — per-row lengths,
  block-table gathers in the DMA engine;
- everything else (chunked prefill q_len > 1, CPU tier-1): an XLA gather
  path — block-table gather to a dense `[B, P*page, H, D]` view plus a
  per-row position mask into the reference einsum attention. Same math,
  shape-static, differentiable-free (decode only), and the oracle the
  kernel is tested against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from llm_training_tpu.ops.attention import _xla_attention


def paged_append(
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    lengths: jnp.ndarray,
    block_tables: jnp.ndarray,
    segment_ids: jnp.ndarray | None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter this chunk's k/v `[B, S, H, D]` into the pool at each row's
    next positions (`lengths[b] + i`). Padded chunk positions (segment id
    0) and any out-of-table position are redirected to the reserved trash
    block 0 — garbage can land there but never in a live block."""
    batch, seq = k.shape[:2]
    page_size = pool_k.shape[1]
    num_pages = block_tables.shape[1]
    pos = lengths[:, None] + jnp.arange(seq, dtype=jnp.int32)[None, :]  # [B, S]
    valid = (
        jnp.ones((batch, seq), bool) if segment_ids is None else segment_ids > 0
    )
    valid &= pos < num_pages * page_size
    page = jnp.take_along_axis(
        block_tables, jnp.minimum(pos // page_size, num_pages - 1), axis=1
    )
    page = jnp.where(valid, page, 0)
    offset = jnp.where(valid, pos % page_size, 0)
    return (
        pool_k.at[page, offset].set(k.astype(pool_k.dtype)),
        pool_v.at[page, offset].set(v.astype(pool_v.dtype)),
    )


def _gather_attention(
    q, pool_k, pool_v, lengths, block_tables, segment_ids,
    sliding_window, logits_soft_cap, scale,
):
    """XLA fallback: dense gather of each row's pages + per-row causal
    mask. `lengths` here is the PRE-append count, so q position i of row b
    sits at absolute slot lengths[b] + i."""
    batch, seq = q.shape[:2]
    page_size = pool_k.shape[1]
    num_pages = block_tables.shape[1]
    # [B, P, page, H, D] -> [B, P*page, H, D]: row b's cache in slot order
    gk = pool_k[block_tables].reshape(batch, num_pages * page_size, *pool_k.shape[2:])
    gv = pool_v[block_tables].reshape(batch, num_pages * page_size, *pool_v.shape[2:])
    q_pos = lengths[:, None] + jnp.arange(seq, dtype=jnp.int32)[None, :]
    kv_pos = jnp.arange(num_pages * page_size, dtype=jnp.int32)
    # [B, 1, S, KV] — True = attend; the causal term alone hides unwritten
    # slots (their position is ahead of every query) and other requests'
    # blocks never appear in this row's table
    mask = kv_pos[None, None, None, :] <= q_pos[:, None, :, None]
    if sliding_window is not None:
        mask &= q_pos[:, None, :, None] - kv_pos[None, None, None, :] < sliding_window
    if segment_ids is not None:
        mask &= (segment_ids > 0)[:, None, :, None]
    return _xla_attention(
        q, gk.astype(q.dtype), gv.astype(q.dtype), mask, scale, logits_soft_cap
    )


def paged_cached_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    layer_kv: tuple[jnp.ndarray, jnp.ndarray],
    lengths: jnp.ndarray,
    block_tables: jnp.ndarray,
    *,
    segment_ids: jnp.ndarray | None = None,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    impl: str = "auto",
) -> tuple[jnp.ndarray, tuple[jnp.ndarray, jnp.ndarray]]:
    """Append this chunk's k/v through the block table, then attend each
    row against its own cache. q/k/v `[B, S, H*, D]` (S == 1 on the decode
    hot path, S == chunk width during chunked prefill); `layer_kv` is this
    layer's pool pair; `lengths [B]` counts tokens already in each row's
    cache BEFORE this chunk. Returns `(out [B, S, Hq, D], new pool pair)`.

    impl: 'auto' (Pallas kernel for single-token decode on TPU, XLA gather
    otherwise) | 'pallas' (kernel forced — interpreted off-TPU) | 'xla'.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    pool_k, pool_v = layer_kv
    lengths = lengths.astype(jnp.int32)
    ck, cv = paged_append(pool_k, pool_v, k, v, lengths, block_tables, segment_ids)

    seq = q.shape[1]
    use_kernel = seq == 1 and (
        impl == "pallas" or (impl == "auto" and jax.default_backend() == "tpu")
    )
    if use_kernel:
        from llm_training_tpu.ops.pallas.paged_attention import (
            paged_decode_attention,
        )

        out = paged_decode_attention(
            q[:, 0], ck, cv, block_tables, lengths + 1,
            scale=scale, sliding_window=sliding_window,
            logits_soft_cap=logits_soft_cap,
            interpret=jax.default_backend() != "tpu",
        )[:, None]
    else:
        out = _gather_attention(
            q, ck, cv, lengths, block_tables, segment_ids,
            sliding_window, logits_soft_cap, scale,
        )
    return out, (ck, cv)
