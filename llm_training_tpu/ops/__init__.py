"""Numerics / kernel layer.

TPU-native counterpart of the reference's `src/llm_training/ops/` package
(attention_op.py, rope_utils.py, rms_norm_op.py, rope_op.py, swiglu_op.py,
cross_entropy_op.py and the Triton wrappers under ops/liger_kernel/).

Pure-jnp reference implementations live here; Pallas TPU kernels live in
`llm_training_tpu.ops.pallas` and are dispatched via the `impl=` arguments.
"""

from llm_training_tpu.ops.rms_norm import rms_norm
from llm_training_tpu.ops.rope import apply_rope, rotate_half
from llm_training_tpu.ops.rope_utils import RoPEConfig, compute_rope_frequencies, compute_rope_cos_sin
from llm_training_tpu.ops.swiglu import swiglu, silu_mul
from llm_training_tpu.ops.cross_entropy import (
    shift_labels,
    cross_entropy,
    fused_linear_cross_entropy,
)
from llm_training_tpu.ops.attention import (
    dot_product_attention,
    make_attention_mask,
    segment_ids_from_attention_mask,
)

__all__ = [
    "rms_norm",
    "apply_rope",
    "rotate_half",
    "RoPEConfig",
    "compute_rope_frequencies",
    "compute_rope_cos_sin",
    "swiglu",
    "silu_mul",
    "shift_labels",
    "cross_entropy",
    "fused_linear_cross_entropy",
    "dot_product_attention",
    "make_attention_mask",
    "segment_ids_from_attention_mask",
]
