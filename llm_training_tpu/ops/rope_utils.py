"""RoPE frequency computation: all six scaling variants.

Capability parity: reference `src/llm_training/ops/rope_utils.py` — the
`default` / `linear` / `dynamic` (NTK) / `yarn` / `longrope` / `llama3`
variants of `ROPE_INIT_FUNCTIONS` (`rope_utils.py:289-296`) plus the
per-variant config validation (`rope_utils.py:462-469`).

Frequencies are computed host-side in float64-free numpy (fp32), since they
depend only on static config + (for `dynamic`/`longrope`) a static sequence
length; the device-side work is just `positions * inv_freq` (see
`compute_rope_cos_sin`), which stays in fp32 as the reference does
(`models/llama/llama_model.py:367-387`).
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np
from pydantic import BaseModel, ConfigDict, model_validator

logger = logging.getLogger(__name__)


class RoPEConfig(BaseModel):
    """Static description of a rotary embedding.

    `scaling` holds the variant-specific knobs (HF `rope_scaling` dict):
      linear/dynamic: factor
      yarn:     factor, [attention_factor, beta_fast, beta_slow]
      longrope: short_factor, long_factor, factor, [attention_factor]
      llama3:   factor, low_freq_factor, high_freq_factor,
                original_max_position_embeddings
    """

    model_config = ConfigDict(extra="forbid")

    type: str = "default"
    base: float = 10000.0
    dim: int
    max_position_embeddings: int
    scaling: dict[str, Any] | None = None

    @model_validator(mode="after")
    def _validate(self) -> "RoPEConfig":
        fn = _VALIDATORS.get(self.type)
        if fn is None:
            raise ValueError(
                f"Unknown rope type {self.type!r}; expected one of {sorted(ROPE_INIT_FUNCTIONS)}"
            )
        fn(self)
        return self


def rope_config_from_hf(
    rope_scaling: dict | None,
    base: float,
    dim: int,
    max_position_embeddings: int,
) -> RoPEConfig:
    """Build a RoPEConfig from HF-style fields: `rope_scaling` may carry the
    variant under 'rope_type' (new) or 'type' (legacy); the rest of the dict
    is the variant's knobs."""
    scaling = dict(rope_scaling) if rope_scaling else None
    rope_type = "default"
    if scaling:
        for key in ("rope_type", "type"):
            if key in scaling:
                rope_type = scaling.pop(key)
    return RoPEConfig(
        type=rope_type,
        base=base,
        dim=dim,
        max_position_embeddings=max_position_embeddings,
        scaling=scaling or None,
    )


def _require(config: RoPEConfig, keys: set[str], optional: set[str] = frozenset()) -> None:
    scaling = config.scaling or {}
    received = set(scaling)
    missing = keys - received
    if missing:
        raise ValueError(f"rope type {config.type!r} requires scaling keys {sorted(missing)}")
    unknown = received - keys - set(optional)
    if unknown:
        logger.warning("rope type %r received unused scaling keys %s", config.type, sorted(unknown))


def _validate_default(config: RoPEConfig) -> None:
    if config.scaling:
        logger.warning("rope type 'default' ignores scaling config %s", config.scaling)


def _validate_factor(config: RoPEConfig) -> None:
    _require(config, {"factor"})
    if config.scaling["factor"] < 1.0:
        raise ValueError(f"rope scaling factor must be >= 1, got {config.scaling['factor']}")


def _validate_yarn(config: RoPEConfig) -> None:
    _require(config, {"factor"}, {
        "attention_factor", "beta_fast", "beta_slow",
        # DeepSeek-style yarn extensions (HF _compute_yarn_parameters)
        "mscale", "mscale_all_dim", "original_max_position_embeddings",
        "truncate",
    })


def _validate_longrope(config: RoPEConfig) -> None:
    _require(config, {"short_factor", "long_factor", "factor"}, {"attention_factor"})
    for key in ("short_factor", "long_factor"):
        factors = config.scaling[key]
        if len(factors) != config.dim // 2:
            raise ValueError(
                f"longrope {key} must have length dim/2={config.dim // 2}, got {len(factors)}"
            )


def _validate_llama3(config: RoPEConfig) -> None:
    _require(
        config,
        {"factor", "low_freq_factor", "high_freq_factor", "original_max_position_embeddings"},
    )
    s = config.scaling
    if s["low_freq_factor"] >= s["high_freq_factor"]:
        raise ValueError("llama3 rope needs low_freq_factor < high_freq_factor")


_VALIDATORS: dict[str, Callable[[RoPEConfig], None]] = {
    "default": _validate_default,
    "linear": _validate_factor,
    "dynamic": _validate_factor,
    "yarn": _validate_yarn,
    "longrope": _validate_longrope,
    "llama3": _validate_llama3,
}


def _base_inv_freq(base: float, dim: int) -> np.ndarray:
    return 1.0 / (base ** (np.arange(0, dim, 2, dtype=np.float32) / dim))


def _default_rope(config: RoPEConfig, seq_len: int | None) -> tuple[np.ndarray, float]:
    return _base_inv_freq(config.base, config.dim), 1.0


def _linear_rope(config: RoPEConfig, seq_len: int | None) -> tuple[np.ndarray, float]:
    inv_freq, attention_factor = _default_rope(config, seq_len)
    return inv_freq / config.scaling["factor"], attention_factor


def _dynamic_ntk_rope(config: RoPEConfig, seq_len: int | None) -> tuple[np.ndarray, float]:
    dim = config.dim
    factor = config.scaling["factor"]
    max_pos = config.max_position_embeddings
    seq_len = seq_len if seq_len is not None and seq_len > max_pos else max_pos
    base = config.base * ((factor * seq_len / max_pos) - (factor - 1)) ** (dim / (dim - 2))
    return _base_inv_freq(base, dim), 1.0


def _yarn_rope(config: RoPEConfig, seq_len: int | None) -> tuple[np.ndarray, float]:
    base, dim = config.base, config.dim
    max_pos = config.max_position_embeddings
    scaling = config.scaling
    factor = scaling["factor"]

    # DeepSeek-style yarn (HF _compute_yarn_parameters): the pre-extension
    # context length anchors the correction range ONLY — the interpolation
    # factor stays rope_scaling['factor']; mscale/mscale_all_dim shape the
    # attention factor
    max_pos = scaling.get("original_max_position_embeddings") or max_pos

    def get_mscale(scale: float, mscale: float = 1.0) -> float:
        if scale <= 1.0:
            return 1.0
        return 0.1 * mscale * math.log(scale) + 1.0

    attention_factor = scaling.get("attention_factor")
    if attention_factor is None:
        mscale = scaling.get("mscale")
        mscale_all_dim = scaling.get("mscale_all_dim")
        if mscale and mscale_all_dim:
            attention_factor = get_mscale(factor, mscale) / get_mscale(
                factor, mscale_all_dim
            )
        else:
            attention_factor = get_mscale(factor)
    beta_fast = scaling.get("beta_fast") or 32
    beta_slow = scaling.get("beta_slow") or 1

    def correction_dim(num_rotations: float) -> float:
        # Dimension whose wavelength completes `num_rotations` over the context.
        return dim * math.log(max_pos / (num_rotations * 2 * math.pi)) / (2 * math.log(base))

    low, high = correction_dim(beta_fast), correction_dim(beta_slow)
    if scaling.get("truncate", True):  # HF default: integer range bounds
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001  # avoid a 0-width ramp

    ramp = np.clip((np.arange(dim // 2, dtype=np.float32) - low) / (high - low), 0, 1)
    pos_freqs = config.base ** (np.arange(0, dim, 2, dtype=np.float32) / dim)
    extrapolation = 1.0 / pos_freqs
    interpolation = 1.0 / (factor * pos_freqs)
    # ramp==0 → pure extrapolation (high-freq dims); ramp==1 → pure interpolation.
    extrapolation_weight = 1.0 - ramp
    inv_freq = interpolation * (1 - extrapolation_weight) + extrapolation * extrapolation_weight
    return inv_freq.astype(np.float32), float(attention_factor)


def _longrope_rope(config: RoPEConfig, seq_len: int | None) -> tuple[np.ndarray, float]:
    base, dim = config.base, config.dim
    max_pos = config.max_position_embeddings
    scaling = config.scaling
    factor = scaling["factor"]

    seq_len = seq_len or int(max_pos * factor)
    attention_factor = scaling.get("attention_factor")
    if attention_factor is None:
        if factor <= 1.0:
            attention_factor = 1.0
        else:
            attention_factor = math.sqrt(1 + math.log(factor) / math.log(max_pos))

    key = "long_factor" if seq_len > max_pos else "short_factor"
    ext_factors = np.asarray(scaling[key], dtype=np.float32)
    inv_freq = 1.0 / (ext_factors * base ** (np.arange(0, dim, 2, dtype=np.float32) / dim))
    return inv_freq.astype(np.float32), float(attention_factor)


def _llama3_rope(config: RoPEConfig, seq_len: int | None) -> tuple[np.ndarray, float]:
    inv_freq, attention_factor = _default_rope(config, seq_len)
    scaling = config.scaling
    factor = scaling["factor"]
    low_freq_factor = scaling["low_freq_factor"]
    high_freq_factor = scaling["high_freq_factor"]
    old_context_len = scaling["original_max_position_embeddings"]

    low_freq_wavelen = old_context_len / low_freq_factor
    high_freq_wavelen = old_context_len / high_freq_factor
    wavelen = 2 * math.pi / inv_freq

    scaled = np.where(wavelen > low_freq_wavelen, inv_freq / factor, inv_freq)
    smooth = (old_context_len / wavelen - low_freq_factor) / (high_freq_factor - low_freq_factor)
    smoothed = (1 - smooth) * scaled / factor + smooth * scaled
    is_medium = (wavelen >= high_freq_wavelen) & (wavelen <= low_freq_wavelen)
    return np.where(is_medium, smoothed, scaled).astype(np.float32), attention_factor


ROPE_INIT_FUNCTIONS: dict[str, Callable[[RoPEConfig, int | None], tuple[np.ndarray, float]]] = {
    "default": _default_rope,
    "linear": _linear_rope,
    "dynamic": _dynamic_ntk_rope,
    "yarn": _yarn_rope,
    "longrope": _longrope_rope,
    "llama3": _llama3_rope,
}


def compute_rope_frequencies(
    config: RoPEConfig, seq_len: int | None = None
) -> tuple[np.ndarray, float]:
    """Return (inv_freq[dim/2] fp32 numpy, attention_factor)."""
    return ROPE_INIT_FUNCTIONS[config.type](config, seq_len)


def compute_rope_cos_sin(
    inv_freq: np.ndarray | jnp.ndarray,
    positions: jnp.ndarray,
    attention_factor: float = 1.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for `positions` (any leading shape), fp32.

    Output shape: positions.shape + (dim,), with the frequency vector
    duplicated along the last dim (HF half-rotation layout).
    """
    inv_freq = jnp.asarray(inv_freq, dtype=jnp.float32)
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb) * attention_factor, jnp.sin(emb) * attention_factor
