"""Attention with segment-id packing (no-cross-contamination), GQA,
sliding window, and soft-capping.

Capability parity: reference `src/llm_training/ops/attention_op.py` — the
entire 4-D mask-building + varlen unpad/repad machinery
(`attention_op.py:286-535`) collapses on TPU into *segment ids*: the
reference's per-document attention-mask ids (1..N, 0 = padding) are used
directly as segment ids, and the mask `seg_q == seg_kv & causal & window`
reproduces its block-diagonal packed mask (`attention_op.py:305-314`) with no
unpadding (static shapes are required by XLA anyway; packed batches waste no
FLOPs on padding because packing fills rows to max_length).

`flash_attention_forward`'s dispatch surface (`attention_op.py:538-654`:
causal, sliding window, softcap, varlen-vs-dense) maps onto the `impl=`
argument: 'xla' is the einsum/softmax reference path (fp32 accumulation),
'pallas' is the flash kernel in `ops/pallas/flash_attention.py`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def segment_ids_from_attention_mask(attention_mask: jnp.ndarray) -> jnp.ndarray:
    """The reference's document-id attention mask *is* a segment-id tensor:
    values 1..N identify packed documents, 0 marks padding
    (`attention_op.py:286-302`)."""
    return attention_mask.astype(jnp.int32)


def make_attention_mask(
    segment_ids_q: jnp.ndarray | None,
    segment_ids_kv: jnp.ndarray | None,
    q_len: int,
    kv_len: int,
    causal: bool = True,
    sliding_window: int | None = None,
    q_offset: int | jnp.ndarray = 0,
) -> jnp.ndarray:
    """Boolean mask [batch, 1, q_len, kv_len] (True = attend).

    `q_offset` is the absolute position of query row 0 in the kv sequence:
    a static int for ring attention (q is a rotating kv chunk's neighbour),
    or a TRACED scalar for KV-cache decoding (`infer/`), where kv is the
    whole static-shape cache and the offset is the dynamic append index —
    row `q_offset + i` of this mask must equal row `q_offset + i` of the
    full dense q_len==kv_len mask (the invariant the decode path relies
    on; tests/test_ops.py::test_make_attention_mask_q_offset_decode_rows).
    Positions the cache has not reached yet fall away via the causal term
    (kv_pos > q_pos) and the `seg_kv > 0` term (unwritten slots carry
    segment id 0)."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    mask = jnp.ones((q_len, kv_len), dtype=bool)
    if causal:
        mask &= kv_pos <= q_pos
    if sliding_window is not None:
        mask &= q_pos - kv_pos < sliding_window
    mask = mask[None, None]  # [1, 1, q, kv]
    if segment_ids_q is not None:
        seg_q = segment_ids_q[:, None, :, None]
        seg_kv = segment_ids_kv[:, None, None, :]
        mask = mask & (seg_q == seg_kv) & (seg_q > 0) & (seg_kv > 0)
    return mask


def _xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray | None,
    scale: float,
    logits_soft_cap: float | None,
    sinks: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Reference einsum attention, fp32 softmax, GQA without repeating kv.

    q: [B, Sq, Hq, D]; k/v: [B, Skv, Hkv, D]; Hq % Hkv == 0.
    """
    batch, q_len, num_q_heads, head_dim = q.shape
    num_kv_heads = k.shape[2]
    if num_q_heads % num_kv_heads != 0:
        raise ValueError(
            f"num_q_heads ({num_q_heads}) must be divisible by num_kv_heads ({num_kv_heads})"
        )
    group = num_q_heads // num_kv_heads

    qg = q.reshape(batch, q_len, num_kv_heads, group, head_dim)
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if logits_soft_cap is not None:
        scores = logits_soft_cap * jnp.tanh(scores / logits_soft_cap)
    if mask is not None:
        scores = jnp.where(mask[:, :, None], scores, _MASK_VALUE)
    if sinks is not None:
        # gpt-oss attention sinks: one learned logit per query head joins
        # each row's softmax denominator (with zero value), damping rows
        # whose real scores are all weak
        sink = sinks.reshape(num_kv_heads, group)[None, :, :, None]
        m = jnp.maximum(scores.max(axis=-1), sink)
        p = jnp.exp(scores - m[..., None])
        if mask is not None:
            p = jnp.where(mask[:, :, None], p, 0.0)
        denom = p.sum(axis=-1) + jnp.exp(sink - m)
        probs = p / denom[..., None]
    else:
        probs = jax.nn.softmax(scores, axis=-1)
    if mask is not None:
        # fully-masked rows (padding / empty ring chunks) emit exactly 0, not
        # the mean of v that a softmax over all-masked scores would give —
        # the invariant the flash kernel and ring combiner provide
        probs = jnp.where(mask[:, :, None].any(-1, keepdims=True), probs, 0.0)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v)
    out = out.reshape(batch, q_len, num_q_heads, head_dim)
    # same remat tag the flash kernel carries, so
    # recompute_granularity='selective' saves the attention output on this
    # path too (its backward still rebuilds softmax internals from q/k —
    # autodiff residuals, unlike the flash kernel's O/LSE-only backward)
    return checkpoint_name(out, "flash_out")


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray | None = None,
    q_segment_ids: jnp.ndarray | None = None,
    causal: bool = True,
    sliding_window: int | None = None,
    logits_soft_cap: float | None = None,
    scale: float | None = None,
    q_offset: int = 0,
    impl: str = "auto",
    sinks: jnp.ndarray | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    bwd_block_q: int | None = None,
    bwd_block_k: int | None = None,
) -> jnp.ndarray:
    """Multi-head attention over packed sequences.

    q: [batch, q_len, num_q_heads, head_dim]
    k, v: [batch, kv_len, num_kv_heads, head_dim]
    segment_ids: [batch, kv_len] int (0 = padding, 1..N = packed documents)
    q_segment_ids: [batch, q_len]; defaults to `segment_ids` when q and kv
        are the same sequence (q_len == kv_len). Required when packing is
        used with q_len != kv_len (e.g. ring-attention chunks).
    q_offset: absolute position of query row 0 within the kv sequence, for
        causal masking of cross-length chunks.
    impl: 'auto' (pallas flash kernel on TPU, einsum path elsewhere) |
        'xla' | 'pallas' (forced; interpreted off-TPU).
    sinks: [num_q_heads] learned per-head sink logits (gpt-oss); joins each
        softmax denominator with zero value (both impls — the flash kernel
        seeds its online-softmax denominator with the sink mass).
    block_q/block_k/bwd_block_q/bwd_block_k: flash-kernel tile overrides
        (fwd and bwd independently); None resolves at call time through
        `ops/pallas/tuning.py` (env > tuning table > default). Ignored on
        the XLA path.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if q_segment_ids is None and segment_ids is not None:
        if q.shape[1] != k.shape[1]:
            raise ValueError(
                "q_segment_ids is required when segment_ids is given and "
                f"q_len ({q.shape[1]}) != kv_len ({k.shape[1]})"
            )
        q_segment_ids = segment_ids

    use_pallas = impl == "pallas" or (impl == "auto" and jax.default_backend() == "tpu")
    if use_pallas:
        from llm_training_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention(
            q, k, v,
            segment_ids=segment_ids,
            q_segment_ids=q_segment_ids,
            causal=causal,
            sliding_window=sliding_window,
            logits_soft_cap=logits_soft_cap,
            scale=scale,
            q_offset=q_offset,
            sinks=sinks,
            block_q=block_q,
            block_k=block_k,
            bwd_block_q=bwd_block_q,
            bwd_block_k=bwd_block_k,
        )

    mask = None
    if segment_ids is not None or causal or sliding_window is not None:
        mask = make_attention_mask(
            q_segment_ids, segment_ids, q.shape[1], k.shape[1],
            causal=causal, sliding_window=sliding_window, q_offset=q_offset,
        )
    return _xla_attention(q, k, v, mask, scale, logits_soft_cap, sinks=sinks)
