"""Cross-entropy losses, including chunked fused-linear-CE.

Capability parity: reference `src/llm_training/ops/cross_entropy_op.py:4-8`
(`shift_labels`) and the liger Triton kernels
`ops/liger_kernel/cross_entropy_op.py:10-54` (`cross_entropy`,
`fused_linear_cross_entropy`).

The fused-linear variant is the TPU-idiomatic equivalent of liger's kernel:
instead of a hand-written Triton kernel that never materializes the full
`[tokens, vocab]` logit tensor, we chunk the token axis with `lax.scan` and
wrap the chunk body in `jax.checkpoint`, so both forward and backward peak at
`O(chunk_size * vocab)` logits. XLA fuses the matmul + logsumexp + gather per
chunk onto the MXU/VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def shift_labels(labels: jnp.ndarray, ignore_index: int = -100) -> jnp.ndarray:
    """Next-token shift: labels[i] = input[i+1]; final position is ignored."""
    shifted = jnp.roll(labels, -1, axis=-1)
    return shifted.at[..., -1].set(ignore_index)


def _token_nll(logits32: jnp.ndarray, labels: jnp.ndarray, ignore_index: int):
    """Per-token negative log-likelihood (fp32) and validity mask."""
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.scipy.special.logsumexp(logits32, axis=-1)
    label_logits = jnp.take_along_axis(logits32, safe_labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, lse - label_logits, 0.0)
    return nll, valid


def cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_index: int = -100,
    reduction: str = "mean",
) -> jnp.ndarray:
    """Cross-entropy over the last dim of `logits`, fp32 accumulation.

    reduction: 'mean' (over non-ignored tokens), 'sum', or 'none'.
    """
    nll, valid = _token_nll(logits.astype(jnp.float32), labels, ignore_index)
    if reduction == "none":
        return nll
    if reduction == "sum":
        return nll.sum()
    if reduction == "mean":
        return nll.sum() / jnp.maximum(valid.sum(), 1).astype(jnp.float32)
    raise ValueError(f"unknown reduction {reduction!r}")


def fused_linear_cross_entropy(
    hidden: jnp.ndarray,
    weight: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_index: int = -100,
    chunk_size: int = 1024,
    logits_soft_cap: float | None = None,
    bias: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """CE of `hidden @ weight (+ bias)` against `labels` without full logits.

    hidden: [tokens, embed] (any leading shape is flattened)
    weight: [embed, vocab] — the lm_head matrix
    bias: [vocab] — the lm_head bias (Phi-style heads), added per chunk
    Returns (sum_nll fp32 scalar, num_valid_tokens int32 scalar); callers
    divide to get the mean so distributed reductions stay exact.
    """
    embed = hidden.shape[-1]
    hidden = hidden.reshape(-1, embed)
    labels = labels.reshape(-1)
    n_tokens = hidden.shape[0]

    chunk_size = min(chunk_size, n_tokens)
    num_chunks = -(-n_tokens // chunk_size)
    pad = num_chunks * chunk_size - n_tokens
    if pad:
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)

    hidden_chunks = hidden.reshape(num_chunks, chunk_size, embed)
    label_chunks = labels.reshape(num_chunks, chunk_size)

    @functools.partial(jax.checkpoint, policy=jax.checkpoint_policies.nothing_saveable)
    def chunk_loss(h: jnp.ndarray, l: jnp.ndarray):
        logits = jnp.dot(h, weight, preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        nll, valid = _token_nll(logits, l, ignore_index)
        return nll.sum(), valid.sum()

    def body(carry, xs):
        total, count = carry
        s, c = chunk_loss(*xs)
        return (total + s, count + c), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (hidden_chunks, label_chunks)
    )
    return total, count


def fused_linear_log_probs(
    hidden: jnp.ndarray,
    weight: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_index: int = -100,
    chunk_size: int = 1024,
    logits_soft_cap: float | None = None,
    bias: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-sequence label log-probs of `hidden @ weight` without full logits.

    hidden: [batch, seq, embed]; labels: [batch, seq].
    Returns (sum log p per row [batch] fp32, valid-token counts [batch]).
    The DPO/ORPO building block (reference `dpo.py:89-108`,
    `orpo.py:60-93`): chunked over the sequence axis with rematerialized
    chunks, so peak memory is O(batch * chunk * vocab) — the same trick as
    `fused_linear_cross_entropy` but with per-row reductions.
    """
    batch, seq, embed = hidden.shape
    chunk_size = min(chunk_size, seq)
    num_chunks = -(-seq // chunk_size)
    pad = num_chunks * chunk_size - seq
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)), constant_values=ignore_index)

    # [num_chunks, batch, chunk, ...] for scan
    hidden_chunks = jnp.moveaxis(
        hidden.reshape(batch, num_chunks, chunk_size, embed), 1, 0
    )
    label_chunks = jnp.moveaxis(
        labels.reshape(batch, num_chunks, chunk_size), 1, 0
    )

    @functools.partial(jax.checkpoint, policy=jax.checkpoint_policies.nothing_saveable)
    def chunk_logps(h: jnp.ndarray, l: jnp.ndarray):
        logits = jnp.dot(h, weight, preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        nll, valid = _token_nll(logits, l, ignore_index)
        return -nll.sum(axis=-1), valid.sum(axis=-1)

    def body(carry, xs):
        total, count = carry
        s, c = chunk_logps(*xs)
        return (total + s, count + c), None

    (logps, counts), _ = jax.lax.scan(
        body,
        (jnp.zeros((batch,), jnp.float32), jnp.zeros((batch,), jnp.int32)),
        (hidden_chunks, label_chunks),
    )
    return logps, counts


def fused_linear_token_log_probs(
    hidden: jnp.ndarray,
    weight: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_index: int = -100,
    chunk_size: int = 1024,
    logits_soft_cap: float | None = None,
    bias: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-TOKEN label log-probs of `hidden @ weight` without full logits.

    hidden: [batch, seq, embed]; labels: [batch, seq].
    Returns (log p per token [batch, seq] fp32 — 0.0 at ignore_index
    positions — and the validity mask [batch, seq] bool). The GRPO
    building block (lms/grpo.py): a token-level policy gradient needs
    each completion token's logp under policy and reference, not a
    per-sequence sum, but must still never materialize [batch, seq,
    vocab] logits — same chunked-remat scan as `fused_linear_log_probs`,
    stacking per-chunk results instead of reducing them.
    """
    batch, seq, embed = hidden.shape
    chunk_size = min(chunk_size, seq)
    num_chunks = -(-seq // chunk_size)
    pad = num_chunks * chunk_size - seq
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)), constant_values=ignore_index)

    hidden_chunks = jnp.moveaxis(
        hidden.reshape(batch, num_chunks, chunk_size, embed), 1, 0
    )
    label_chunks = jnp.moveaxis(
        labels.reshape(batch, num_chunks, chunk_size), 1, 0
    )

    @functools.partial(jax.checkpoint, policy=jax.checkpoint_policies.nothing_saveable)
    def chunk_logps(h: jnp.ndarray, l: jnp.ndarray):
        logits = jnp.dot(h, weight, preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        if logits_soft_cap is not None:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        nll, valid = _token_nll(logits, l, ignore_index)
        return -nll, valid

    def body(carry, xs):
        return carry, chunk_logps(*xs)

    _, (logps, valids) = jax.lax.scan(body, None, (hidden_chunks, label_chunks))
    # [num_chunks, batch, chunk] -> [batch, seq(+pad)] -> strip the pad
    logps = jnp.moveaxis(logps, 0, 1).reshape(batch, -1)[:, :seq]
    valids = jnp.moveaxis(valids, 0, 1).reshape(batch, -1)[:, :seq]
    return logps, valids
