"""RMS normalization.

Capability parity: reference `src/llm_training/ops/rms_norm_op.py:4-14` (fp32
upcast, variance over last dim) and the Triton-fused
`ops/liger_kernel/rms_norm_op.py`. On TPU the fused version is just this
function under XLA fusion — the normalization fuses into the surrounding
elementwise/matmul HLO, so no hand-written kernel is needed for parity.
"""

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """y = weight * (x / rms(x)).

    The normalization runs in fp32; the normalized value is rounded back to
    x.dtype *before* the weight multiply, matching the reference's order of
    operations (`rms_norm_op.py:4-14`: `weight * x_normed.to(input_dtype)`) so
    bf16 activations produce bit-identical results.
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    x_normed = (x32 * jax.lax.rsqrt(variance + eps)).astype(dtype)
    return weight * x_normed
