"""Rotary position embedding application.

Capability parity: reference `src/llm_training/ops/rope_op.py:4-20`
(rotate_half / apply_rope) and the Triton-fused `ops/liger_kernel/rope_op.py`.
Uses the HF "half rotation" layout: cos/sin are `[..., seq, head_dim]` with the
frequency vector duplicated along the last dim.
"""

import jax.numpy as jnp


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    """Rotate the second half of the last dim into the (negated) first half."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def rotate_interleaved(x: jnp.ndarray) -> jnp.ndarray:
    """GPT-J/Cohere pairing: rotate within (even, odd) pairs of the last dim
    — (x0, x1) -> (-x1, x0)."""
    x2 = x.reshape(*x.shape[:-1], -1, 2)
    rot = jnp.stack([-x2[..., 1], x2[..., 0]], axis=-1)
    return rot.reshape(x.shape)


def apply_rope(
    q: jnp.ndarray,
    k: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    interleaved: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply rotary embedding to q and k.

    q: [batch, seq, num_heads, head_dim] (head axis broadcast-compatible)
    k: [batch, seq, num_kv_heads, head_dim]
    cos/sin: [batch, seq, head_dim] or [seq, head_dim]
    interleaved: Cohere/GPT-J pairing — the caller supplies
    repeat_interleave(freqs, 2) tables and rotation pairs (even, odd) dims
    instead of (i, i + head_dim/2)

    cos/sin are computed in fp32 by the rotary cache (see rope_utils) and cast
    to the activation dtype here, matching the reference's precision choice
    (`models/llama/llama_model.py:367-387`).
    """
    if cos.ndim == 2:
        cos = cos[None]
        sin = sin[None]
    # -> [batch, seq, 1, head_dim] to broadcast over heads; cast the fp32
    # tables to each tensor's dtype independently (no double rounding when
    # q and k dtypes differ).
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    rotate = rotate_interleaved if interleaved else rotate_half
    q_rot = q * cos.astype(q.dtype) + rotate(q) * sin.astype(q.dtype)
    k_rot = k * cos.astype(k.dtype) + rotate(k) * sin.astype(k.dtype)
    return q_rot, k_rot
