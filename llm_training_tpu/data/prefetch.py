"""Background host->device batch prefetching.

The reference gets transfer/compute overlap for free from torch DataLoader
worker processes + CUDA async H2D (`pin_memory`/`prefetch_factor`,
`base_datamodule_config.py:4-13`). The JAX analogue: a daemon thread runs
the host-side pipeline (collation, numpy) and `jax.device_put` onto the
batch shardings a few steps ahead, so the TPU never waits on the host
between steps. Depth 2 is the classic double buffer."""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterator

import jax

_SENTINEL = object()


class DevicePrefetcher:
    """Wraps a host-batch iterator; yields `(device_batch, aux)` pairs where
    the batch is already resident on device (placed with `shardings`) and
    `aux = host_aux_fn(host_batch)` (None when no fn is given). `close()`
    stops the worker — the trainer calls it when the fit ends so infinite
    data streams don't leave threads parked behind a full queue."""

    def __init__(
        self,
        batches: Iterator[dict],
        shardings: Any,
        depth: int = 2,
        host_aux_fn: Any | None = None,
        registry: Any | None = None,
    ):
        self._batches = iter(batches)
        self._shardings = shardings
        # host_aux_fn runs on the HOST batch before transfer; its result is
        # yielded alongside the device batch (the trainer counts consumed
        # samples/tokens there — doing it on the device copy would force a
        # blocking sync every step and undo the prefetch overlap)
        self._host_aux_fn = host_aux_fn
        # telemetry (optional): producer-side batch production time vs
        # consumer-side queue waits — the pair that tells whether the input
        # pipeline or the device is the bottleneck (docs/observability.md)
        if registry is None:
            from llm_training_tpu.telemetry import get_registry

            registry = get_registry()
        self._produce_timer = registry.timer("data/produce")
        self._wait_timer = registry.timer("data/host_wait")
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._error: BaseException | None = None
        self._stop = threading.Event()
        self._finished = False
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self) -> None:
        try:
            while True:
                # time successful productions only — the end-of-stream probe
                # must not skew the mean produce latency
                t0 = time.perf_counter()
                try:
                    batch = next(self._batches)
                except StopIteration:
                    break
                aux = self._host_aux_fn(batch) if self._host_aux_fn else None
                placed = (jax.device_put(batch, self._shardings), aux)
                self._produce_timer.add(time.perf_counter() - t0)
                while not self._stop.is_set():
                    try:
                        self._queue.put(placed, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer thread
            self._error = e
        finally:
            # the sentinel must actually arrive (a full queue would drop a
            # put_nowait and leave the consumer blocked forever); close()
            # setting _stop is the only way out of this loop
            while not self._stop.is_set():
                try:
                    self._queue.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def close(self) -> None:
        self._stop.set()
        while True:  # unblock the worker if it is parked on a full queue
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set() or self._finished:
            raise StopIteration
        with self._wait_timer.time():
            item = self._queue.get()
        if item is _SENTINEL:
            self._finished = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item
