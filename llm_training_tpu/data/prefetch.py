"""Background host->device batch prefetching.

The reference gets transfer/compute overlap for free from torch DataLoader
worker processes + CUDA async H2D (`pin_memory`/`prefetch_factor`,
`base_datamodule_config.py:4-13`). The JAX analogue: a daemon thread runs
the host-side pipeline (collation, numpy) and `jax.device_put` onto the
batch shardings a few steps ahead, so the TPU never waits on the host
between steps. Depth 2 is the classic double buffer.

Resilience (docs/resilience.md): transient data-source errors (remote
storage hiccups — OSError and friends) can be retried with backoff before
surfacing (`retries`, default 0 = historical fail-fast), counted in the
`data/retries` registry counter; each successful production feeds an
optional heartbeat so the hang watchdog can tell a stalled input pipeline
from a stalled device. Passing an iterator *factory* instead of a plain
iterator makes retries actually work against generator sources: a
generator closed by an in-flight error cannot be re-pulled (its retry
raises StopIteration), so the retry path rebuilds the stream from the
factory at the current position instead."""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator

import jax

_SENTINEL = object()


class DevicePrefetcher:
    """Wraps a host-batch iterator; yields `(device_batch, aux)` pairs where
    the batch is already resident on device (placed with `shardings`) and
    `aux = host_aux_fn(host_batch)` (None when no fn is given). `close()`
    stops the worker — the trainer calls it when the fit ends so infinite
    data streams don't leave threads parked behind a full queue.

    `batches` is either a plain iterator (historical signature) or a
    factory `Callable[[int], Iterator]` mapping a production offset to an
    iterator positioned at that batch — the trainer passes
    `lambda n: datamodule.train_batches(start_step=start_micro + n, ...)`.
    With a factory, a failed pull rebuilds the stream at the batch being
    retried, so retries survive closed generators."""

    def __init__(
        self,
        batches: Iterator[dict] | Callable[[int], Iterator[dict]],
        shardings: Any,
        depth: int = 2,
        host_aux_fn: Any | None = None,
        registry: Any | None = None,
        retries: int = 0,
        retry_backoff_s: float = 0.5,
        heartbeat: Any | None = None,
    ):
        if callable(batches) and not hasattr(batches, "__next__"):
            self._factory: Callable[[int], Iterator[dict]] | None = batches
            self._batches = iter(self._factory(0))
        else:
            self._factory = None
            self._batches = iter(batches)
        self._stream_dirty = False  # an error may have closed the generator
        self._shardings = shardings
        # hang-watchdog hook: called (no args) after each successful
        # production so a stalled data source is distinguishable from a
        # stalled device in the dump
        self._heartbeat = heartbeat
        from llm_training_tpu.resilience import RetryPolicy

        self._retry_policy = RetryPolicy(
            max_retries=retries, backoff_base_s=retry_backoff_s
        )
        self._produced = 0  # production index (chaos site + retry label)
        self._last_error: BaseException | None = None
        self._last_pull_s = 0.0  # successful pull time of the newest batch
        # host_aux_fn runs on the HOST batch before transfer; its result is
        # yielded alongside the device batch (the trainer counts consumed
        # samples/tokens there — doing it on the device copy would force a
        # blocking sync every step and undo the prefetch overlap)
        self._host_aux_fn = host_aux_fn
        # telemetry (optional): producer-side batch production time vs
        # consumer-side queue waits — the pair that tells whether the input
        # pipeline or the device is the bottleneck (docs/observability.md)
        if registry is None:
            from llm_training_tpu.telemetry import get_registry

            registry = get_registry()
        self._produce_timer = registry.timer("data/produce")
        self._wait_timer = registry.timer("data/host_wait")
        self._retry_counter = registry.counter("data/retries")
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, depth))
        # lint: allow(race-unguarded-shared): single-writer handoff — only the worker assigns _error, and the consumer reads it strictly after the queue SENTINEL the same worker enqueues later; the Queue's lock orders write-then-read
        self._error: BaseException | None = None
        self._stop = threading.Event()
        self._finished = False
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _produce_one(self, attempt: int) -> dict:
        """One data-source pull. The chaos hook sits BEFORE the underlying
        `next`, so an injected fault leaves the source untouched and the
        retry really re-pulls the same batch. With a plain iterator, a
        generator that raised from inside cannot be resumed (its retry
        raises StopIteration), so real transient errors are only retryable
        when the source itself is (remote readers are) — the `_last_error`
        bookkeeping keeps a closed-by-error generator from masquerading as
        a clean end of stream: the ORIGINAL transient error surfaces once
        the retries exhaust. With a FACTORY, a retry after any error
        rebuilds the stream at the batch being retried instead, so even
        generator sources retry for real."""
        from llm_training_tpu.resilience import chaos_point

        if self._stream_dirty and self._factory is not None:
            # the previous attempt's error may have closed a generator
            # mid-pull; rebuild positioned at the batch being retried
            self._batches = iter(self._factory(self._produced))
            self._stream_dirty = False
        t0 = time.perf_counter()
        try:
            chaos_point("data", step=self._produced)
            batch = next(self._batches)
        except StopIteration:
            if attempt > 0 and self._last_error is not None:
                raise self._last_error
            raise
        except Exception as e:
            self._last_error = e
            self._stream_dirty = True
            raise
        self._last_error = None
        self._stream_dirty = False
        # the successful attempt's pull time only — failed attempts and
        # retry backoff must not skew the produce latency (they are visible
        # as data/retries instead)
        self._last_pull_s = time.perf_counter() - t0
        return batch

    def _worker(self) -> None:
        from llm_training_tpu.resilience import retry_call

        try:
            while True:
                # time successful productions only — the end-of-stream probe,
                # failed attempts, and retry backoff must not skew the mean
                # produce latency (the pull part comes from _produce_one)
                try:
                    batch = retry_call(
                        self._produce_one,
                        self._retry_policy,
                        label=f"data source (batch {self._produced})",
                        counter=self._retry_counter,
                    )
                except StopIteration:
                    break
                self._produced += 1
                t0 = time.perf_counter()
                aux = self._host_aux_fn(batch) if self._host_aux_fn else None
                # lint: allow(thread-jax-free): this worker IS the sanctioned device-work thread — overlapping H2D transfer with the step is its entire job, coordinated through a bounded queue (contracts.THREAD_JAX_FREE_WHY)
                placed = (jax.device_put(batch, self._shardings), aux)
                self._produce_timer.add(
                    self._last_pull_s + time.perf_counter() - t0
                )
                if self._heartbeat is not None:
                    self._heartbeat()
                while not self._stop.is_set():
                    try:
                        self._queue.put(placed, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer thread
            self._error = e
        finally:
            # the sentinel must actually arrive (a full queue would drop a
            # put_nowait and leave the consumer blocked forever); close()
            # setting _stop is the only way out of this loop
            while not self._stop.is_set():
                try:
                    self._queue.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def close(self) -> None:
        self._stop.set()
        while True:  # unblock the worker if it is parked on a full queue
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set() or self._finished:
            raise StopIteration
        with self._wait_timer.time():
            item = self._queue.get()
        if item is _SENTINEL:
            self._finished = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item
