"""HF-datasets-backed pipeline with fingerprint-stable caching.

Capability parity: reference `data/hf_based/hf_based_datamodule.py:26-240` —
`datasets.load_dataset` wrapper, seed-42 train/val split, save/load of
pre-processed data, deterministic `.map` fingerprinting that includes a
tokenizer-content hash (so cache hits survive process restarts,
`hash_tokenizer` `:89-97`), and cache enable/disable.
"""

from __future__ import annotations

import hashlib
import inspect
import logging
from pathlib import Path
from typing import Any, Callable

import datasets
from datasets import Dataset, DatasetDict
from datasets.fingerprint import Hasher

from llm_training_tpu.data.base import BaseDataModule, BaseDataModuleConfig

logger = logging.getLogger(__name__)


def hash_tokenizer(tokenizer: Any) -> str:
    """Content hash of a tokenizer (vocab + config), stable across processes
    (reference `hf_based_datamodule.py:89-97` hashes the backing files)."""
    h = hashlib.sha256()
    h.update(str(type(tokenizer)).encode())
    if hasattr(tokenizer, "_tokenizer"):  # fast tokenizer: serialized state
        h.update(tokenizer._tokenizer.to_str().encode())
    else:
        h.update(repr(sorted(tokenizer.get_vocab().items())).encode())
    h.update(repr(sorted((tokenizer.special_tokens_map or {}).items())).encode())
    return h.hexdigest()


class HFBasedDataModuleConfig(BaseDataModuleConfig):
    dataset_kwargs: dict | None = None
    num_proc: int | None = None
    enable_cache: bool = True
    cleanup_cache_files: bool = False
    pre_processed_data_path: str | None = None


class HFBasedDataModule(BaseDataModule):
    config: HFBasedDataModuleConfig

    # ------------------------------------------------------------ pipeline

    def load_data(self) -> DatasetDict:
        kwargs = self.config.dataset_kwargs or {}
        dataset = datasets.load_dataset(**kwargs)
        if isinstance(dataset, Dataset):
            dataset = DatasetDict(train=dataset)
        return dataset

    def pre_process_data(self, dataset_dict: DatasetDict) -> DatasetDict:
        return dataset_dict

    def post_process_data(self, dataset_dict: DatasetDict) -> DatasetDict:
        return dataset_dict

    def split_data(self, dataset_dict: DatasetDict) -> DatasetDict:
        """seed-42 train/validation split (reference `:55-59`)."""
        split = self.config.validation_split
        if split and "validation" not in dataset_dict:
            train = dataset_dict["train"]
            n_val = int(split) if split >= 1 else max(1, int(len(train) * split))
            parts = train.train_test_split(test_size=n_val, seed=42)
            dataset_dict = DatasetDict(
                {**dataset_dict, "train": parts["train"], "validation": parts["test"]}
            )
        return dataset_dict

    def setup(self) -> None:
        path = self.config.pre_processed_data_path
        if path and Path(path).exists():
            logger.info("loading pre-processed data from %s", path)
            dataset_dict = datasets.load_from_disk(path)
        else:
            dataset_dict = self.load_data()
            if self.config.cleanup_cache_files:
                # before any processing (reference hf_based_datamodule.py:49-50)
                # so we never delete cache files backing the datasets we
                # are about to create
                dataset_dict.cleanup_cache_files()
            dataset_dict = self.pre_process_data(dataset_dict)
        self.pre_processed_dataset_dict = dataset_dict
        dataset_dict = self.split_data(dataset_dict)
        dataset_dict = self.post_process_data(dataset_dict)
        self.dataset_dict = dataset_dict
        self.train_dataset = dataset_dict.get("train")
        self.val_dataset = dataset_dict.get("validation")

    def save_pre_processed_data(self, path: str | None = None) -> None:
        path = path or self.config.pre_processed_data_path
        if path is None:
            raise ValueError("pre_processed_data_path is required")
        self.pre_processed_dataset_dict.save_to_disk(path)
        logger.info("saved pre-processed data to %s", path)

    # ------------------------------------------------------------ mapping

    def map_dataset_dict(
        self,
        dataset_dict: DatasetDict,
        function: Callable,
        fn_kwargs: dict[str, Any] | None = None,
        remove_columns: bool | list[str] = False,
        **map_kwargs: Any,
    ) -> DatasetDict:
        """`.map` with a deterministic fingerprint: function source +
        hashable kwargs (tokenizers hashed by content), so the datasets cache
        hits across process restarts (reference `map_dataset` `:107-176`)."""
        fn_kwargs = fn_kwargs or {}
        # hash the function's WHOLE module source: helpers called by the map
        # function live beside it, and an edit to any of them must invalidate
        # the cache (hashing only the function's own source would miss them)
        hash_parts = [
            function.__qualname__,
            inspect.getsource(inspect.getmodule(function)),
        ]
        for key in sorted(fn_kwargs):
            value = fn_kwargs[key]
            if hasattr(value, "get_vocab"):
                hash_parts.append(f"{key}=tokenizer:{hash_tokenizer(value)}")
            else:
                hash_parts.append(f"{key}={Hasher.hash(value)}")

        out = {}
        for name, dataset in dataset_dict.items():
            if remove_columns is True:
                map_kwargs["remove_columns"] = dataset.column_names
            elif remove_columns:
                map_kwargs["remove_columns"] = remove_columns
            # per-dataset: includes the resolved remove_columns list
            kwargs_hash = Hasher.hash(
                {k: v for k, v in sorted(map_kwargs.items()) if k != "desc"}
            )
            fingerprint = Hasher.hash([dataset._fingerprint, kwargs_hash] + hash_parts)
            if not self.config.enable_cache:
                fingerprint = None
            out[name] = dataset.map(
                function,
                fn_kwargs=fn_kwargs,
                num_proc=self.config.num_proc if len(dataset) > 1 else None,
                new_fingerprint=fingerprint,
                load_from_cache_file=self.config.enable_cache,
                **map_kwargs,
            )
        return DatasetDict(out)
