"""Chat templates with `{% generation %}` assistant-token masks.

Capability parity: reference `data/chat_templates/` — 9 Jinja2 templates
whose `{% generation %}` tags let `tokenizer.apply_chat_template(...,
return_assistant_tokens_mask=True)` produce exact assistant-token masks.
Written from the public formats of each model family. Loader resolves
name → packaged file → literal template string
(reference `chat_templates/__init__.py:24-37`).
"""

from __future__ import annotations

from pathlib import Path

_TEMPLATE_DIR = Path(__file__).parent


def available_chat_templates() -> list[str]:
    return sorted(p.stem for p in _TEMPLATE_DIR.glob("*.j2"))


def get_chat_template(name_or_template: str) -> str:
    path = _TEMPLATE_DIR / f"{name_or_template}.j2"
    if path.exists():
        return path.read_text()
    if "{" in name_or_template:  # literal jinja template
        return name_or_template
    raise ValueError(
        f"unknown chat template {name_or_template!r}; "
        f"available: {available_chat_templates()}"
    )
