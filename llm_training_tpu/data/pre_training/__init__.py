from llm_training_tpu.data.pre_training.datamodule import (
    PackingMethod,
    PreTrainingDataModule,
    PreTrainingDataModuleConfig,
)
from llm_training_tpu.data.pre_training.collator import PreTrainingDataCollator

__all__ = [
    "PackingMethod",
    "PreTrainingDataModule",
    "PreTrainingDataModuleConfig",
    "PreTrainingDataCollator",
]
