"""Pre-training collator.

Capability parity: reference
`data/pre_training/pre_training_datacollator.py:9-46`: pad-to-longest with
`pad_to_multiple_of`, configurable side, labels masking BOS and padding, one
shared position_ids row (positions run across packed documents, as the
reference does for pre-training; instruction tuning restarts them per doc).
"""

from __future__ import annotations

from typing import Any

import numpy as np


class PreTrainingDataCollator:
    def __init__(self, config: Any, padding_side: str = "right"):
        self.config = config
        self.padding_side = padding_side
        tokenizer = config.tokenizer
        if tokenizer.pad_token_id is None:
            raise ValueError(
                "tokenizer needs a pad token (reference asserts the same, "
                "pre_training_datacollator.py:19)"
            )
        self.pad_token_id = tokenizer.pad_token_id
        self.bos_token_id = tokenizer.bos_token_id

    def _padded_len(self, longest: int) -> int:
        multiple = self.config.pad_to_multiple_of
        if multiple:
            return -(-longest // multiple) * multiple
        return longest

    def __call__(self, examples: list[dict]) -> dict[str, np.ndarray]:
        lengths = [len(e["input_ids"]) for e in examples]
        width = self._padded_len(max(lengths))
        batch = len(examples)

        if self.padding_side == "right":
            from llm_training_tpu import native

            rows = [np.asarray(e["input_ids"], np.int32) for e in examples]
            row_labels = [
                np.where(ids == self.bos_token_id, -100, ids).astype(np.int32)
                if self.bos_token_id is not None
                else ids
                for ids in rows
            ]
            out = native.pad_batch(
                rows,
                [np.asarray(e["segment_ids"], np.int32) for e in examples],
                row_labels,
                width,
                self.pad_token_id,
                restart_positions=False,  # one shared position stream per row
            )
            if out is not None:
                return out

        input_ids = np.full((batch, width), self.pad_token_id, np.int32)
        segment_ids = np.zeros((batch, width), np.int32)
        labels = np.full((batch, width), -100, np.int32)

        position_ids = np.zeros((batch, width), np.int32)
        for row, example in enumerate(examples):
            ids = np.asarray(example["input_ids"], np.int32)
            segs = np.asarray(example["segment_ids"], np.int32)
            sl = slice(0, len(ids)) if self.padding_side == "right" else slice(width - len(ids), width)
            input_ids[row, sl] = ids
            segment_ids[row, sl] = segs
            row_labels = ids.copy()
            if self.bos_token_id is not None:
                row_labels[ids == self.bos_token_id] = -100
            labels[row, sl] = row_labels
            # positions start at 0 at the first real token, whichever side
            # the padding is on (packed documents share one position stream,
            # as the reference's pre-training collator does)
            position_ids[row, sl] = np.arange(len(ids), dtype=np.int32)
        return {
            "input_ids": input_ids,
            "labels": labels,
            "segment_ids": segment_ids,
            "position_ids": position_ids,
        }
