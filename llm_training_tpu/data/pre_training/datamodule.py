"""Pre-training data: tokenize → stride-truncate → pack → sample.

Capability parity: reference
`data/pre_training/pre_training_datamodule.py:23-360`:
- tokenize with BOS/EOS added per document (`:30-59`)
- stride truncation of overlong documents (`:61-83`)
- naive packing: greedy concatenation per source, emitting per-document
  segment ids (`:85-142`; the reference's doc-id `attention_mask` IS our
  `segment_ids` column)
- best-fit-decreasing bin packing per source (`:156-211`)
- per-source sampling with integer + fractional rates, seed 42 (`:278-302`)
- per-source token-count tables (`:312-344`)
"""

from __future__ import annotations

import logging
import math
import random
from enum import Enum
from typing import Any

from datasets import Dataset, DatasetDict, Features, Sequence, Value
from pydantic import ConfigDict, field_validator, model_validator

from llm_training_tpu.data.hf_based import HFBasedDataModule, HFBasedDataModuleConfig
from llm_training_tpu.data.pre_training.collator import PreTrainingDataCollator
from llm_training_tpu.data.tokenizer import resolve_tokenizer

logger = logging.getLogger(__name__)


class PackingMethod(str, Enum):
    NO_PACKING = "no_packing"
    NAIVE_PACKING = "naive_packing"
    BEST_FIT_BIN_PACKING = "best_fit_bin_packing"


class PreTrainingDataModuleConfig(HFBasedDataModuleConfig):
    model_config = ConfigDict(extra="forbid", arbitrary_types_allowed=True)

    tokenizer: Any  # path or PreTrainedTokenizer; resolved by validator
    max_length: int | None = None
    stride: int | None = None
    packing_method: PackingMethod = PackingMethod.NAIVE_PACKING
    sample_rate: dict[str, float] = {}
    pre_processing_batch_size: int = 1000
    pad_to_multiple_of: int | None = None

    @field_validator("tokenizer")
    @classmethod
    def _resolve_tokenizer(cls, value: Any) -> Any:
        return resolve_tokenizer(value)

    @model_validator(mode="after")
    def _validate(self) -> "PreTrainingDataModuleConfig":
        if self.packing_method != PackingMethod.NO_PACKING and self.max_length is None:
            raise ValueError("max_length is required when packing")
        if self.stride is None:
            self.stride = self.max_length
        elif self.max_length is None:
            raise ValueError("stride requires max_length")
        elif self.stride > self.max_length:
            raise ValueError("stride must be <= max_length")
        return self


def _tokenize_batch(batch: dict[str, list], tokenizer: Any) -> dict[str, list]:
    """Each document becomes BOS + tokens + EOS; empty texts are dropped."""
    keep = [i for i, text in enumerate(batch["text"]) if text]
    texts = [batch["text"][i] for i in keep]
    sources = [
        (batch["source"][i] if "source" in batch else "default") for i in keep
    ]
    encoded = tokenizer(
        texts, add_special_tokens=False, return_attention_mask=False
    )["input_ids"]
    # BOS-less (Qwen/GPT-2-style) and EOS-less tokenizers get no sentinel
    prefix = [tokenizer.bos_token_id] if tokenizer.bos_token_id is not None else []
    suffix = [tokenizer.eos_token_id] if tokenizer.eos_token_id is not None else []
    input_ids = [[*prefix, *ids, *suffix] for ids in encoded]
    return {
        "source": sources,
        "input_ids": input_ids,
        "length": [len(ids) for ids in input_ids],
    }


def _truncate_batch(batch: dict[str, list], max_length: int, stride: int) -> dict[str, list]:
    """Split overlong documents into windows starting every `stride` tokens."""
    out = {"source": [], "input_ids": [], "length": []}
    for source, ids in zip(batch["source"], batch["input_ids"]):
        for start in range(0, len(ids), stride):
            window = ids[start : start + max_length]
            out["source"].append(source)
            out["input_ids"].append(window)
            out["length"].append(len(window))
    return out


def _flush(out: dict, source: str, ids: list[int], segs: list[int]) -> None:
    out["source"].append(source)
    out["input_ids"].append(ids)
    out["segment_ids"].append(segs)
    out["length"].append(len(ids))


def _naive_packing(batch: dict[str, list], max_length: int) -> dict[str, list]:
    """Greedy concatenation in arrival order, never mixing sources; rows are
    cut at exactly max_length, documents may span rows. Segment ids restart
    at 1 per row."""
    out = {"source": [], "input_ids": [], "segment_ids": [], "length": []}
    cur_source = None
    cur_ids: list[int] = []
    cur_segs: list[int] = []

    def renumber(segs: list[int]) -> list[int]:
        offset = segs[0] - 1
        return [s - offset for s in segs] if offset else segs

    for source, ids in zip(batch["source"], batch["input_ids"]):
        if source != cur_source and cur_ids:
            _flush(out, cur_source, cur_ids, renumber(cur_segs))
            cur_ids, cur_segs = [], []
        cur_source = source
        next_seg = cur_segs[-1] + 1 if cur_segs else 1
        cur_ids += ids
        cur_segs += [next_seg] * len(ids)
        while len(cur_ids) >= max_length:
            _flush(out, cur_source, cur_ids[:max_length], renumber(cur_segs[:max_length]))
            cur_ids = cur_ids[max_length:]
            cur_segs = cur_segs[max_length:]
    if cur_ids:
        _flush(out, cur_source, cur_ids, renumber(cur_segs))
    return out


def best_fit_bin_packing(capacity: int, lengths: list[int]) -> list[list[int]]:
    """Best-fit: each item goes to the fullest bin it still fits in.

    Dispatches to the native C++ engine (native/packing.cc, std::set-based
    O(n log n)) when available — this is the corpus-preprocessing hot loop,
    run over millions of documents under datasets.map. The Python twin
    produces byte-identical groups (sorted free-space list + bisect; the
    reference's version, `:156-179`, scans every bin per item — O(n^2))."""
    from llm_training_tpu import native

    if len(lengths) >= 64:
        groups = native.bfd_pack(capacity, lengths)
        if groups is not None:
            return groups
    return best_fit_bin_packing_py(capacity, lengths)


def best_fit_bin_packing_py(capacity: int, lengths: list[int]) -> list[list[int]]:
    """Pure-Python best-fit (the native engine's reference semantics,
    including the oversize-item error contract)."""
    import bisect

    for length in lengths:
        if length > capacity or length < 0:
            raise ValueError(f"an item exceeds capacity {capacity}")

    groups: list[list[int]] = []
    spaces: list[tuple[int, int]] = []  # sorted (free_space, bin_index)
    for i, length in enumerate(lengths):
        pos = bisect.bisect_left(spaces, (length, -1))
        if pos < len(spaces):
            free, j = spaces.pop(pos)
            groups[j].append(i)
            bisect.insort(spaces, (free - length, j))
        else:
            groups.append([i])
            bisect.insort(spaces, (capacity - length, len(groups) - 1))
    return groups


def _best_fit_decreasing(batch: dict[str, list], max_length: int) -> dict[str, list]:
    """Sort docs by length descending per source, best-fit into bins; no
    document ever spans rows (unlike naive packing)."""
    out = {"source": [], "input_ids": [], "segment_ids": [], "length": []}
    by_source: dict[str, list[int]] = {}
    for i, source in enumerate(batch["source"]):
        by_source.setdefault(source, []).append(i)
    for source, indices in by_source.items():
        indices = sorted(indices, key=lambda i: batch["length"][i], reverse=True)
        lengths = [batch["length"][i] for i in indices]
        for group in best_fit_bin_packing(max_length, lengths):
            ids: list[int] = []
            segs: list[int] = []
            for doc_num, local_idx in enumerate(group, start=1):
                doc = batch["input_ids"][indices[local_idx]]
                ids += doc
                segs += [doc_num] * len(doc)
            _flush(out, source, ids, segs)
    return out


def _pre_process(
    batch: dict[str, list],
    tokenizer: Any,
    max_length: int | None,
    stride: int | None,
    packing_method: str,
) -> dict[str, list]:
    batch = _tokenize_batch(batch, tokenizer)
    if max_length is not None:
        batch = _truncate_batch(batch, max_length, stride)
    if packing_method == PackingMethod.NAIVE_PACKING:
        batch = _naive_packing(batch, max_length)
    elif packing_method == PackingMethod.BEST_FIT_BIN_PACKING:
        batch = _best_fit_decreasing(batch, max_length)
    else:
        batch = {
            **batch,
            "segment_ids": [[1] * len(ids) for ids in batch["input_ids"]],
        }
    return batch


class PreTrainingDataModule(HFBasedDataModule):
    config: PreTrainingDataModuleConfig

    def __init__(self, config: PreTrainingDataModuleConfig):
        super().__init__(config)
        self.collator = PreTrainingDataCollator(config)

    def pre_process_data(self, dataset_dict: DatasetDict) -> DatasetDict:
        for name, dataset in dataset_dict.items():
            if "source" in dataset.column_names:
                dataset_dict[name] = dataset.sort("source")
        return self.map_dataset_dict(
            dataset_dict,
            _pre_process,
            fn_kwargs=dict(
                tokenizer=self.config.tokenizer,
                max_length=self.config.max_length,
                stride=self.config.stride,
                packing_method=self.config.packing_method.value,
            ),
            batched=True,
            batch_size=self.config.pre_processing_batch_size,
            remove_columns=True,
            features=Features(
                {
                    "source": Value("string"),
                    "input_ids": Sequence(Value("int32")),
                    "segment_ids": Sequence(Value("uint16")),
                    "length": Value("uint32"),
                }
            ),
            desc="Pre-processing data",
        )

    def post_process_data(self, dataset_dict: DatasetDict) -> DatasetDict:
        if "train" in dataset_dict and self.config.sample_rate:
            dataset_dict["train"] = self.sample_data(dataset_dict["train"])
        return dataset_dict

    def sample_data(self, dataset: Dataset) -> Dataset:
        """Integer part replicates the source, fractional part samples it
        (seed 42), matching reference `sample_data` `:278-302`."""
        sample_rate = self.config.sample_rate
        if all(rate == 1.0 for rate in sample_rate.values()):
            return dataset
        by_source: dict[str, list[int]] = {}
        for i, source in enumerate(dataset["source"]):
            by_source.setdefault(source, []).append(i)
        rng = random.Random(42)
        unused = dict(sample_rate)
        selected: list[int] = []
        for source, indices in by_source.items():
            rate = sample_rate.get(source, 1.0)
            unused.pop(source, None)
            frac, integer = math.modf(rate)
            selected += indices * int(integer)
            if frac > 0:
                selected += rng.sample(indices, k=int(len(indices) * frac))
        if unused:
            logger.warning("sample_rate sources not in dataset: %s", sorted(unused))
        return dataset.select(selected)

    def collate(self, examples: list[dict]) -> dict:
        return self.collator(examples)

    def tokens_table(self) -> str:
        """Per-split, per-source token counts (reference `:312-344`)."""
        lines = [f"{'Split':<12} {'Source':<20} {'Tokens':>14}"]
        for name, dataset in self.dataset_dict.items():
            totals: dict[str, int] = {}
            for source, length in zip(dataset["source"], dataset["length"]):
                totals[source] = totals.get(source, 0) + int(length)
            lines.append(f"{name:<12} {'*':<20} {sum(totals.values()):>14,}")
            for source in sorted(totals):
                lines.append(f"{name:<12} {source:<20} {totals[source]:>14,}")
        return "\n".join(lines)
