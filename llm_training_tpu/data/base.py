"""Base datamodule: load → pre-process → split → batches.

Capability parity: reference `data/base_datamodule.py:18-119` +
`base_datamodule_config.py` + `resumable_dataloader.py`. The resume story is
designed differently (and O(1) instead of O(skipped)): batch order is a pure
function of (seed, epoch, step), so resuming is just starting the index
stream at `start_step` — no batches are drawn and thrown away
(reference `resumable_dataloader.py:20-25` skips one by one).
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np
from pydantic import BaseModel, ConfigDict


class BaseDataModuleConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    batch_size: int = 1
    validation_split: float | int | None = None
    seed: int = 42


class BaseDataModule:
    """Subclasses implement `setup()` filling `self.train_dataset` /
    `self.val_dataset` (sequences of examples) and `collate(examples)`."""

    def __init__(self, config: BaseDataModuleConfig):
        self.config = config
        self.train_dataset: Any = None
        self.val_dataset: Any = None

    # -- pipeline hooks (reference base_datamodule.py:89-111)
    def setup(self) -> None:
        raise NotImplementedError

    def collate(self, examples: list[Any]) -> dict[str, np.ndarray]:
        raise NotImplementedError

    # -- batch streams
    def _batch_indices(self, n: int, epoch: int, shuffle: bool) -> np.ndarray:
        order = np.arange(n)
        if shuffle:
            order = np.random.default_rng((self.config.seed, epoch)).permutation(n)
        usable = (n // self.config.batch_size) * self.config.batch_size
        return order[:usable].reshape(-1, self.config.batch_size)

    def steps_per_epoch(self) -> int:
        return len(self.train_dataset) // self.config.batch_size

    def train_batches(self, start_step: int = 0) -> Iterator[dict[str, np.ndarray]]:
        """Infinite shuffled stream; deterministic in (seed, step) so resume
        at `start_step` reproduces the exact post-crash data order."""
        step = 0
        epoch = 0
        while True:
            batches = self._batch_indices(len(self.train_dataset), epoch, shuffle=True)
            for row in batches:
                if step >= start_step:
                    yield self.collate([self.train_dataset[int(i)] for i in row])
                step += 1
            epoch += 1

    def val_batches(self) -> Iterator[dict[str, np.ndarray]]:
        if self.val_dataset is None:
            return
        for row in self._batch_indices(len(self.val_dataset), 0, shuffle=False):
            yield self.collate([self.val_dataset[int(i)] for i in row])
