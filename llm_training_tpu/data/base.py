"""Base datamodule: load → pre-process → split → batches.

Capability parity: reference `data/base_datamodule.py:18-119` +
`base_datamodule_config.py` + `resumable_dataloader.py`. The resume story is
designed differently (and O(1) instead of O(skipped)): batch order is a pure
function of (seed, epoch, step), so resuming is just starting the index
stream at `start_step` — no batches are drawn and thrown away
(reference `resumable_dataloader.py:20-25` skips one by one).
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np
from pydantic import BaseModel, ConfigDict


class BaseDataModuleConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    batch_size: int = 1
    validation_split: float | int | None = None
    seed: int = 42


class BaseDataModule:
    """Subclasses implement `setup()` filling `self.train_dataset` /
    `self.val_dataset` (sequences of examples) and `collate(examples)`."""

    def __init__(self, config: BaseDataModuleConfig):
        self.config = config
        self.train_dataset: Any = None
        self.val_dataset: Any = None

    # -- pipeline hooks (reference base_datamodule.py:89-111)
    def setup(self) -> None:
        raise NotImplementedError

    def collate(self, examples: list[Any]) -> dict[str, np.ndarray]:
        raise NotImplementedError

    # -- batch streams
    def _batch_indices(self, n: int, epoch: int, shuffle: bool) -> np.ndarray:
        order = np.arange(n)
        if shuffle:
            order = np.random.default_rng((self.config.seed, epoch)).permutation(n)
        usable = (n // self.config.batch_size) * self.config.batch_size
        return order[:usable].reshape(-1, self.config.batch_size)

    def steps_per_epoch(self) -> int:
        return len(self.train_dataset) // self.config.batch_size

    def train_batches(
        self, start_step: int = 0, skip_list: Any | None = None
    ) -> Iterator[dict[str, np.ndarray]]:
        """Infinite shuffled stream; deterministic in (seed, step) so resume
        at `start_step` reproduces the exact post-crash data order.

        `skip_list` (a `resilience.DataSkipList`, passed by the trainer when
        rollback-and-skip recovery is enabled) makes the stream a pure
        function of (seed, step, windows, reserve) instead: the LAST
        `skip_list.reserve` batches of every epoch permutation are held out
        as a replacement pool, and a step inside a poisoned window serves
        the next reserved batch instead of its own. No batch is served
        twice and none is lost (until the pool is exhausted, which wraps
        with a warning), so a resumed run — or a clean run configured with
        the same windows — replays the identical global batch sequence.
        With `skip_list=None` the stream is byte-identical to before."""
        step = 0
        epoch = 0
        reserve = int(getattr(skip_list, "reserve", 0)) if skip_list is not None else 0
        while True:
            batches = self._batch_indices(len(self.train_dataset), epoch, shuffle=True)
            if reserve:
                if reserve >= len(batches):
                    raise ValueError(
                        f"recovery reserve ({reserve} batches/epoch) consumes "
                        f"the whole epoch ({len(batches)} batches); shrink "
                        "recovery.reserve_batches or the skip budget"
                    )
                served, pool = batches[:-reserve], batches[-reserve:]
            else:
                served, pool = batches, batches[:0]
            epoch_start = step
            for row in served:
                if skip_list is not None and skip_list.is_skipped(step):
                    replacement = skip_list.replacement_row(step, epoch_start, pool)
                    row = replacement if replacement is not None else row
                if step >= start_step:
                    yield self.collate([self.train_dataset[int(i)] for i in row])
                step += 1
            epoch += 1

    def replica_batches(
        self,
        dp_rank: int,
        dp_size: int,
        start_step: int = 0,
        skip_list: Any | None = None,
    ) -> Iterator[dict[str, np.ndarray]]:
        """Replica `dp_rank`'s share of the GLOBAL stream: rows
        [rank*stride, (rank+1)*stride) of every `train_batches` batch,
        stride = batch_size // dp_size.

        This is the elastic-resume data contract (docs/resilience.md#elastic):
        the (seed, step) → sample mapping lives entirely in the global
        stream, and a replica's view is a pure slice of it — so
        concatenating the dp_size replica streams row-wise reconstructs the
        global stream EXACTLY, for any dp_size dividing batch_size. Scaling
        data-parallel replicas up or down between segments changes only the
        stride, never which samples step k serves; skip windows and the
        start cursor compose unchanged because they are applied to the
        global stream before the slice."""
        if dp_size < 1:
            raise ValueError(f"dp_size must be >= 1, got {dp_size}")
        if not 0 <= dp_rank < dp_size:
            raise ValueError(f"dp_rank {dp_rank} outside [0, {dp_size})")
        if self.config.batch_size % dp_size != 0:
            raise ValueError(
                f"global batch size {self.config.batch_size} is not divisible "
                f"by dp_size {dp_size}; the per-replica stride must be exact"
            )
        stride = self.config.batch_size // dp_size
        lo, hi = dp_rank * stride, (dp_rank + 1) * stride
        for batch in self.train_batches(start_step=start_step, skip_list=skip_list):
            yield {key: value[lo:hi] for key, value in batch.items()}

    def val_batches(self) -> Iterator[dict[str, np.ndarray]]:
        if self.val_dataset is None:
            return
        for row in self._batch_indices(len(self.val_dataset), 0, shuffle=False):
            yield self.collate([self.val_dataset[int(i)] for i in row])
