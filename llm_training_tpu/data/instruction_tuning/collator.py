"""Instruction-tuning collator.

Capability parity: reference
`data/instruction_tuning/instruction_tuning_datacollator.py:12-72`:
packing-aware padding where position_ids restart at 0 for each packed
document (`:45-55`) and per-document segment ids are preserved. Labels come
pre-masked (-100 outside assistant tokens) from the datamodule.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class InstructionTuningDataCollator:
    def __init__(self, config: Any, padding_side: str = "right"):
        self.config = config
        tokenizer = config.tokenizer
        if tokenizer.pad_token_id is None:
            raise ValueError("tokenizer needs a pad token")
        self.pad_token_id = tokenizer.pad_token_id
        self.padding_side = padding_side

    def _padded_len(self, longest: int) -> int:
        multiple = self.config.pad_to_multiple_of
        if multiple:
            return -(-longest // multiple) * multiple
        return longest

    def __call__(self, examples: list[dict]) -> dict[str, np.ndarray]:
        width = self._padded_len(max(len(e["input_ids"]) for e in examples))
        batch = len(examples)

        if self.padding_side == "right":
            from llm_training_tpu import native

            segs_rows = [np.asarray(e["segment_ids"], np.int32) for e in examples]
            # the native kernel restarts positions on segment-id CHANGE; that
            # equals the Python per-unique-segment rule only for monotonic ids
            # (the only thing our packers emit) — fall back otherwise
            if all(np.all(np.diff(s) >= 0) for s in segs_rows):
                out = native.pad_batch(
                    [np.asarray(e["input_ids"], np.int32) for e in examples],
                    segs_rows,
                    [np.asarray(e["labels"], np.int32) for e in examples],
                    width,
                    self.pad_token_id,
                    restart_positions=True,
                )
                if out is not None:
                    return out

        input_ids = np.full((batch, width), self.pad_token_id, np.int32)
        labels = np.full((batch, width), -100, np.int32)
        segment_ids = np.zeros((batch, width), np.int32)
        position_ids = np.zeros((batch, width), np.int32)

        for row, example in enumerate(examples):
            n = len(example["input_ids"])
            sl = slice(0, n) if self.padding_side == "right" else slice(width - n, width)
            input_ids[row, sl] = example["input_ids"]
            labels[row, sl] = example["labels"]
            segs = np.asarray(example["segment_ids"], np.int32)
            segment_ids[row, sl] = segs
            # positions restart at each packed document boundary
            positions = np.arange(n, dtype=np.int32)
            for seg in np.unique(segs):
                mask = segs == seg
                positions[mask] -= positions[mask][0]
            position_ids[row, sl] = positions

        return {
            "input_ids": input_ids,
            "labels": labels,
            "segment_ids": segment_ids,
            "position_ids": position_ids,
        }
