from llm_training_tpu.data.instruction_tuning.datamodule import (
    InstructionTuningDataModule,
    InstructionTuningDataModuleConfig,
    OverlongHandlingMethod,
    PackingMethod,
)
from llm_training_tpu.data.instruction_tuning.collator import InstructionTuningDataCollator

__all__ = [
    "InstructionTuningDataModule",
    "InstructionTuningDataModuleConfig",
    "InstructionTuningDataCollator",
    "OverlongHandlingMethod",
    "PackingMethod",
]
