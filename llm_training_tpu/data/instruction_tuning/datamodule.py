"""Instruction tuning data: chat template → assistant-mask labels → pack.

Capability parity: reference
`data/instruction_tuning/instruction_tuning_datamodule.py:17-202`:
- chat-template application with `{% generation %}` assistant masks →
  labels (`:31-78`); requires tokenizers >= 0.20.1 (`:24-28`)
- seeded random default-system-prompt injection (`:47-55`)
- drop-or-truncate overlong handling (`:80-100`)
- GROUP_BY_LENGTH packing: length-sorted best-fit grouping with per-document
  segment ids; documents never span rows (`:102-145`)

Expected example format: `{"messages": [{"role": ..., "content": ...}, ...]}`.
"""

from __future__ import annotations

import logging
import random
from enum import Enum
from typing import Any

import tokenizers
from datasets import DatasetDict, Features, Sequence, Value
from packaging.version import Version
from pydantic import ConfigDict, field_validator, model_validator

from llm_training_tpu.data.chat_templates import get_chat_template
from llm_training_tpu.data.hf_based import HFBasedDataModule, HFBasedDataModuleConfig
from llm_training_tpu.data.instruction_tuning.collator import InstructionTuningDataCollator
from llm_training_tpu.data.pre_training.datamodule import best_fit_bin_packing
from llm_training_tpu.data.tokenizer import resolve_tokenizer

logger = logging.getLogger(__name__)


class OverlongHandlingMethod(str, Enum):
    DROP = "drop"
    TRUNCATE = "truncate"


class PackingMethod(str, Enum):
    NO_PACKING = "no_packing"
    GROUP_BY_LENGTH = "group_by_length"


class InstructionTuningDataModuleConfig(HFBasedDataModuleConfig):
    model_config = ConfigDict(extra="forbid", arbitrary_types_allowed=True)

    tokenizer: Any
    chat_template: str | None = None
    max_length: int | None = None
    overlong_handling_method: OverlongHandlingMethod = OverlongHandlingMethod.DROP
    packing_method: PackingMethod = PackingMethod.NO_PACKING
    pad_to_multiple_of: int | None = None
    add_default_system_prompt_rate: float | None = None
    default_system_prompt: str | None = None

    @field_validator("tokenizer")
    @classmethod
    def _resolve_tokenizer(cls, value: Any) -> Any:
        return resolve_tokenizer(value)

    @field_validator("chat_template")
    @classmethod
    def _resolve_template(cls, value: str | None) -> str | None:
        return get_chat_template(value) if value is not None else None

    @model_validator(mode="after")
    def _validate(self) -> "InstructionTuningDataModuleConfig":
        if Version(tokenizers.__version__) < Version("0.20.1"):
            # reference gate `:24-28`: older tokenizers mis-mask llama-3 prompts
            raise RuntimeError("tokenizers >= 0.20.1 required for assistant masks")
        if self.default_system_prompt and self.add_default_system_prompt_rate is None:
            raise ValueError(
                "add_default_system_prompt_rate is required with default_system_prompt"
            )
        if self.packing_method == PackingMethod.GROUP_BY_LENGTH and self.max_length is None:
            raise ValueError("max_length is required for group_by_length packing")
        return self


def _apply_template_and_tokenize(
    batch: dict[str, list],
    indices: list[int],
    tokenizer: Any,
    chat_template: str | None,
    default_system_prompt: str | None,
    add_rate: float | None,
    seed: int,
) -> dict[str, list]:
    conversations = []
    for idx, messages in zip(indices, batch["messages"]):
        messages = list(messages)
        has_system = any(m["role"] == "system" for m in messages)
        if default_system_prompt and not has_system:
            # per-example seeded draw: stable across runs and num_proc shards
            if random.Random(f"{seed}-{idx}").random() < add_rate:
                messages.insert(0, {"role": "system", "content": default_system_prompt})
        conversations.append(messages)

    encoded = tokenizer.apply_chat_template(
        conversations,
        chat_template=chat_template,
        return_dict=True,
        return_assistant_tokens_mask=True,
        tokenizer_kwargs=dict(return_attention_mask=False, verbose=False),
    )
    out = {"input_ids": [], "labels": [], "length": []}
    for input_ids, mask in zip(encoded["input_ids"], encoded["assistant_masks"]):
        out["input_ids"].append(input_ids)
        out["labels"].append(
            [t if m == 1 else -100 for t, m in zip(input_ids, mask)]
        )
        out["length"].append(len(input_ids))
    return out


def _handle_overlong(batch: dict[str, list], max_length: int, method: str) -> dict[str, list]:
    if method == OverlongHandlingMethod.DROP:
        keep = [i for i, n in enumerate(batch["length"]) if n <= max_length]
        return {k: [v[i] for i in keep] for k, v in batch.items()}
    return {
        "input_ids": [ids[:max_length] for ids in batch["input_ids"]],
        "labels": [l[:max_length] for l in batch["labels"]],
        "length": [min(n, max_length) for n in batch["length"]],
    }


def _group_by_length_packing(batch: dict[str, list], max_length: int) -> dict[str, list]:
    indices = sorted(range(len(batch["length"])), key=batch["length"].__getitem__, reverse=True)
    lengths = [batch["length"][i] for i in indices]
    out = {"input_ids": [], "labels": [], "segment_ids": [], "length": []}
    for group in best_fit_bin_packing(max_length, lengths):
        ids: list[int] = []
        labels: list[int] = []
        segs: list[int] = []
        for doc_num, local in enumerate(group, start=1):
            example = indices[local]
            ids += batch["input_ids"][example]
            labels += batch["labels"][example]
            segs += [doc_num] * batch["length"][example]
        out["input_ids"].append(ids)
        out["labels"].append(labels)
        out["segment_ids"].append(segs)
        out["length"].append(len(ids))
    return out


def _add_trivial_segments(batch: dict[str, list]) -> dict[str, list]:
    return {**batch, "segment_ids": [[1] * n for n in batch["length"]]}


class InstructionTuningDataModule(HFBasedDataModule):
    config: InstructionTuningDataModuleConfig

    def __init__(self, config: InstructionTuningDataModuleConfig):
        super().__init__(config)
        self.collator = InstructionTuningDataCollator(config)

    def pre_process_data(self, dataset_dict: DatasetDict) -> DatasetDict:
        cfg = self.config
        dataset_dict = self.map_dataset_dict(
            dataset_dict,
            _apply_template_and_tokenize,
            fn_kwargs=dict(
                tokenizer=cfg.tokenizer,
                chat_template=cfg.chat_template,
                default_system_prompt=cfg.default_system_prompt,
                add_rate=cfg.add_default_system_prompt_rate,
                seed=cfg.seed,
            ),
            batched=True,
            with_indices=True,
            remove_columns=True,
            desc="Applying chat template",
        )
        if cfg.max_length is not None:
            dataset_dict = self.map_dataset_dict(
                dataset_dict,
                _handle_overlong,
                fn_kwargs=dict(
                    max_length=cfg.max_length,
                    method=cfg.overlong_handling_method.value,
                ),
                batched=True,
                desc="Handling overlong examples",
            )
        packer = (
            _group_by_length_packing
            if cfg.packing_method == PackingMethod.GROUP_BY_LENGTH
            else _add_trivial_segments
        )
        dataset_dict = self.map_dataset_dict(
            dataset_dict,
            packer,
            fn_kwargs=(
                dict(max_length=cfg.max_length)
                if cfg.packing_method == PackingMethod.GROUP_BY_LENGTH
                else {}
            ),
            batched=True,
            batch_size=10000,
            remove_columns=True,
            features=Features(
                {
                    "input_ids": Sequence(Value("int32")),
                    "labels": Sequence(Value("int32")),
                    "segment_ids": Sequence(Value("uint16")),
                    "length": Value("uint32"),
                }
            ),
            desc="Packing",
        )
        return dataset_dict

    def collate(self, examples: list[dict]) -> dict:
        return self.collator(examples)
