"""Seeded synthetic token stream for smoke/perf runs.

Capability parity: reference `data/dummy/` (`dummy_datamodule.py:10`,
`dummy_dataset.py:9-33`): deterministic tokens sized by `num_samples` or
`num_tokens`. The reference broadcasts the seed from rank 0
(`dummy_datamodule.py:16-19`); in single-program SPMD every host computes the
same stream from the same config seed, so no broadcast exists.
"""

from __future__ import annotations

import numpy as np

from llm_training_tpu.data.base import BaseDataModule, BaseDataModuleConfig


class DummyDataModuleConfig(BaseDataModuleConfig):
    vocab_size: int = 32000
    max_length: int = 2048
    num_samples: int | None = None
    num_tokens: int | None = None


class DummyDataModule(BaseDataModule):
    config: DummyDataModuleConfig

    def __init__(self, config: DummyDataModuleConfig):
        super().__init__(config)

    def setup(self) -> None:
        cfg = self.config
        if cfg.num_samples is None and cfg.num_tokens is None:
            raise ValueError("one of num_samples / num_tokens is required")
        n = cfg.num_samples if cfg.num_samples is not None else -(-cfg.num_tokens // cfg.max_length)
        rng = np.random.default_rng(cfg.seed)
        self.train_dataset = rng.integers(
            0, cfg.vocab_size, size=(n, cfg.max_length), dtype=np.int32
        )
        if cfg.validation_split:
            n_val = (
                int(cfg.validation_split)
                if cfg.validation_split >= 1
                else max(1, int(n * cfg.validation_split))
            )
            self.val_dataset = self.train_dataset[:n_val]
            self.train_dataset = self.train_dataset[n_val:]

    def collate(self, examples: list[np.ndarray]) -> dict[str, np.ndarray]:
        input_ids = np.stack(examples)
        batch, seq = input_ids.shape
        return {
            "input_ids": input_ids,
            "labels": input_ids.copy(),
            "segment_ids": np.ones((batch, seq), np.int32),
            "position_ids": np.broadcast_to(np.arange(seq, dtype=np.int32), (batch, seq)).copy(),
        }
