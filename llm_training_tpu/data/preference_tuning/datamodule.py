"""Preference-pair data (DPO/ORPO).

Capability parity: reference
`data/preference_tuning/preference_tuning_datamodule.py:16-150`:
`{prompt, chosen, rejected}` → two tokenized streams with assistant-mask
labels (`:29-92`), dropping pairs whose longer side exceeds max_length
(`:94-104`).
"""

from __future__ import annotations

from typing import Any

from datasets import DatasetDict, Features, Sequence, Value
from pydantic import ConfigDict, field_validator

from llm_training_tpu.data.chat_templates import get_chat_template
from llm_training_tpu.data.hf_based import HFBasedDataModule, HFBasedDataModuleConfig
from llm_training_tpu.data.preference_tuning.collator import PreferenceTuningDataCollator
from llm_training_tpu.data.tokenizer import resolve_tokenizer


class PreferenceTuningDataModuleConfig(HFBasedDataModuleConfig):
    model_config = ConfigDict(extra="forbid", arbitrary_types_allowed=True)

    tokenizer: Any
    chat_template: str | None = None
    max_length: int | None = None
    pad_to_multiple_of: int | None = None

    @field_validator("tokenizer")
    @classmethod
    def _resolve_tokenizer(cls, value: Any) -> Any:
        return resolve_tokenizer(value)

    @field_validator("chat_template")
    @classmethod
    def _resolve_template(cls, value: str | None) -> str | None:
        return get_chat_template(value) if value is not None else None


def _tokenize_pairs(
    batch: dict[str, list], tokenizer: Any, chat_template: str | None
) -> dict[str, list]:
    out: dict[str, list] = {}
    for side in ("chosen", "rejected"):
        conversations = [
            [
                {"role": "user", "content": prompt},
                {"role": "assistant", "content": answer},
            ]
            for prompt, answer in zip(batch["prompt"], batch[side])
        ]
        encoded = tokenizer.apply_chat_template(
            conversations,
            chat_template=chat_template,
            return_dict=True,
            return_assistant_tokens_mask=True,
            tokenizer_kwargs=dict(return_attention_mask=False, verbose=False),
        )
        out[f"{side}_input_ids"] = encoded["input_ids"]
        out[f"{side}_labels"] = [
            [t if m == 1 else -100 for t, m in zip(ids, mask)]
            for ids, mask in zip(encoded["input_ids"], encoded["assistant_masks"])
        ]
        out[f"{side}_length"] = [len(ids) for ids in encoded["input_ids"]]
    return out


def _drop_overlong(batch: dict[str, list], max_length: int) -> dict[str, list]:
    keep = [
        i
        for i in range(len(batch["chosen_length"]))
        if max(batch["chosen_length"][i], batch["rejected_length"][i]) <= max_length
    ]
    return {k: [v[i] for i in keep] for k, v in batch.items()}


class PreferenceTuningDataModule(HFBasedDataModule):
    config: PreferenceTuningDataModuleConfig

    def __init__(self, config: PreferenceTuningDataModuleConfig):
        super().__init__(config)
        self.collator = PreferenceTuningDataCollator(config)

    def pre_process_data(self, dataset_dict: DatasetDict) -> DatasetDict:
        cfg = self.config
        features = Features(
            {
                f"{side}_{field}": (
                    Sequence(Value("int32")) if field != "length" else Value("uint32")
                )
                for side in ("chosen", "rejected")
                for field in ("input_ids", "labels", "length")
            }
        )
        dataset_dict = self.map_dataset_dict(
            dataset_dict,
            _tokenize_pairs,
            fn_kwargs=dict(tokenizer=cfg.tokenizer, chat_template=cfg.chat_template),
            batched=True,
            remove_columns=True,
            features=features,
            desc="Tokenizing preference pairs",
        )
        if cfg.max_length is not None:
            dataset_dict = self.map_dataset_dict(
                dataset_dict,
                _drop_overlong,
                fn_kwargs=dict(max_length=cfg.max_length),
                batched=True,
                desc="Dropping overlong pairs",
            )
        return dataset_dict

    def collate(self, examples: list[dict]) -> dict:
        return self.collator(examples)
