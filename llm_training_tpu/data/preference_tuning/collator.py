"""Preference-pair collator.

Capability parity: reference
`data/preference_tuning/preference_tuning_datacollator.py:12-69`: pads the
chosen/rejected sextuple and adds position_ids. Both sides pad to one common
width so the DPO objective can run them as a single stacked forward.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class PreferenceTuningDataCollator:
    def __init__(self, config: Any, padding_side: str = "right"):
        self.config = config
        tokenizer = config.tokenizer
        if tokenizer.pad_token_id is None:
            raise ValueError("tokenizer needs a pad token")
        self.pad_token_id = tokenizer.pad_token_id
        self.padding_side = padding_side

    def __call__(self, examples: list[dict]) -> dict[str, np.ndarray]:
        longest = max(
            max(e["chosen_length"], e["rejected_length"]) for e in examples
        )
        multiple = self.config.pad_to_multiple_of
        width = -(-longest // multiple) * multiple if multiple else longest
        batch = len(examples)

        out: dict[str, np.ndarray] = {}
        for side in ("chosen", "rejected"):
            input_ids = np.full((batch, width), self.pad_token_id, np.int32)
            labels = np.full((batch, width), -100, np.int32)
            segment_ids = np.zeros((batch, width), np.int32)
            position_ids = np.zeros((batch, width), np.int32)
            for row, example in enumerate(examples):
                n = example[f"{side}_length"]
                sl = slice(0, n) if self.padding_side == "right" else slice(width - n, width)
                input_ids[row, sl] = example[f"{side}_input_ids"]
                labels[row, sl] = example[f"{side}_labels"]
                segment_ids[row, sl] = 1
                position_ids[row, sl] = np.arange(n, dtype=np.int32)
            out[f"{side}_input_ids"] = input_ids
            out[f"{side}_labels"] = labels
            out[f"{side}_segment_ids"] = segment_ids
            out[f"{side}_position_ids"] = position_ids
        return out
