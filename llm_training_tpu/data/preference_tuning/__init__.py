from llm_training_tpu.data.preference_tuning.datamodule import (
    PreferenceTuningDataModule,
    PreferenceTuningDataModuleConfig,
)
from llm_training_tpu.data.preference_tuning.collator import PreferenceTuningDataCollator

__all__ = [
    "PreferenceTuningDataModule",
    "PreferenceTuningDataModuleConfig",
    "PreferenceTuningDataCollator",
]
