"""Data pipeline.

Capability parity: reference `src/llm_training/data/` — base datamodule +
collator, HF-datasets-based preprocessing with stable caching, pre-training /
instruction-tuning / preference-tuning modules, packing (naive, best-fit-
decreasing, group-by-length), chat templates with assistant masks, dummy
synthetic data, and resumable loading.

Batches are numpy dicts with `input_ids`, `labels`, `position_ids` and
`segment_ids` (the reference's document-id attention masks,
`attention_op.py:286-302` — 0 = padding, 1..N = packed docs).
"""

from llm_training_tpu.data.base import BaseDataModule, BaseDataModuleConfig
from llm_training_tpu.data.dummy import DummyDataModule, DummyDataModuleConfig
from llm_training_tpu.data.hf_based import HFBasedDataModule, HFBasedDataModuleConfig
from llm_training_tpu.data.pre_training import (
    PreTrainingDataModule,
    PreTrainingDataModuleConfig,
)
from llm_training_tpu.data.instruction_tuning import (
    InstructionTuningDataModule,
    InstructionTuningDataModuleConfig,
)
from llm_training_tpu.data.preference_tuning import (
    PreferenceTuningDataModule,
    PreferenceTuningDataModuleConfig,
)

__all__ = [
    "BaseDataModule",
    "BaseDataModuleConfig",
    "DummyDataModule",
    "DummyDataModuleConfig",
    "HFBasedDataModule",
    "HFBasedDataModuleConfig",
    "PreTrainingDataModule",
    "PreTrainingDataModuleConfig",
    "InstructionTuningDataModule",
    "InstructionTuningDataModuleConfig",
    "PreferenceTuningDataModule",
    "PreferenceTuningDataModuleConfig",
]
