"""Tokenizer resolution / factory.

Capability parity: reference `lightning/cli/utils.py:7-22` (`HFTokenizer`
jsonargparse factory: path + pad_token + padding_side). In YAML configs the
tokenizer is a string path or a `{path, pad_token, padding_side}` dict.
"""

from __future__ import annotations

from typing import Any


def resolve_tokenizer(value: Any) -> Any:
    if hasattr(value, "get_vocab"):
        return value
    from transformers import AutoTokenizer

    if isinstance(value, str):
        return AutoTokenizer.from_pretrained(value)
    if isinstance(value, dict):
        kwargs = dict(value)
        path = kwargs.pop("path")
        pad_token = kwargs.pop("pad_token", None)
        tokenizer = AutoTokenizer.from_pretrained(path, **kwargs)
        if pad_token is not None:
            tokenizer.pad_token = pad_token
        return tokenizer
    raise TypeError(f"cannot resolve tokenizer from {type(value)}")
