"""llm-training-tpu: a TPU-native (JAX/XLA/Pallas/pjit) LLM training framework.

A from-scratch re-design of the capabilities of cchou0519/LLM-Training
(full-parameter pre-training / instruction tuning / DPO / ORPO of Llama- and
Phi-3-family models) built TPU-first:

- single-program SPMD over a `jax.sharding.Mesh` (data / fsdp / tensor / sequence axes)
- GSPMD-sharded parameters (ZeRO-3 semantics), tensor + sequence parallelism via
  logical-axis sharding rules, ring attention for long context
- Pallas TPU kernels for the hot ops (flash attention with segment-id packing,
  fused-linear-cross-entropy) with XLA fallbacks
- optax optimizers (fp32 master state over bf16 compute), orbax checkpoints,
  HF checkpoint round-tripping
"""

__version__ = "0.1.0"
