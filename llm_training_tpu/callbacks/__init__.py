"""Trainer callbacks: observability and run management.

Capability parity: the reference's Lightning callbacks/loggers layer
(SURVEY.md §2.7) — `TrainingTimeEstimator`
(`lightning/callbacks/training_time_estimator.py:12`), `OutputRedirection`
(`lightning/callbacks/output_redirection.py:13`), `WandbLogger`
(`lightning/loggers/wandb.py:10`) — plus TPU-native additions the reference
lacks: MFU reporting and a `jax.profiler` trace hook (SURVEY.md §5.1 notes
the reference has no profiler integration at all).
"""

from llm_training_tpu.callbacks.nan_guard import (
    LossSpikeError,
    NanGuard,
    NanGuardConfig,
    NonFiniteLossError,
)
from llm_training_tpu.callbacks.loggers import JsonlLogger, JsonlLoggerConfig, WandbLogger, WandbLoggerConfig
from llm_training_tpu.callbacks.output_redirection import OutputRedirection, OutputRedirectionConfig
from llm_training_tpu.callbacks.progress import ProgressBar, ProgressBarConfig
from llm_training_tpu.callbacks.profiler import ProfilerCallback, ProfilerCallbackConfig
from llm_training_tpu.callbacks.time_estimator import TrainingTimeEstimator, TrainingTimeEstimatorConfig

__all__ = [
    "LossSpikeError",
    "NanGuard",
    "NanGuardConfig",
    "NonFiniteLossError",
    "JsonlLogger",
    "JsonlLoggerConfig",
    "WandbLogger",
    "WandbLoggerConfig",
    "OutputRedirection",
    "OutputRedirectionConfig",
    "ProgressBar",
    "ProgressBarConfig",
    "ProfilerCallback",
    "ProfilerCallbackConfig",
    "TrainingTimeEstimator",
    "TrainingTimeEstimatorConfig",
]
