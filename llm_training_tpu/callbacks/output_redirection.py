"""Tee stdout/stderr + logging to a per-run log file.

Capability parity: reference `lightning/callbacks/output_redirection.py:13`
— numbered `.log` files in the run dir, with output produced before setup
buffered and flushed once the file exists (`:60-87`).
"""

from __future__ import annotations

import logging
import sys
from pathlib import Path

from pydantic import BaseModel, ConfigDict


class OutputRedirectionConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    log_dir: str = "runs/logs"


class _Tee:
    def __init__(self, stream, sink):
        self._stream = stream
        self._sink = sink

    def write(self, data):
        self._stream.write(data)
        self._sink.write(data)
        return len(data)

    def flush(self):
        self._stream.flush()
        self._sink.flush()

    def __getattr__(self, name):
        return getattr(self._stream, name)


class OutputRedirection:
    """Installs the tee at fit start; removes it (and closes the file) at
    fit end. Files are numbered `0.log`, `1.log`, ... per directory, like
    the reference's `_get_log_file` (`output_redirection.py:35-44`)."""

    def __init__(self, config: OutputRedirectionConfig | None = None):
        self.config = config or OutputRedirectionConfig()
        self._file = None
        self._saved = None
        self.log_path: Path | None = None

    def on_fit_start(self, trainer, objective, datamodule, start_step) -> None:
        log_dir = Path(self.config.log_dir)
        log_dir.mkdir(parents=True, exist_ok=True)
        taken = [
            int(p.stem) for p in log_dir.glob("*.log") if p.stem.isdigit()
        ]
        n = max(taken, default=-1) + 1  # gaps never clobber an existing log
        self.log_path = log_dir / f"{n}.log"
        self._file = open(self.log_path, "w")
        self._saved = (sys.stdout, sys.stderr)
        sys.stdout = _Tee(self._saved[0], self._file)
        sys.stderr = _Tee(self._saved[1], self._file)
        # loggers don't necessarily write through sys.stdout/stderr (their
        # handlers may hold other streams), so tee them with a real handler —
        # the reference redirects handler streams the same way
        # (`output_redirection.py:60-87`)
        self._handler = logging.StreamHandler(self._file)
        self._handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logging.getLogger().addHandler(self._handler)

    def on_fit_end(self, trainer, state) -> None:
        self.teardown()

    def teardown(self) -> None:
        """Idempotent; also invoked by the trainer's finally block so a
        raising fit cannot leak the tee or the extra root handler."""
        if self._saved is not None:
            logging.getLogger().removeHandler(self._handler)
            self._handler = None
            sys.stdout, sys.stderr = self._saved
            self._saved = None
        if self._file is not None:
            self._file.close()
            self._file = None
