"""Metric loggers.

Capability parity: reference `lightning/loggers/wandb.py:10` (W&B logger
with project/name-scoped save dirs) and `SaveConfigCallback`'s resolved-
config upload (`save_config_callback.py:15-41`). W&B is optional at runtime
(this image has no wandb and zero egress), so the always-available default
is a JSONL metrics file per run — machine-readable like a W&B history file.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path

from pydantic import BaseModel, ConfigDict

logger = logging.getLogger(__name__)

# metric keys routed (additionally) to telemetry.jsonl — the observability
# record a `report` invocation reads (docs/observability.md)
TELEMETRY_PREFIXES = (
    "goodput/", "hbm/", "xla/", "data/", "checkpoint/", "perf/",
    "health/", "nan_guard/", "resilience/", "decode/", "eval/", "serve/",
    "elastic/", "flash/", "trace/", "slo/", "exporter/", "attr/",
    "profile/", "hbm_timeline/", "router/", "rl/", "ckpt/",
)
TELEMETRY_KEYS = ("compile_time_s",)


def _is_telemetry_key(key: str) -> bool:
    return key in TELEMETRY_KEYS or key.startswith(TELEMETRY_PREFIXES)


def _primary_host() -> bool:
    """Run-dir artifacts are written by process 0 only: in multi-host SPMD
    every host runs the same program, and N hosts appending to one
    metrics.jsonl (or racing W&B inits) corrupts the run record."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


class JsonlLoggerConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    # save_dir/project defaults are mirrored by cli.main's
    # _jsonl_run_dir_jaxfree (the supervisor path cannot import this
    # package — its __init__ pulls jax); keep them in sync
    save_dir: str = "runs"
    project: str = "llm-training-tpu"
    name: str | None = None  # default: timestamp


class JsonlLogger:
    """Appends one JSON object per logged step to
    `<save_dir>/<project>/<name>/metrics.jsonl` (all metrics) and
    `telemetry.jsonl` (the goodput/device/registry subset `report` reads),
    and writes the resolved run config next to them (the reference embeds it
    in W&B + checkpoints). All writes happen on process 0 only."""

    def __init__(self, config: JsonlLoggerConfig | None = None):
        self.config = config or JsonlLoggerConfig()
        name = self.config.name or time.strftime("%Y%m%d-%H%M%S")
        self.run_dir = Path(self.config.save_dir) / self.config.project / name
        self._files: dict[str, object] = {}

    def _ensure_open(self, filename: str):
        if filename not in self._files:
            self.run_dir.mkdir(parents=True, exist_ok=True)
            self._files[filename] = open(self.run_dir / filename, "a")
        return self._files[filename]

    def _write(self, filename: str, record: dict) -> None:
        f = self._ensure_open(filename)
        f.write(json.dumps(record) + "\n")
        f.flush()

    def on_fit_start(self, trainer, objective, datamodule, start_step) -> None:
        if not _primary_host():
            return
        self.run_dir.mkdir(parents=True, exist_ok=True)
        # one metadata snapshot per run: reuse the checkpointer's (collected
        # at construction) so the checkpoint meta and the run dir record the
        # SAME world/env/rev; collect only when no checkpointer exists
        ckpt = getattr(trainer, "checkpointer", None)
        run_metadata = getattr(ckpt, "run_metadata", None)
        if run_metadata is None:
            from llm_training_tpu.run_metadata import collect_run_metadata

            run_metadata = collect_run_metadata()
        (self.run_dir / "run_metadata.json").write_text(
            json.dumps(run_metadata, indent=2, default=str)
        )
        run_config = getattr(ckpt, "run_config", None)
        if run_config:
            (self.run_dir / "config.json").write_text(json.dumps(run_config, indent=2, default=str))

    def on_step_end(self, trainer, step, metrics) -> None:
        if not _primary_host():
            return
        record = {"step": step}
        for key, value in metrics.items():
            try:
                record[key] = float(value)
            except (TypeError, ValueError):
                record[key] = str(value)
        self._write("metrics.jsonl", record)
        telemetry = {k: v for k, v in record.items() if _is_telemetry_key(k)}
        if telemetry:
            self._write("telemetry.jsonl", {"step": step, **telemetry})

    def on_validation_end(self, trainer, step, metrics) -> None:
        self.on_step_end(trainer, step, metrics)

    def on_telemetry(self, trainer, step, record) -> None:
        """End-of-fit telemetry flush (trainer epilogue): the post-loop
        checkpoint save/wait lands after the last log step — without this,
        `report` would render totals missing that tail."""
        if not _primary_host():
            return
        telemetry = {}
        for key, value in record.items():
            if not _is_telemetry_key(key):
                continue
            try:
                telemetry[key] = float(value)
            except (TypeError, ValueError):
                telemetry[key] = str(value)
        if telemetry:
            self._write("telemetry.jsonl", {"step": step, **telemetry})

    def on_fit_end(self, trainer, state) -> None:
        for f in self._files.values():
            f.close()
        self._files = {}


class WandbLoggerConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    save_dir: str = "runs"
    project: str = "llm-training-tpu"
    name: str | None = None
    entity: str | None = None
    mode: str = "offline"  # zero-egress default; 'online' where permitted
    # upload the resolved run config YAML + a snapshot of the framework's
    # .py sources to the run (reference save_config_callback.py:15-41)
    log_code: bool = True


class WandbLogger:
    """W&B metrics logging, import-gated: constructing it without wandb
    installed raises immediately (no silent no-op), matching the reference's
    hard dependency (`lightning/loggers/wandb.py`)."""

    def __init__(self, config: WandbLoggerConfig | None = None):
        import wandb  # noqa: F401 — fail fast if unavailable

        self.config = config or WandbLoggerConfig()
        self._run = None

    def on_fit_start(self, trainer, objective, datamodule, start_step) -> None:
        if not _primary_host():
            return
        import wandb

        cfg = self.config
        save_dir = Path(cfg.save_dir) / cfg.project / (cfg.name or "")
        save_dir.mkdir(parents=True, exist_ok=True)
        run_config = getattr(getattr(trainer, "checkpointer", None), "run_config", None)
        self._run = wandb.init(
            project=cfg.project,
            name=cfg.name,
            entity=cfg.entity,
            dir=str(save_dir),
            mode=cfg.mode,
            config=run_config,
            resume="allow",
        )
        if cfg.log_code:
            # resolved config as a run file + the package's .py sources as a
            # code artifact — the reference's `experiment.save(config_path)`
            # + `log_code` pair (save_config_callback.py:38-41), so a run is
            # reproducible from its W&B page alone
            import yaml

            if run_config is not None:
                config_path = save_dir / "config.yaml"
                with open(config_path, "w") as f:
                    yaml.safe_dump(run_config, f, sort_keys=False)
                self._run.save(str(config_path), base_path=str(save_dir), policy="now")
            import llm_training_tpu

            root = Path(llm_training_tpu.__file__).parent
            self._run.log_code(
                root=str(root),
                name=f"source-{cfg.project}",
                include_fn=lambda p: p.endswith(".py"),
            )

    def on_step_end(self, trainer, step, metrics) -> None:
        if self._run is not None:
            self._run.log(
                {k: v for k, v in metrics.items() if isinstance(v, (int, float)) or hasattr(v, "item")},
                step=step,
            )

    def on_validation_end(self, trainer, step, metrics) -> None:
        self.on_step_end(trainer, step, metrics)

    def on_telemetry(self, trainer, step, record) -> None:
        # W&B merges re-logs at the same step, so the end-of-fit tail
        # (final checkpoint save/wait) updates the run's last history row
        self.on_step_end(trainer, step, record)

    def on_fit_end(self, trainer, state) -> None:
        if self._run is not None:
            self._run.finish()
            self._run = None
