"""Resume-aware single-line training progress display.

Capability parity: reference `lightning/callbacks/tqdm_progress.py:6-11` —
a TQDMProgressBar whose `initial` offset is set from the restored batch
index so a resumed run's bar starts where training actually is. Here the
bar is a dependency-free `\r` status line (tqdm is not in this image):
step/total, percent, steps/s, tokens/s, loss (from the latest log step),
and an ETA extrapolated from steps completed *this run* — the resume
offset is excluded from the rate so the ETA stays honest after restore.
"""

from __future__ import annotations

import sys
import time

from pydantic import BaseModel, ConfigDict, Field


class ProgressBarConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    # minimum seconds between redraws (the step loop can run >10/s; drawing
    # every step would dominate the host thread)
    refresh_rate: float = Field(0.5, gt=0)
    # auto-disable when stdout is not a TTY (log files, CI); force with True
    force: bool = False


def _fmt_duration(seconds: float) -> str:
    seconds = int(seconds)
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    return f"{h}:{m:02d}:{s:02d}" if h else f"{m}:{s:02d}"


class ProgressBar:
    def __init__(self, config: ProgressBarConfig | None = None):
        self.config = config or ProgressBarConfig()
        self._stream = sys.stdout
        self._enabled = False
        self._start_step = 0
        self._start_time = 0.0
        self._start_tokens = 0
        self._last_draw = 0.0
        self._last_loss: float | None = None
        self._drew = False

    def on_fit_start(self, trainer, objective, datamodule, start_step) -> None:
        # the bar is terminal furniture, not log content: write to the
        # process's ORIGINAL stdout so OutputRedirection's tee never records
        # the \r redraws into the persistent run log. force=True keeps the
        # current sys.stdout so tests (and piped verifies) can capture it.
        self._stream = (
            sys.stdout
            if self.config.force or sys.__stdout__ is None
            else sys.__stdout__
        )
        self._enabled = self.config.force or self._stream.isatty()
        self._start_step = start_step  # resume offset: rate counts this run only
        self._start_time = time.perf_counter()
        self._start_tokens = trainer.counters.get("consumed_tokens", 0)
        self._last_draw = 0.0
        self._drew = False

    def on_train_step(self, trainer, step) -> None:
        if not self._enabled:
            return
        now = time.perf_counter()
        if now - self._last_draw < self.config.refresh_rate:
            return
        self._last_draw = now
        total = trainer.config.max_steps
        done_this_run = step - self._start_step
        elapsed = now - self._start_time
        rate = done_this_run / elapsed if elapsed > 0 else 0.0
        tokens = trainer.counters.get("consumed_tokens", 0) - self._start_tokens
        tok_rate = tokens / elapsed if elapsed > 0 else 0.0
        eta = (total - step) / rate if rate > 0 else float("inf")
        parts = [
            f"step {step}/{total} ({100.0 * step / total:.0f}%)",
            f"{rate:.2f} it/s",
            f"{tok_rate:,.0f} tok/s",
        ]
        if self._last_loss is not None:
            parts.append(f"loss {self._last_loss:.4f}")
        if eta != float("inf"):
            parts.append(f"ETA {_fmt_duration(eta)}")
        line = " | ".join(parts)
        self._stream.write("\r\x1b[2K" + line)
        self._stream.flush()
        self._drew = True

    def on_step_end(self, trainer, step, metrics) -> None:
        try:
            self._last_loss = float(metrics.get("loss"))
        except (TypeError, ValueError):
            pass

    def on_fit_end(self, trainer, state) -> None:
        if self._drew:
            self._stream.write("\n")
            self._stream.flush()
            self._drew = False

    def teardown(self) -> None:
        # restore the terminal even when fit raises mid-run
        self.on_fit_end(None, None)
