"""Run-health guard: stop or raise on non-finite OR spiking loss/grad_norm,
with NaN provenance and anomaly dumps.

Capability parity: the reference's failure-detection surface (SURVEY.md
§5.3) is fp16-specific — DeepSpeed loss-scale underflow with
`raise_error_at_min_scale` (`deepspeed_strategy.py:104-108`) plus a
skipped-steps metric (`:131-142`). bf16 training has no loss scale; the
TPU-native equivalent watches the loss and grad norm directly, counts
non-finite steps (published as the `nan_guard/non_finite_steps` registry
counter — the skipped-steps-metric analogue, persisted to telemetry.jsonl/
W&B), and kills the run before it burns accelerator-hours on a diverged
model. Checks run on log steps (host metrics already materialized there —
no extra device sync).

Beyond the reference: an EMA z-score spike detector
(`telemetry/anomaly.EmaZScore`) catches divergence precursors while
everything is still finite — large-scale TPU runs stop-and-rewind on
exactly this signal (arXiv 2204.06514 §5) — and both the NaN and spike
paths name the offending layer groups (from the trainer's most recent
health-step snapshot, `trainer.last_health`) and write an
`anomaly-<step>.json` dump into the run directory."""

from __future__ import annotations

import logging
import math

from pydantic import BaseModel, ConfigDict, Field

from llm_training_tpu.telemetry import anomaly as _anomaly

logger = logging.getLogger(__name__)


class NanGuardConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    # consecutive non-finite log-steps tolerated before aborting; 0 = abort
    # on the first one
    patience: int = Field(0, ge=0)
    # raise (crash the run, let the scheduler restart from the checkpoint)
    # vs stop (end the fit cleanly)
    action: str = Field("raise", pattern="^(raise|stop)$")
    # spike guard: z-score threshold on loss/grad_norm vs their EMA
    # mean/std — trips on UPWARD excursions only (a sharp loss improvement
    # scores negative and never aborts); None (default) disables spike
    # detection entirely. 6-8 is a sane band for pretraining loss curves
    # (log-step cadence smooths the per-step noise the threshold sees)
    spike_zscore: float | None = Field(None, gt=0)
    # log-steps of EMA warmup before the z-score arms — early-training
    # descent is steep and would false-positive against a cold EMA
    spike_warmup_steps: int = Field(20, ge=2)
    spike_ema_beta: float = Field(0.98, gt=0, lt=1)
    # consecutive spiking log-steps tolerated before acting
    spike_patience: int = Field(0, ge=0)
    # write anomaly-<step>.json into the run dir on abort (skipped when the
    # run has no artifact directory — no logger run_dir / checkpoint dir)
    dump_anomalies: bool = True


class NonFiniteLossError(RuntimeError):
    pass


class LossSpikeError(RuntimeError):
    pass


class NanGuard:
    def __init__(self, config: NanGuardConfig | None = None):
        self.config = config or NanGuardConfig()
        self.non_finite_steps = 0  # total, the skipped-steps metric analogue
        self.spike_steps = 0
        self._streak = 0
        self._spike_streak = 0
        self._detectors: dict[str, _anomaly.EmaZScore] = {}
        if self.config.spike_zscore:
            self._detectors = {
                name: _anomaly.EmaZScore(
                    beta=self.config.spike_ema_beta,
                    warmup=self.config.spike_warmup_steps,
                )
                for name in ("loss", "grad_norm")
            }

    def on_step_end(self, trainer, step, metrics) -> None:
        loss = float(metrics.get("loss", 0.0))
        grad_norm = float(metrics.get("grad_norm", 0.0))
        if math.isfinite(loss) and math.isfinite(grad_norm):
            self._streak = 0
            self._check_spikes(
                trainer, step, {"loss": loss, "grad_norm": grad_norm}, metrics
            )
            return
        self.non_finite_steps += 1
        self._streak += 1
        self._count(trainer, "nan_guard/non_finite_steps")
        offending = _anomaly.offending_layers(getattr(trainer, "last_health", None))
        logger.warning(
            "non-finite training signal at step %d (loss=%s grad_norm=%s), "
            "streak %d%s",
            step, loss, grad_norm, self._streak,
            f"; non-finite grad layers: {', '.join(offending)}" if offending else "",
        )
        if self._streak > self.config.patience:
            dump = self._dump(trainer, step, "non_finite", metrics, offending)
            message = (
                f"training diverged: non-finite loss/grad_norm for "
                f"{self._streak} consecutive log steps (step {step})"
            )
            if offending:
                message += (
                    "; first non-finite gradient layer(s): " + ", ".join(offending)
                )
            if dump is not None:
                message += f" [anomaly dump: {dump}]"
            if self.config.action == "raise":
                raise NonFiniteLossError(message)
            logger.error("%s — stopping", message)
            trainer.should_stop = True
            # the diverged state must not become the newest checkpoint: a
            # resume would restart from NaN weights
            trainer.abort_final_save = True

    # ------------------------------------------------------------ spikes

    def _check_spikes(self, trainer, step, values, metrics) -> None:
        if not self._detectors:
            return
        spiking: list[tuple[str, float]] = []
        for name, detector in self._detectors.items():
            z = detector.score(values[name])
            if z is not None and z > self.config.spike_zscore:
                # the excursion is NOT folded into the EMA — the tracker
                # models the healthy process, so a sustained spike keeps
                # scoring against the pre-spike statistics
                spiking.append((name, z))
            else:
                detector.update(values[name])
        if not spiking:
            self._spike_streak = 0
            return
        self.spike_steps += 1
        self._spike_streak += 1
        self._count(trainer, "nan_guard/spike_steps")
        described = ", ".join(f"{name} z={z:.1f}" for name, z in spiking)
        suspects = _anomaly.top_layers(getattr(trainer, "last_health", None))
        logger.warning(
            "loss-spike signal at step %d (%s), streak %d%s",
            step, described, self._spike_streak,
            f"; fastest-moving layers: {', '.join(suspects)}" if suspects else "",
        )
        if self._spike_streak > self.config.spike_patience:
            dump = self._dump(
                trainer, step, "spike", metrics, suspects,
                extra={"zscores": {name: z for name, z in spiking}},
            )
            message = (
                f"training spiked: {described} exceeded spike_zscore="
                f"{self.config.spike_zscore} for {self._spike_streak} "
                f"consecutive log steps (step {step})"
            )
            if suspects:
                message += "; fastest-moving layer(s): " + ", ".join(suspects)
            if dump is not None:
                message += f" [anomaly dump: {dump}]"
            if self.config.action == "raise":
                raise LossSpikeError(message)
            logger.error("%s — stopping", message)
            trainer.should_stop = True
            # unlike the NaN path, the weights are still finite — the final
            # checkpoint stays useful for post-mortem / rewind, so the save
            # is NOT aborted

    # ------------------------------------------------------------ state

    def state_dict(self) -> dict:
        """JSON-serializable guard state, persisted in checkpoint metadata
        (the trainer gathers every callback's `state_dict` on save). The
        EMA trackers matter most: without them the spike detector restarts
        its warmup window blind right after every resume — the moment
        spikes are most likely."""
        return {
            "non_finite_steps": self.non_finite_steps,
            "spike_steps": self.spike_steps,
            "streak": self._streak,
            "spike_streak": self._spike_streak,
            "detectors": {
                name: {"count": d.count, "mean": d.mean, "var": d.var}
                for name, d in self._detectors.items()
            },
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore from checkpoint metadata. Detector hyperparameters
        (beta/warmup) come from THIS run's config — only the tracked
        statistics are restored, and only for detectors this config builds
        (a run that disabled spike detection ignores persisted trackers)."""
        self.non_finite_steps = int(state.get("non_finite_steps", 0))
        self.spike_steps = int(state.get("spike_steps", 0))
        self._streak = int(state.get("streak", 0))
        self._spike_streak = int(state.get("spike_streak", 0))
        for name, data in (state.get("detectors") or {}).items():
            detector = self._detectors.get(name)
            if detector is None:
                continue
            detector.count = int(data.get("count", 0))
            detector.mean = float(data.get("mean", 0.0))
            detector.var = float(data.get("var", 0.0))

    def on_rollback(self, trainer, step: int) -> None:
        """In-process recovery rewound to `step`: clear the failure streaks
        (the diverged window is being discarded) but keep the EMA trackers
        and lifetime totals — they model the healthy process and the run's
        history, not the excursion."""
        self._streak = 0
        self._spike_streak = 0

    # ------------------------------------------------------------ plumbing

    @staticmethod
    def _count(trainer, name: str) -> None:
        registry = getattr(trainer, "telemetry", None)
        if registry is not None:
            registry.counter(name).inc()

    def _dump(self, trainer, step, reason, metrics, offending, extra=None):
        if not self.config.dump_anomalies:
            return None
        run_dir = _anomaly.resolve_run_dir(trainer)
        if run_dir is None:
            logger.info(
                "no run directory (logger/checkpointer) — skipping the "
                "anomaly dump for step %d", step,
            )
            return None
        return _anomaly.dump_anomaly(
            run_dir, step, reason, metrics,
            offending=offending,
            health=getattr(trainer, "last_health", None),
            extra=extra,
        )
