"""Divergence detection: stop or raise on non-finite loss / gradients.

Capability parity: the reference's failure-detection surface (SURVEY.md
§5.3) is fp16-specific — DeepSpeed loss-scale underflow with
`raise_error_at_min_scale` (`deepspeed_strategy.py:104-108`) plus a
skipped-steps metric (`:131-142`). bf16 training has no loss scale; the
TPU-native equivalent watches the loss and grad norm directly, counts
non-finite steps, and kills the run before it burns accelerator-hours on a
diverged model. Checks run on log steps (host metrics already materialized
there — no extra device sync)."""

from __future__ import annotations

import logging
import math

from pydantic import BaseModel, ConfigDict, Field

logger = logging.getLogger(__name__)


class NanGuardConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    # consecutive non-finite log-steps tolerated before aborting; 0 = abort
    # on the first one
    patience: int = Field(0, ge=0)
    # raise (crash the run, let the scheduler restart from the checkpoint)
    # vs stop (end the fit cleanly)
    action: str = Field("raise", pattern="^(raise|stop)$")


class NonFiniteLossError(RuntimeError):
    pass


class NanGuard:
    def __init__(self, config: NanGuardConfig | None = None):
        self.config = config or NanGuardConfig()
        self.non_finite_steps = 0  # total, the skipped-steps metric analogue
        self._streak = 0

    def on_step_end(self, trainer, step, metrics) -> None:
        loss = float(metrics.get("loss", 0.0))
        grad_norm = float(metrics.get("grad_norm", 0.0))
        if math.isfinite(loss) and math.isfinite(grad_norm):
            self._streak = 0
            return
        self.non_finite_steps += 1
        self._streak += 1
        logger.warning(
            "non-finite training signal at step %d (loss=%s grad_norm=%s), streak %d",
            step, loss, grad_norm, self._streak,
        )
        if self._streak > self.config.patience:
            message = (
                f"training diverged: non-finite loss/grad_norm for "
                f"{self._streak} consecutive log steps (step {step})"
            )
            if self.config.action == "raise":
                raise NonFiniteLossError(message)
            logger.error("%s — stopping", message)
            trainer.should_stop = True
            # the diverged state must not become the newest checkpoint: a
            # resume would restart from NaN weights
            trainer.abort_final_save = True
