"""jax.profiler trace capture over a step window.

TPU-native addition with no reference analogue (SURVEY.md §5.1: the
reference has no profiler integration). Captures an XLA/TensorBoard trace
for steps [start_step, start_step + num_steps) — the standard workflow for
finding HBM-bound ops and collective stalls.

When the fit owns a `ProfileTrigger` (telemetry/profiling.py), this
callback goes passive: the trainer reads `profile_window()` at fit start,
schedules the window on the trigger (same budget accounting, artifacts
inside the run dir by default), and marks the callback `_absorbed` — one
owner for jax.profiler.start/stop_trace, so a breach-fired capture can
never nest inside a config-window capture. The standalone path below is
kept for direct use outside a trainer fit (bench stages, tests).
"""

from __future__ import annotations

import logging

import jax
from pydantic import BaseModel, ConfigDict

logger = logging.getLogger(__name__)

# standalone fallback only; inside a fit the ProfileTrigger resolves an
# unset trace_dir to <run_dir>/profile-window-<start> instead
DEFAULT_TRACE_DIR = "runs/profile"


class ProfilerCallbackConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    # None = let the owner pick (ProfileTrigger: inside the run dir;
    # standalone: DEFAULT_TRACE_DIR)
    trace_dir: str | None = None
    start_step: int = 5  # past compile/warmup
    num_steps: int = 3


class ProfilerCallback:
    def __init__(self, config: ProfilerCallbackConfig | None = None):
        self.config = config or ProfilerCallbackConfig()
        self._active = False
        self._stop_step: int | None = None
        # set by the trainer when the window was handed to a ProfileTrigger
        self._absorbed = False

    def profile_window(self) -> tuple[int, int, str | None]:
        """The configured capture window, for a ProfileTrigger to adopt."""
        cfg = self.config
        return cfg.start_step, cfg.num_steps, cfg.trace_dir

    def on_train_step(self, trainer, step) -> None:
        if self._absorbed:
            return
        cfg = self.config
        if not self._active and cfg.start_step <= step < cfg.start_step + cfg.num_steps:
            # explicit stop boundary, clamped to the fit's last step: when
            # start_step + num_steps overruns max_steps the trace must still
            # stop inside the loop (at the final step) rather than relying
            # on teardown after the fit unwinds
            stop_step = cfg.start_step + cfg.num_steps
            max_steps = getattr(getattr(trainer, "config", None), "max_steps", None)
            if max_steps is not None:
                stop_step = min(stop_step, max_steps)
            if step >= stop_step:
                # zero-length window (e.g. start_step == max_steps): a trace
                # started now would capture only the fit epilogue — no later
                # on_train_step exists to close it inside the loop
                logger.warning(
                    "profiler window [%d, %d) truncated to nothing at step %d; "
                    "not tracing", cfg.start_step, cfg.start_step + cfg.num_steps, step,
                )
                return
            if cfg.trace_dir is None:
                # write the resolved dir back so callers (and tests) read
                # the actual capture location off the config afterwards
                cfg.trace_dir = DEFAULT_TRACE_DIR
            self._stop_step = stop_step
            jax.profiler.start_trace(cfg.trace_dir)
            self._active = True
            logger.info("profiler trace started at step %d -> %s", step, cfg.trace_dir)
        elif self._active and self._stop_step is not None and step >= self._stop_step:
            jax.profiler.stop_trace()
            self._active = False
            logger.info("profiler trace stopped at step %d", step)

    def on_fit_end(self, trainer, state) -> None:
        self.teardown()

    def teardown(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
