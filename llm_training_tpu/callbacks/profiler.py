"""jax.profiler trace capture over a step window.

TPU-native addition with no reference analogue (SURVEY.md §5.1: the
reference has no profiler integration). Captures an XLA/TensorBoard trace
for steps [start_step, start_step + num_steps) — the standard workflow for
finding HBM-bound ops and collective stalls.
"""

from __future__ import annotations

import logging

import jax
from pydantic import BaseModel, ConfigDict

logger = logging.getLogger(__name__)


class ProfilerCallbackConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    trace_dir: str = "runs/profile"
    start_step: int = 5  # past compile/warmup
    num_steps: int = 3


class ProfilerCallback:
    def __init__(self, config: ProfilerCallbackConfig | None = None):
        self.config = config or ProfilerCallbackConfig()
        self._active = False

    def on_train_step(self, trainer, step) -> None:
        cfg = self.config
        if not self._active and step >= cfg.start_step:
            end = cfg.start_step + cfg.num_steps
            if step < end:
                jax.profiler.start_trace(cfg.trace_dir)
                self._active = True
                logger.info("profiler trace started at step %d -> %s", step, cfg.trace_dir)
        elif self._active and step >= cfg.start_step + cfg.num_steps:
            jax.profiler.stop_trace()
            self._active = False
            logger.info("profiler trace stopped at step %d", step)

    def on_fit_end(self, trainer, state) -> None:
        self.teardown()

    def teardown(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
