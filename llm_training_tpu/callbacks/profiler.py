"""jax.profiler trace capture over a step window.

TPU-native addition with no reference analogue (SURVEY.md §5.1: the
reference has no profiler integration). Captures an XLA/TensorBoard trace
for steps [start_step, start_step + num_steps) — the standard workflow for
finding HBM-bound ops and collective stalls.
"""

from __future__ import annotations

import logging

import jax
from pydantic import BaseModel, ConfigDict

logger = logging.getLogger(__name__)


class ProfilerCallbackConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    trace_dir: str = "runs/profile"
    start_step: int = 5  # past compile/warmup
    num_steps: int = 3


class ProfilerCallback:
    def __init__(self, config: ProfilerCallbackConfig | None = None):
        self.config = config or ProfilerCallbackConfig()
        self._active = False
        self._stop_step: int | None = None

    def on_train_step(self, trainer, step) -> None:
        cfg = self.config
        if not self._active and cfg.start_step <= step < cfg.start_step + cfg.num_steps:
            # explicit stop boundary, clamped to the fit's last step: when
            # start_step + num_steps overruns max_steps the trace must still
            # stop inside the loop (at the final step) rather than relying
            # on teardown after the fit unwinds
            stop_step = cfg.start_step + cfg.num_steps
            max_steps = getattr(getattr(trainer, "config", None), "max_steps", None)
            if max_steps is not None:
                stop_step = min(stop_step, max_steps)
            if step >= stop_step:
                # zero-length window (e.g. start_step == max_steps): a trace
                # started now would capture only the fit epilogue — no later
                # on_train_step exists to close it inside the loop
                logger.warning(
                    "profiler window [%d, %d) truncated to nothing at step %d; "
                    "not tracing", cfg.start_step, cfg.start_step + cfg.num_steps, step,
                )
                return
            self._stop_step = stop_step
            jax.profiler.start_trace(cfg.trace_dir)
            self._active = True
            logger.info("profiler trace started at step %d -> %s", step, cfg.trace_dir)
        elif self._active and self._stop_step is not None and step >= self._stop_step:
            jax.profiler.stop_trace()
            self._active = False
            logger.info("profiler trace stopped at step %d", step)

    def on_fit_end(self, trainer, state) -> None:
        self.teardown()

    def teardown(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
