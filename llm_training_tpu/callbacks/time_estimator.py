"""Training time + MFU estimator.

Capability parity: reference `lightning/callbacks/training_time_estimator.py`
— its only benchmarking tool: an N-step timed dry run extrapolated to a
total-training-time table (`:62-83`), optionally stopping the run
(`:32-37` disables checkpointing for the dry run; here `stop_after_steps`
ends the fit). TPU-native addition: tokens/sec/device and **MFU** against
the chip's peak bf16 FLOP/s — the number BASELINE.md is scored in — using
the standard decoder FLOP model (6·params·tokens + 12·L·H·D·S·tokens for
attention scores/values).
"""

from __future__ import annotations

import logging
import time

import jax
from pydantic import BaseModel, ConfigDict, Field

logger = logging.getLogger(__name__)

# peak dense bf16 FLOP/s per chip by device_kind substring
_PEAK_FLOPS = (
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
)


def peak_flops_per_device() -> float | None:
    kind = jax.devices()[0].device_kind.lower()
    for key, flops in _PEAK_FLOPS:
        if key in kind:
            return flops
    return None


def transformer_step_flops(
    num_params: int,
    tokens_per_step: int,
    num_layers: int | None = None,
    hidden_size: int | None = None,
    seq_len: int | None = None,
) -> float:
    """FLOPs for one fwd+bwd step: 6·N·T plus the attention quadratic term
    12·L·S·H·T when the shape is known (PaLM appendix B convention)."""
    flops = 6.0 * num_params * tokens_per_step
    if num_layers and hidden_size and seq_len:
        flops += 12.0 * num_layers * hidden_size * seq_len * tokens_per_step
    return flops


class TrainingTimeEstimatorConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    # measure steps [skip_first_n_steps, skip_first_n_steps + num_steps)
    num_steps: int = Field(20, ge=1)
    skip_first_n_steps: int = Field(2, ge=0)  # compile + warmup excluded, like `:40-62`
    stop_after_steps: int | None = Field(None, ge=1)  # dry run: end the fit afterwards


class TrainingTimeEstimator:
    """Reports steps/sec, tokens/sec(/device), MFU, and extrapolated total
    training time once the measurement window closes."""

    def __init__(self, config: TrainingTimeEstimatorConfig | None = None):
        self.config = config or TrainingTimeEstimatorConfig()
        self._t0 = None
        self._start_step = None
        self._start_tokens = 0
        self._num_params = None
        self._flops_hint: dict = {}
        self.result: dict | None = None

    def on_fit_start(self, trainer, objective, datamodule, start_step) -> None:
        self._fit_start_step = start_step
        model_cfg = getattr(getattr(objective, "model", None), "config", None)
        if model_cfg is not None:
            self._flops_hint = dict(
                num_layers=getattr(model_cfg, "num_hidden_layers", None),
                hidden_size=getattr(model_cfg, "hidden_size", None),
            )

    def _maybe_count_params(self, trainer) -> None:
        if self._num_params is None and getattr(trainer, "abstract_state", None) is not None:
            self._num_params = sum(
                leaf.size for leaf in jax.tree.leaves(trainer.abstract_state.params)
            )

    def on_train_step(self, trainer, step) -> None:
        cfg = self.config
        begin = self._fit_start_step + cfg.skip_first_n_steps
        if step >= begin and self._t0 is None:
            # drain the async dispatch queue: without this, perf_counter
            # timestamps measure dispatch rate, not device step time
            self._sync(trainer)
            self._t0 = time.perf_counter()
            self._start_step = step
            self._start_tokens = trainer.counters["consumed_tokens"]
        if self._t0 is not None and self.result is None and step - self._start_step >= cfg.num_steps:
            self._finish(trainer, step)
        if cfg.stop_after_steps and step - self._fit_start_step >= cfg.stop_after_steps:
            trainer.should_stop = True

    @staticmethod
    def _sync(trainer) -> None:
        if getattr(trainer, "last_metrics", None) is not None:
            jax.block_until_ready(trainer.last_metrics)

    def _finish(self, trainer, step) -> None:
        self._maybe_count_params(trainer)
        self._sync(trainer)
        elapsed = time.perf_counter() - self._t0
        steps = step - self._start_step
        tokens = trainer.counters["consumed_tokens"] - self._start_tokens
        n_dev = len(jax.devices())
        steps_per_sec = steps / elapsed
        tokens_per_sec = tokens / elapsed
        result = {
            "measured_steps": steps,
            "steps_per_sec": steps_per_sec,
            "tokens_per_sec": tokens_per_sec,
            "tokens_per_sec_per_device": tokens_per_sec / n_dev,
            "estimated_total_hours": (
                trainer.config.max_steps / steps_per_sec / 3600.0
            ),
        }
        peak = peak_flops_per_device()
        if self._num_params and peak:
            seq_len = getattr(trainer, "last_seq_len", None)
            step_flops = transformer_step_flops(
                self._num_params,
                int(tokens / steps),
                seq_len=seq_len,
                **self._flops_hint,
            )
            result["model_flops_per_step"] = step_flops
            result["mfu"] = step_flops * steps_per_sec / (peak * n_dev)
        # cross-check against XLA's own FLOP count for the compiled step
        # (telemetry gauge set by the trainer's AOT pre-compile). XLA counts
        # executed FLOPs per device — including remat recompute the analytic
        # model deliberately excludes — so mfu_xla >= mfu is expected under
        # gradient checkpointing; a LOWER mfu_xla flags a stale FLOP model
        telemetry = getattr(trainer, "telemetry", None)
        if telemetry is not None:
            # the gauge is PER-DEVICE FLOPs per train_step INVOCATION (one
            # micro-batch of the SPMD module); scale by accumulation and
            # device count so the published key is global per OPTIMIZER
            # step — the same units as model_flops_per_step above
            xla_flops = telemetry.snapshot().get("xla/flops_per_step")
            accum = getattr(getattr(trainer, "config", None), "accumulate_grad_batches", 1)
            if xla_flops and peak:
                global_xla_flops = xla_flops * accum * n_dev
                result["xla_flops_per_step"] = global_xla_flops
                result["mfu_xla"] = global_xla_flops * steps_per_sec / (peak * n_dev)
            # publish for the log-step metrics merge -> telemetry.jsonl ->
            # `report` (perf/ prefix routes them)
            for key, value in result.items():
                if isinstance(value, (int, float)):
                    telemetry.gauge(f"perf/{key}").set(float(value))
        self.result = result
        logger.info(
            "training time estimate: %s",
            {k: (round(v, 4) if isinstance(v, float) else v) for k, v in result.items()},
        )

    def on_fit_end(self, trainer, state) -> None:
        # short runs: close the window with whatever was measured
        if (
            self.result is None
            and self._t0 is not None
            and trainer.last_step is not None
            and trainer.last_step > self._start_step
        ):
            self._finish(trainer, trainer.last_step)
