from llm_training_tpu.cli.main import main

if __name__ == "__main__":
    raise SystemExit(main())
