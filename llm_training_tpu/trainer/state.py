"""Training state pytree.

The whole of the reference's distributed runtime state (module params,
optimizer shards, loop counters, persistent metric counters —
`fsdp2_strategy.py:314-409`, `metrics/consumed_*.py`) is this one pytree;
sharding it over the mesh IS the distribution strategy.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class TrainState:
    """`step` counts train_step invocations (micro-steps when gradient
    accumulation is on); the trainer derives optimizer-step numbering.
    Consumed-sample/token counters live host-side in the Trainer (python
    ints — no int32 overflow at pre-training scale) and persist via
    checkpoint metadata, like the reference's meta.pt counters."""

    step: jnp.ndarray             # int32 scalar, micro-steps
    params: Any                   # fp32 master params (flax tree)
    opt_state: Any                # optax state (fp32)
    rng: jax.Array                # objective rng (NEFTune etc.)

    @classmethod
    def create(cls, params: Any, opt_state: Any, rng: jax.Array) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=rng,
        )
