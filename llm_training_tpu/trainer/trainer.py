"""The SPMD training loop.

Capability parity: the reference's `Trainer.fit` call stack (SURVEY.md §3.1):
environment setup → mesh → model configure/materialize → optimizer → hot
loop with grad clip + optimizer step + metrics, plus validation and
checkpoint hooks. FSDP2Strategy/DeepSpeedStrategy (SURVEY.md §2.8) have no
analogue classes: parameter sharding IS the `fsdp` mesh axis, master weights
ARE fp32 params with a bf16 forward, grad accumulation is `optax.MultiSteps`,
grad-norm computation is `optax.global_norm` inside the jitted step.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterator

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from pydantic import BaseModel, ConfigDict

from llm_training_tpu.callbacks.nan_guard import LossSpikeError, NonFiniteLossError
from llm_training_tpu.optim.builder import build_optimizer
from llm_training_tpu.optim.quantized_state import (
    cast_state,
    decode_state,
    encode_state,
    uncast_state,
)
from llm_training_tpu.parallel.mesh import MeshConfig, build_mesh
from llm_training_tpu.parallel.sharding import (
    DEFAULT_LOGICAL_AXIS_RULES,
    logical_to_spec,
    resolve_spec,
)
from llm_training_tpu.resilience import (
    GracefulShutdown,
    HangWatchdog,
    PreemptionInterrupt,
    RecoveryManager,
    ResilienceConfig,
    check_data_continuity,
    config_from_env,
    get_chaos,
    install_chaos,
    uninstall_chaos,
)
from llm_training_tpu.telemetry import (
    GoodputLedger,
    HBMTimeline,
    HealthConfig,
    TelemetryRegistry,
    build_param_groups,
    build_profile_trigger,
    compiled_attribution_gauges,
    compiled_cost_gauges,
    get_tracer,
    hbm_gauges,
    layer_health_metrics,
    resolve_run_dir,
    set_profile_trigger,
    set_registry,
)
from llm_training_tpu.trainer.state import TrainState

logger = logging.getLogger(__name__)

# flax scan adds a 'layers' stacking axis to scanned params; keep it unsharded.
LOGICAL_AXIS_RULES = tuple(DEFAULT_LOGICAL_AXIS_RULES) + (("layers", None),)


def offload_memory_kinds() -> tuple[str, str]:
    """(compute_kind, host_kind) for optimizer-state offload on THIS
    backend. TPU/GPU devices address ('device', 'pinned_host'); a CPU
    device addresses only 'unpinned_host' — which is also its default
    memory — so both sides collapse to it and offload degrades to a
    same-memory placement. That keeps the whole offload metadata path
    (sharding resolution, memory-kind annotation, the blocked step's
    host/device twins) exercisable in CPU containers instead of raising
    'Could not find memory addressable by device cpu'."""
    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
    except Exception:  # an exotic backend without the memories API
        return "device", "pinned_host"
    if "pinned_host" in kinds:
        return ("device" if "device" in kinds else "pinned_host", "pinned_host")
    fallback = "unpinned_host" if "unpinned_host" in kinds else "device"
    return fallback, fallback


class TrainerConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    max_steps: int = 1000
    seed: int = 42
    accumulate_grad_batches: int = 1
    log_every_n_steps: int = 10
    val_check_interval: int | None = None
    limit_val_batches: int | None = None
    checkpoint_every_n_steps: int | None = None
    # batches placed on device ahead of the step loop by a worker thread
    # (the reference's pin_memory/prefetch_factor analogue); 0 disables
    prefetch_batches: int = 2
    # park optimizer state (mu/nu) in host memory (`pinned_host`), copying
    # it through HBM around each update — the reference's DeepSpeed
    # CPU-offload lever (`deepspeed_strategy.py:23-37`) as XLA host
    # offloading. Buys ~8 bytes/param of HBM for a per-step host round
    # trip that is LINK-BANDWIDTH BOUND (r5 chip measurement: per-leaf
    # copy/update/copy chains overlap nothing — 0.3035 vs 0.313 MFU —
    # because the update compute is negligible next to the transfers; and
    # host-side Adam via XLA host compute is 3-4x slower than the
    # transfers it would save). The working lever is offload_state_dtype,
    # which shrinks the bytes in EITHER layout: per-leaf blocks (when
    # accumulate_grad_batches == 1 and no frozen_modules) or the
    # serialized whole-tree round trip (accumulation / freeze masks),
    # where the codec's field whitelist keeps MultiSteps' fp32 grad
    # accumulators exact. NOTE: memory-kind annotations only execute on
    # TPU — the CPU
    # backend lacks the placement custom-call, so tests assert layout
    # metadata and numerics with device kinds, and the chip proves
    # placement
    offload_optimizer_state: bool = False
    # storage dtype for the offloaded state (works in both layouts —
    # per-leaf blocks and the serialized accumulation/freeze path):
    #   float32  — exact, 8 bytes/param round-trips each step
    #   bfloat16 — elementwise cast, 4 bytes/param (~2x less transfer)
    #   int8     — block-quantized (mu: sym int8, nu: sqrt uint8 with ceil
    #              rounding — see optim/quantized_state.py), 2 bytes/param
    #              + 1.6% scales (~4x less mu/nu transfer; under grad
    #              accumulation the fp32 acc_grads stay exact by field
    #              whitelist, capping that path's overall saving at ~2x).
    #              The capability analogue of DeepSpeed's quantized
    #              ZeRO-offload knobs (deepspeed_strategy.py:70-102),
    #              built for the real bottleneck here: the host link, not
    #              HBM
    offload_state_dtype: str = "float32"
    # quantization block (elements of the last axis sharing one scale) for
    # offload_state_dtype=int8; arrays whose last axis is not a multiple
    # stay fp32. 256 = 1.6% scale overhead
    offload_quant_block: int = 256
    # model-health layer (telemetry/health.py): per-layer-group grad/param/
    # update norms + MoE router health computed inside a jitted step VARIANT
    # every `health.every_n_steps` optimizer steps. Default (unset) builds
    # no variant — the compiled train step is byte-identical to health-off
    health: HealthConfig = HealthConfig()
    # fault tolerance (resilience/): preemption signal handling (on by
    # default — zero cost until a signal arrives), hang watchdog (off by
    # default), data-source retry policy, and the fault-injection harness
    # (docs/resilience.md)
    resilience: ResilienceConfig = ResilienceConfig()
    mesh: MeshConfig = MeshConfig()


def _batch_shardings(batch: dict[str, np.ndarray], mesh: Mesh) -> dict[str, NamedSharding]:
    spec = logical_to_spec(("batch", "act_seq"), LOGICAL_AXIS_RULES)
    return {k: NamedSharding(mesh, spec) for k in batch}


def _grads_and_metrics(objective, state: "TrainState", batch, with_health: bool = False):
    """Shared train-step preamble (both optimizer paths must stay in sync).
    `with_health` asks the objective for its health extras (MoE router
    stats) — only passed when the objective's signature supports it."""
    step_rng = jax.random.fold_in(state.rng, state.step)

    def loss_fn(params):
        if with_health:
            return objective.loss_and_metrics(
                params, batch, rng=step_rng, train=True, with_health=True
            )
        return objective.loss_and_metrics(params, batch, rng=step_rng, train=True)

    return jax.grad(loss_fn, has_aux=True)(state.params)


def _objective_supports_health(objective) -> bool:
    import inspect

    try:
        params = inspect.signature(objective.loss_and_metrics).parameters
    except (TypeError, ValueError):
        return False
    return "with_health" in params


class Trainer:
    """Drives objective + datamodule over a mesh.

    Usage: Trainer(config).fit(objective, datamodule).
    Callbacks (logging, checkpointing, timing) hook `on_step_end`.
    """

    def __init__(
        self,
        config: TrainerConfig,
        callbacks: list[Any] | None = None,
        checkpointer: Any | None = None,
        devices: list | None = None,
    ):
        self.config = config
        self.callbacks = callbacks or []
        self.checkpointer = checkpointer
        self.devices = devices  # None = all (tests pin subsets)
        self.mesh: Mesh | None = None
        self.state_shardings = None
        # host-side persistent counters (reference metrics/consumed_*.py);
        # python ints — no overflow; saved/restored via checkpoint metadata
        self.counters = {"consumed_samples": 0, "consumed_tokens": 0}
        # callback-visible run state (time/MFU estimator reads these).
        # abstract_state is the jax.eval_shape tree — safe to inspect any
        # time; live TrainState buffers are donated into the next step and
        # must never be cached by callbacks outside the current hook call
        self.should_stop = False
        # callbacks set this when the state must NOT be persisted (e.g. the
        # NaN guard stopping on divergence — saving would poison resume)
        self.abort_final_save = False
        # resilience runtime (built per fit): signal-driven shutdown manager,
        # hang watchdog, and whether this fit is ending due to a preemption
        # (fit then raises PreemptionInterrupt after the emergency save)
        self._shutdown: GracefulShutdown | None = None
        self._watchdog: HangWatchdog | None = None
        # live telemetry (built per fit, both optional): the /metrics //
        # statusz//healthz exporter (LLMT_METRICS_PORT) and the SLO
        # burn-rate monitor (LLMT_SLO_*) — docs/observability.md
        self._exporter = None
        self._slo = None
        self._profile_trigger = None
        self._hbm_timeline = None
        self._preempted = False
        # rollback-and-skip recovery (resilience/recovery.py): built per fit
        # when cfg.resilience.recovery is set; the save path persists its
        # skip-list/cooldown metadata into every checkpoint
        self._recovery: RecoveryManager | None = None
        # metadata of the checkpoint this fit restored from (callback state
        # + recovery riders come out of it); None on fresh starts
        self._restored_meta: dict | None = None
        # elastic topology (resilience/elastic.py): the plan this fit's mesh
        # came from (None with resilience.elastic unset) and the global
        # batch size the data stream is keyed to (the checkpoint data_state
        # rider — a resume must never change it)
        self.topology_plan = None
        self._global_batch_size: int | None = None
        # optimizer step of the newest in-loop interval save this fit (the
        # final-save epilogue skips re-saving an identical step)
        self._last_interval_save: int | None = None
        self.abstract_state = None
        self.last_step: int | None = None
        self.last_seq_len: int | None = None
        # host snapshot of the newest health step's metrics (NaN/spike
        # provenance reads this — callbacks/nan_guard.py); None until the
        # first health step (or always, with health.every_n_steps unset)
        self.last_health: dict[str, float] | None = None
        self._param_groups = None
        # per-fit telemetry: a thread-safe metric registry (prefetcher and
        # checkpointer record into it) + the goodput wall-time ledger; both
        # flow into the metrics dict on log steps (docs/observability.md)
        self.telemetry = TelemetryRegistry()
        self.ledger = GoodputLedger()
        # blocked optimizer offload (decided at fit start): the optimizer
        # state is a TUPLE of per-param-leaf states, each running its own
        # copy-in -> update -> copy-out chain with global grad clipping
        # factored out front (it couples all leaves). The layout exists for
        # the compressed storage dtypes (offload_state_dtype) — the r5 chip
        # measurement showed the chains themselves overlap nothing.
        self._blocked_offload = False
        self._clip_norm: float | None = None

    # ------------------------------------------------------------ setup

    def _build_tx(
        self, objective, schedule_transform: Callable | None = None
    ) -> tuple[optax.GradientTransformation, optax.Schedule]:
        """Decide the optimizer LAYOUT and build the transformation. The
        blocked (per-leaf) offload step needs a clip-free leaf-local
        transform; accumulation (MultiSteps wraps the whole tree) and
        path-named freeze masks fall back to the serialized round trip.
        fit and validate_from_checkpoint both go through here so the
        opt_state pytree layout — which checkpoints persist — always
        matches. `schedule_transform` (the recovery LR cooldown) wraps the
        LR schedule only — it can never change the opt_state layout, so a
        rebuilt tx accepts a previously-restored state unchanged."""
        cfg = self.config
        self._blocked_offload = (
            cfg.offload_optimizer_state
            and cfg.accumulate_grad_batches == 1
            and not objective.config.frozen_modules
        )
        if cfg.offload_state_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"offload_state_dtype {cfg.offload_state_dtype!r}; expected "
                "float32, bfloat16 or int8"
            )
        if cfg.offload_state_dtype != "float32" and not cfg.offload_optimizer_state:
            raise ValueError(
                "offload_state_dtype != float32 is a storage codec for the "
                "OFFLOADED state; set offload_optimizer_state=True"
            )
        if cfg.offload_quant_block < 1:
            raise ValueError(
                f"offload_quant_block must be >= 1, got {cfg.offload_quant_block}"
            )
        optim_config = objective.config.optim
        self._clip_norm = None
        if self._blocked_offload:
            self._clip_norm = optim_config.grad_clip_norm
            optim_config = optim_config.model_copy(update={"grad_clip_norm": None})
        tx, schedule = build_optimizer(
            optim_config,
            num_total_steps=cfg.max_steps,
            frozen_modules=objective.config.frozen_modules or None,
            schedule_transform=schedule_transform,
        )
        if cfg.accumulate_grad_batches > 1:
            tx = optax.MultiSteps(tx, cfg.accumulate_grad_batches)
        return tx, schedule

    def _opt_init(self, tx, params) -> Any:
        """Whole-tree optimizer state, or (blocked offload) one state per
        param leaf. Flattening stops at Partitioned boxes so per-leaf init
        preserves the sharding metadata zeros_like carries through them;
        boxed and unboxed trees flatten in the same order."""
        if not self._blocked_offload:
            if self.config.offload_optimizer_state:
                # serialized path (accumulation / freeze masks): compress
                # the whole tree — the codec's field whitelist leaves
                # MultiSteps accumulators and masked placeholders exact
                return self._encode(tx.init(params))
            return tx.init(params)
        leaves = jax.tree.flatten(
            params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
        )[0]
        return tuple(self._encode(tx.init(leaf)) for leaf in leaves)

    def _encode(self, state):
        """Storage codec for one offloaded per-leaf state block (identity
        unless offload_state_dtype compresses it)."""
        dtype = self.config.offload_state_dtype
        if dtype == "bfloat16":
            return cast_state(state, jnp.bfloat16)
        if dtype == "int8":
            return encode_state(state, block=self.config.offload_quant_block)
        return state

    def _decode(self, state):
        dtype = self.config.offload_state_dtype
        if dtype == "bfloat16":
            return uncast_state(state)
        if dtype == "int8":
            return decode_state(state)
        return state

    def _abstract_state(self, objective, sample_batch, tx) -> Any:
        """Shape-evaluate init to get the param tree WITH logical-axis
        metadata, then map to shardings (the analogue of the reference's
        meta-device init, `base_lm.py:256-267`)."""

        def make_state(rng):
            params = objective.init_params(rng, sample_batch)
            # zeros_like maps through the Partitioned boxes, so the abstract
            # opt_state (mu/nu) carries the same sharding annotations as params
            opt_state = self._opt_init(tx, params)
            return TrainState.create(params, opt_state, jax.random.key(1))

        return jax.eval_shape(make_state, jax.random.key(self.config.seed))

    def _state_shardings(self, abstract_state) -> Any:
        # STRICT resolution: an unknown logical-axis name in any param's
        # metadata raises UnknownLogicalAxisError naming the leaf — the
        # legacy behavior silently replicated the weight across the mesh
        # (OOM/crawl only on real hardware; see `python -m
        # llm_training_tpu.analysis --audit`). Duplicate-mesh-axis drops are
        # legal but no longer invisible: they surface once as a warning.
        drops = []

        def leaf_sharding(path, leaf):
            if isinstance(leaf, nn.Partitioned):
                if not jax.tree_util.tree_leaves(leaf.value):
                    # a box around an EMPTY pytree — optax.masked wraps
                    # frozen params' opt-state slots in MaskedNode(), and
                    # zeros_like maps it THROUGH the Partitioned box. There
                    # is no array to shard; emitting a sharding here would
                    # give the shardings tree a leaf the unboxed state tree
                    # doesn't have, breaking every frozen-modules restore
                    # (DPO/GRPO reference params)
                    return leaf.value
                spec, leaf_drops = resolve_spec(
                    leaf.names, LOGICAL_AXIS_RULES, strict=True,
                    path=jax.tree_util.keystr(path),
                )
                drops.extend(leaf_drops)
            else:
                spec = PartitionSpec()
            return NamedSharding(self.mesh, spec)

        shardings = jax.tree_util.tree_map_with_path(
            leaf_sharding,
            abstract_state,
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )
        for drop in drops:
            logger.warning(
                "sharding: %s dim %d (logical %r) dropped duplicate mesh "
                "axes %s — an earlier dim of the tensor already consumed "
                "them; the dim stays wider per chip than the rule table "
                "suggests", drop.path, drop.position, drop.axis,
                list(drop.mesh_axes),
            )
        if self.config.offload_optimizer_state:
            _, host_kind = offload_memory_kinds()

            def maybe_host(sharding, leaf):
                # only real arrays (mu/nu) move to host; rank-0 counters stay
                # on device — the SPMD partitioner rejects host placement of
                # side-effect scalars ("Side-effect HLO must have sharding")
                shape = leaf.value.shape if isinstance(leaf, nn.Partitioned) else leaf.shape
                if len(shape) == 0:
                    return sharding
                return sharding.with_memory_kind(host_kind)

            shardings = shardings.replace(
                opt_state=jax.tree.map(
                    maybe_host,
                    shardings.opt_state,
                    abstract_state.opt_state,
                    is_leaf=lambda x: isinstance(x, (NamedSharding, nn.Partitioned)),
                )
            )
        return shardings

    def _build_step(self, objective, tx) -> Callable:
        return self._make_step(objective, tx, with_health=False)

    def _build_health_step(self, objective, tx) -> Callable:
        """The instrumented step variant: same update math as `_build_step`
        plus per-layer-group health metrics (and the objective's MoE router
        health, when it supports the `with_health` flag). Compiled
        separately and called only on health-cadence steps, so the default
        step stays byte-identical."""
        return self._make_step(objective, tx, with_health=True)

    def _make_step(self, objective, tx, with_health: bool) -> Callable:
        offload = self.config.offload_optimizer_state
        objective_health = with_health and _objective_supports_health(objective)
        if offload:
            # device-resident twins of the host-kind opt-state shardings:
            # the update math runs in HBM, bracketed by explicit copies
            compute_kind, _ = offload_memory_kinds()
            opt_device = jax.tree.map(
                lambda s: s.with_memory_kind(compute_kind),
                self.state_shardings.opt_state,
            )
            opt_host = self.state_shardings.opt_state
        if self._blocked_offload:
            return self._build_blocked_offload_step(
                objective, tx, opt_device, opt_host,
                with_health=with_health, objective_health=objective_health,
            )

        def train_step(state: TrainState, batch: dict[str, jnp.ndarray]):
            grads, metrics = _grads_and_metrics(
                objective, state, batch, objective_health
            )
            opt_state = state.opt_state
            if offload:
                opt_state = self._decode(
                    jax.tree.map(jax.device_put, opt_state, opt_device)
                )
            updates, opt_state = tx.update(grads, opt_state, state.params)
            if offload:
                opt_state = jax.tree.map(
                    jax.device_put, self._encode(opt_state), opt_host
                )
            params = optax.apply_updates(state.params, updates)
            metrics["grad_norm"] = optax.global_norm(grads)
            if with_health:
                metrics.update(
                    layer_health_metrics(
                        self._param_groups, state.params, grads, updates
                    )
                )
            new_state = state.replace(
                step=state.step + 1,
                params=params,
                opt_state=opt_state,
            )
            return new_state, metrics

        return train_step

    def _build_blocked_offload_step(
        self, objective, tx, opt_device, opt_host,
        with_health: bool = False, objective_health: bool = False,
    ) -> Callable:
        """Per-leaf offloaded update (VERDICT r4 #5): `tx` here EXCLUDES
        grad clipping (built with grad_clip_norm=None; the global norm
        couples every leaf, so it is applied up front as a scalar re-scale
        — identical math to optax.clip_by_global_norm). Each param leaf
        carries its own optimizer-state block whose storage may be
        compressed (self._encode/_decode, offload_state_dtype) — the lever
        that actually cuts the host round trip; the r5 chip measurement
        showed leaf-chain overlap alone recovers nothing (0.3035 vs 0.313
        MFU). Usable-CPU-offload analogue: `deepspeed_strategy.py:23-37`
        + its quantized-offload knobs (`:70-102`)."""
        clip_norm = self._clip_norm

        def train_step(state: TrainState, batch: dict[str, jnp.ndarray]):
            grads, metrics = _grads_and_metrics(
                objective, state, batch, objective_health
            )
            gnorm = optax.global_norm(grads)
            metrics["grad_norm"] = gnorm
            # health reads the PRE-clip gradients (same semantics as the
            # non-offload step): the clip rescale is global, so a single
            # NaN leaf would smear NaN over every group and destroy the
            # per-layer provenance this exists for
            raw_grads = grads
            if clip_norm is not None:
                scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                grads = jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)

            p_leaves, p_def = jax.tree.flatten(state.params)
            g_leaves = jax.tree.flatten(grads)[0]
            new_params, new_opt, upd_leaves = [], [], []
            for p, g, o_host, sh_dev, sh_host in zip(
                p_leaves, g_leaves, state.opt_state, opt_device, opt_host
            ):
                o_dev = jax.tree.map(jax.device_put, o_host, sh_dev)
                upd, o_fp = tx.update(g, self._decode(o_dev), p)
                new_opt.append(
                    jax.tree.map(jax.device_put, self._encode(o_fp), sh_host)
                )
                upd_leaves.append(upd)
                new_params.append(optax.apply_updates(p, upd))
            if with_health:
                metrics.update(
                    layer_health_metrics(
                        self._param_groups, state.params, raw_grads,
                        jax.tree.unflatten(p_def, upd_leaves),
                    )
                )
            new_state = state.replace(
                step=state.step + 1,
                params=jax.tree.unflatten(p_def, new_params),
                opt_state=tuple(new_opt),
            )
            return new_state, metrics

        return train_step

    def _build_eval_step(self, objective) -> Callable:
        def eval_step(state: TrainState, batch):
            _, metrics = objective.loss_and_metrics(
                state.params, batch, rng=state.rng, train=False
            )
            return {"loss": metrics["loss"], "target_tokens": metrics["target_tokens"]}

        return eval_step

    # ------------------------------------------------------------ topology

    def _mesh_axis_sizes(self) -> dict[str, int]:
        """The live mesh's per-axis degrees — the ONE source both the
        segment_topology audit event and the checkpoint `topology` rider
        record (the planner pins model axes to the latter, so the two must
        never drift)."""
        return {
            str(name): int(size)
            for name, size in zip(self.mesh.axis_names, self.mesh.devices.shape)
        }

    def _resolve_topology(self, resume_step: int | None = None):
        """The elastic front door of fit: (devices, mesh_config, plan).

        With `resilience.elastic` unset this only applies the chaos device
        clamp (LLMT_CHAOS_DEVICES, a no-op unless the env var is set) and
        returns the config mesh untouched. With it set, the planner fits
        the mesh to the LIVE device pool: model axes pinned to the degrees
        recorded in the checkpoint being resumed, the data axis scaled to
        absorb the capacity change (resilience/elastic.py)."""
        from llm_training_tpu.resilience.elastic import (
            chaos_device_limit,
            plan_topology,
        )

        cfg = self.config
        devices = self.devices
        if devices is None:
            # the chaos shrink applies only to the default all-devices
            # path: tests that pin an explicit subset stay authoritative
            limit = chaos_device_limit()
            if limit is not None:
                devices = list(jax.devices())
                if limit < len(devices):
                    logger.warning(
                        "chaos: shrinking visible devices %d -> %d "
                        "(LLMT_CHAOS_DEVICES)", len(devices), limit,
                    )
                    devices = devices[:limit]
        if cfg.resilience.elastic is None:
            return devices, cfg.mesh, None
        if devices is None:
            devices = list(jax.devices())
        checkpoint_mesh = None
        checkpoint_batch = None
        if self.checkpointer is not None:
            meta = self.checkpointer.read_meta(resume_step)
            checkpoint_mesh = ((meta or {}).get("topology") or {}).get("mesh")
            checkpoint_batch = ((meta or {}).get("data_state") or {}).get(
                "global_batch_size"
            )
        plan = plan_topology(
            len(devices),
            cfg.mesh.axis_sizes(),
            checkpoint_mesh=checkpoint_mesh,
            global_batch_size=checkpoint_batch,
        )
        logger.info(
            "elastic topology: %s over %d device(s) [%s, from %s]",
            plan.axis_sizes, plan.device_count, plan.decision, plan.source,
        )
        return (
            devices[: plan.device_count],
            MeshConfig.from_axis_sizes(plan.axis_sizes),
            plan,
        )

    def _publish_topology(self, plan) -> None:
        """Tag this segment with its world: goodput cost basis (chip count
        + $/chip-hour -> goodput-per-dollar gauges), elastic/* telemetry,
        and — under a supervisor — a segment_topology event in
        supervisor.jsonl keyed by the launch attempt."""
        from llm_training_tpu.resilience.elastic import (
            log_segment_topology,
            resolve_chip_price,
            segment_attempt,
        )

        chips = int(self.mesh.devices.size)
        price = resolve_chip_price(self.config.resilience.elastic)
        self.ledger.set_cost_basis(chips, price)
        self.telemetry.gauge("elastic/segment").set(segment_attempt())
        self.telemetry.gauge("elastic/device_count").set(chips)
        self.telemetry.gauge("elastic/data_parallel_size").set(
            int(self.mesh.shape["data"])
        )
        log_segment_topology(
            self._mesh_axis_sizes(),
            chips,
            decision=plan.decision if plan is not None else "static mesh",
            price_per_chip_hour=price,
        )

    # ------------------------------------------------------------ fit

    def fit(
        self,
        objective,
        datamodule,
        resume_step: int | None = None,
        state: TrainState | None = None,
    ) -> TrainState:
        cfg = self.config
        devices, mesh_config, plan = self._resolve_topology(resume_step)
        self.mesh = build_mesh(mesh_config, devices)
        self.topology_plan = plan
        datamodule.setup()

        # fresh telemetry per fit, installed as the process-current registry
        # so components constructed elsewhere (the checkpointer) find it
        self.telemetry = TelemetryRegistry()
        self.ledger.start()
        self._publish_topology(plan)
        previous_registry = set_registry(self.telemetry)
        resil = cfg.resilience
        self._preempted = False
        self._last_interval_save = None
        # fault injection first (env overlays the config), so every other
        # resilience layer — and the checkpointer/prefetcher call sites —
        # sees the harness
        install_chaos(config_from_env(resil.chaos), registry=self.telemetry)
        self._shutdown = (
            GracefulShutdown().install() if resil.handle_signals else None
        )
        self._watchdog = None
        run_dir = resolve_run_dir(self)
        if resil.watchdog_timeout_s:
            self._watchdog = HangWatchdog(
                resil.watchdog_timeout_s,
                run_dir=run_dir,
                ledger=self.ledger,
                registry=self.telemetry,
                action=resil.watchdog_action,
            ).start()
        # SLO monitor (docs/observability.md#slo): armed only when
        # LLMT_SLO_* targets are set — otherwise zero cost. The step loop
        # feeds it optimizer-step intervals and goodput; breaches bump
        # slo/* counters and flight-dump the trace ring into the run dir —
        # process 0 only, like every run-dir artifact (N hosts breaching
        # together would clobber one dump file)
        from llm_training_tpu.telemetry.slo import build_slo_monitor

        self._slo = build_slo_monitor(
            registry=self.telemetry,
            run_dir=run_dir if jax.process_index() == 0 else None,
        )
        # device-profile trigger (docs/observability.md#profiling): the
        # request surface is jax-free and process-wide — SLO breaches,
        # watchdog dumps, anomaly dumps, /profilez, and the `profile` CLI
        # all arm captures through it; only this loop's poll() below
        # touches jax.profiler. Process 0 only for the artifact root —
        # captures are run-dir artifacts like flight dumps.
        self._profile_trigger = build_profile_trigger(
            registry=self.telemetry,
            run_dir=run_dir if jax.process_index() == 0 else None,
        )
        # absorb ProfilerCallback step windows into the trigger: the
        # config window becomes a scheduled capture (same budget, same
        # artifact naming) and the callback goes passive — one owner for
        # jax.profiler.start/stop_trace, so an SLO-fired capture can never
        # nest inside a config-window capture (jax raises on nesting)
        for cb in self.callbacks:
            window = getattr(cb, "profile_window", None)
            if callable(window):
                start_step, num_steps, trace_dir = window()
                self._profile_trigger.schedule(
                    start_step, num_steps,
                    trace_dir=trace_dir, max_steps=cfg.max_steps,
                )
                cb._absorbed = True
        # per-device HBM timeline (docs/observability.md#device-plane):
        # sampled on log steps into <run_dir>/hbm.jsonl + registry gauges
        self._hbm_timeline = HBMTimeline(
            run_dir=run_dir if jax.process_index() == 0 else None,
            registry=self.telemetry,
        )
        # live-telemetry exporter (docs/observability.md#live-telemetry):
        # /metrics (registry + ledger), /statusz (phase, step, segment),
        # /healthz (red on a stale watchdog beat). LLMT_METRICS_PORT=0/unset
        # disables; a port collision degrades to a warning, never a crash.
        from llm_training_tpu.resilience.elastic import segment_attempt
        from llm_training_tpu.telemetry.exporter import start_exporter

        self._exporter = start_exporter(
            registry=self.telemetry,
            ledger=self.ledger,
            watchdog=self._watchdog,
            slo=self._slo,
            profile=self._profile_trigger,
            status_fn=lambda: {
                "step": self.last_step,
                "segment": segment_attempt(),
            },
        )
        # trace sink (docs/observability.md#tracing): lifecycle events land
        # in <run_dir>/trace.jsonl; per-step spans only with
        # LLMT_TRACE_TRAIN=1. Process 0 only — run-dir artifacts follow the
        # JsonlLogger policy. attach_sink is False when another owner (a
        # bench stage) already holds the sink — then it keeps it.
        trace_attached = False
        if run_dir is not None and jax.process_index() == 0:
            trace_attached = get_tracer().attach_sink(run_dir / "trace.jsonl")
        try:
            with self.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
                return self._fit_inner(objective, datamodule, resume_step, state)
        finally:
            if self._exporter is not None:
                self._exporter.stop()
                self._exporter = None
            self._slo = None
            if self._profile_trigger is not None:
                # closes any dangling capture window (fit raised mid-trace)
                # and unpublishes the process-wide trigger so the next fit
                # — or a serve loop in the same process — starts clean
                self._profile_trigger.teardown()
                set_profile_trigger(None)
                self._profile_trigger = None
            self._hbm_timeline = None
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None
            if self._shutdown is not None:
                self._shutdown.uninstall()
                self._shutdown = None
            if trace_attached:
                get_tracer().detach_sink()
            uninstall_chaos()
            set_registry(previous_registry)
            # callbacks that alter process state (output tees, profiler
            # traces) must restore it even when fit raises mid-run
            for cb in self.callbacks:
                if hasattr(cb, "teardown"):
                    cb.teardown()

    def _fit_inner(self, objective, datamodule, resume_step, state) -> TrainState:
        cfg = self.config
        # host-side trace spans mirror the jax.profiler annotation sites
        # below (docs/observability.md#tracing): coarse lifecycle events
        # (compile, validation, checkpoint_save, segment boundaries) always
        # reach the sink; the per-micro-step data_load/train_step spans are
        # written only with LLMT_TRACE_TRAIN=1 — the ring records them
        # regardless, so the flight recorder has context on every crash
        tracer = get_tracer()
        trace_train = tracer.train_steps
        batches = datamodule.train_batches(start_step=0)
        sample_batch = next(batches)

        tx, schedule = self._build_tx(objective)

        dp_ways = self.mesh.shape["data"] * self.mesh.shape["fsdp"]
        batch_size = next(iter(sample_batch.values())).shape[0]
        self._global_batch_size = batch_size
        if batch_size % dp_ways != 0:
            # the reference's world-size divisibility assert (fsdp2_strategy.py:185-191)
            raise ValueError(
                f"global batch size {batch_size} must be divisible by "
                f"data*fsdp mesh ways ({dp_ways})"
            )

        # a pipe axis only does work when the model splits into matching
        # stages; a silent mismatch would replicate every computation
        # across it (pipe>1, stages=1) or pay GPipe bubbles for nothing
        pp_mesh = self.mesh.shape.get("pipe", 1)
        # check EVERY model the objective runs (DPO/ORPO carry a ref model
        # too — an unpipelined ref on a pipe mesh would replicate its whole
        # forward across the axis)
        models = {"model": getattr(objective, "model", None)}
        ref = getattr(objective, "ref_model", None)
        if ref is not None and ref is not models["model"]:
            models["ref_model"] = ref
        for name, model in models.items():
            if model is None:
                continue
            pp_model = getattr(getattr(model, "config", None), "pipeline_stages", 1)
            if pp_mesh > 1 and pp_model != pp_mesh:
                raise ValueError(
                    f"mesh pipeline_parallel_size={pp_mesh} but {name} has "
                    f"pipeline_stages={pp_model}; they must match (the pipe "
                    "axis shards the model's stage dimension)"
                )
            if pp_mesh == 1 and pp_model > 1:
                logger.warning(
                    "%s pipeline_stages=%d with no pipe mesh axis: the "
                    "GPipe schedule runs sequentially (debug mode) — its "
                    "bubbles cost throughput without parallelism",
                    name, pp_model,
                )
            if (
                pp_model > 1
                and self.mesh.shape.get("expert", 1) > 1
                and getattr(getattr(model, "config", None), "num_experts", None)
            ):
                # the EP dispatch is a shard_map, which cannot sit under
                # the pipeline's stage vmap; MoE under PP runs the plain
                # (ragged/dense/bucketed) dispatch with experts sharded
                # over fsdp/tensor like other params
                raise ValueError(
                    "pipeline_stages > 1 does not compose with "
                    "expert_parallel_size > 1 (shard_map under the stage "
                    "vmap); use fsdp/tensor sharding for the experts"
                )

        # the boxed (Partitioned-annotated) abstract tree exists only to
        # derive shardings; the canonical runtime state is unboxed
        abstract_boxed = self._abstract_state(objective, sample_batch, tx)
        self.state_shardings = self._state_shardings(abstract_boxed)
        abstract_state = nn.meta.unbox(abstract_boxed)
        self.abstract_state = abstract_state
        batch_shardings = _batch_shardings(sample_batch, self.mesh)

        # restore or initialize, directly into sharded buffers
        self._restored_meta = None
        if state is None and self.checkpointer is not None:
            try:
                restored = self.checkpointer.maybe_restore(
                    abstract_state, self.state_shardings, resume_step
                )
            except Exception as e:
                # the optimizer-state pytree LAYOUT depends on run settings
                # (blocked offload = per-leaf tuple; MultiSteps wraps the
                # tree), so flipping them across a resume cannot restore
                raise RuntimeError(
                    "checkpoint restore failed — note the optimizer-state "
                    "layout depends on offload_optimizer_state, "
                    "offload_state_dtype, offload_quant_block, "
                    "accumulate_grad_batches, and frozen_modules; resume "
                    "with the same settings the checkpoint was written with"
                ) from e
            if restored is not None:
                state, meta = restored
                self.counters.update(meta.get("counters", {}))
                self._restored_meta = meta
                # elastic data contract (docs/resilience.md#elastic): a
                # resume may change the replica count, never the global
                # batch the (seed, step) sample stream is keyed to — raise
                # under elastic, warn on the legacy path
                check_data_continuity(
                    meta.get("data_state"), batch_size,
                    elastic=cfg.resilience.elastic is not None,
                )
                if self.topology_plan is not None:
                    # the planner may have fallen back to the config (meta
                    # read failed, or restore fell back to an older step):
                    # never let orbax reshard model axes silently
                    from llm_training_tpu.resilience.elastic import (
                        verify_restored_topology,
                    )

                    verify_restored_topology(
                        self.topology_plan, meta.get("topology")
                    )
                # callback state riders (NanGuard EMA/z-score trackers):
                # without this every resume restarts the spike detector's
                # warmup blind — right when spikes are most likely
                self._load_callback_state(meta)
        pre_trained = (
            objective.pretrained_source()
            if hasattr(objective, "pretrained_source")
            else None
        )
        # init jits emit all-device buffers; offloaded (host-kind) leaves
        # move EAGERLY afterwards — a mixed-memory-kind out_shardings would
        # annotate every output, which some partitioners reject
        init_shardings = self.state_shardings
        if cfg.offload_optimizer_state:
            compute_kind, _ = offload_memory_kinds()
            init_shardings = jax.tree.map(
                lambda s: s.with_memory_kind(compute_kind), self.state_shardings
            )

        def init_state() -> TrainState:
            """Fresh sharded state (pretrained or seed-init) — the fit-start
            path, and the recovery rollback target when no committed
            checkpoint exists (both are deterministic in cfg.seed)."""
            if pre_trained and objective.config.load_weights:
                # stream HF weights straight into sharded buffers (reference
                # rank-0-load + broadcast, base_lm.py:175-193)
                logger.info("loading pre-trained weights from %s", pre_trained)
                dtypes = jax.tree.map(lambda leaf: leaf.dtype, abstract_state.params)
                params = objective.pretrained_params(self.state_shardings.params, dtypes)
                opt_state = jax.jit(
                    lambda p: self._opt_init(tx, p),
                    out_shardings=init_shardings.opt_state,
                )(params)
                return jax.device_put(
                    TrainState.create(params, opt_state, jax.random.key(cfg.seed + 1)),
                    self.state_shardings,
                )
            logger.info("initializing parameters on the mesh")

            def make_state(rng):
                params = objective.init_params(rng, sample_batch)
                opt_state = self._opt_init(tx, params)
                return nn.meta.unbox(
                    TrainState.create(params, opt_state, jax.random.key(cfg.seed + 1))
                )

            fresh = jax.jit(make_state, out_shardings=init_shardings)(
                jax.random.key(cfg.seed)
            )
            if cfg.offload_optimizer_state:
                fresh = jax.device_put(fresh, self.state_shardings)
            return fresh

        if state is None:
            state = init_state()

        # rollback-and-skip recovery (resilience/recovery.py): restore the
        # persisted skip-list/cooldown riders so a resumed run replays the
        # same data skips and LR; a restored cooldown window re-wraps the
        # schedule before the steps compile (layout untouched)
        recovery = None
        self._recovery = None
        if cfg.resilience.recovery is not None:
            recovery = RecoveryManager(
                cfg.resilience.recovery,
                registry=self.telemetry,
                metadata=(self._restored_meta or {}).get("recovery"),
            )
            self._recovery = recovery
            transform = recovery.schedule_transform()
            if transform is not None:
                tx, schedule = self._build_tx(objective, schedule_transform=transform)

        train_step = jax.jit(
            self._build_step(objective, tx),
            in_shardings=(self.state_shardings, batch_shardings),
            out_shardings=(self.state_shardings, None),
            donate_argnums=0,
        )
        # the instrumented step variant (health.every_n_steps): same update
        # math + per-layer health metrics; compiled separately so the plain
        # step (and therefore every non-health step) is byte-identical to a
        # health-off run. The grouping plan comes from the BOXED abstract
        # tree (Partitioned names identify scan-stacked leaves).
        health_every = cfg.health.every_n_steps
        health_step = None
        if health_every:
            self._param_groups = build_param_groups(abstract_boxed.params)
            health_step = jax.jit(
                self._build_health_step(objective, tx),
                in_shardings=(self.state_shardings, batch_shardings),
                out_shardings=(self.state_shardings, None),
                donate_argnums=0,
            )
        eval_step = jax.jit(
            self._build_eval_step(objective),
            in_shardings=(self.state_shardings, batch_shardings),
        )

        # AOT-compile the hot step up front: the compile lands in its own
        # goodput phase (and compile_time_s gauge) instead of skewing the
        # first step, and the Compiled object exposes XLA's cost/memory
        # analysis — the cross-check for the analytic MFU model. The jitted
        # callable stays as fallback (same avals/shardings, same semantics).
        # With health on EVERY optimizer step (and no accumulation) the
        # plain step would never execute — skip its compile entirely (the
        # health variant compiles on its first call, billed to the compile
        # phase) instead of burning a full XLA compile on dead code.
        aot_step = None
        plain_step_used = not (
            health_every == 1 and cfg.accumulate_grad_batches == 1
        )
        t_compile = time.perf_counter()
        with self.ledger.measure("compile"), \
                tracer.measure("train", "compile"):
            try:
                if plain_step_used:
                    aot_step = train_step.lower(state, sample_batch).compile()
                else:
                    logger.info(
                        "health.every_n_steps=1: skipping the plain-step AOT "
                        "compile (the health step variant runs every step)"
                    )
            except Exception as e:
                logger.info("AOT pre-compile unavailable (%s); compiling on first step", e)
        if aot_step is not None:
            self.telemetry.gauge("compile_time_s").set(time.perf_counter() - t_compile)
            for name, value in compiled_cost_gauges(aot_step).items():
                self.telemetry.gauge(name).set(value)
            # compute/comm attribution (docs/observability.md#device-plane):
            # walk the compiled step's HLO for collective payload bytes and
            # split them per mesh axis — the static comm fraction that
            # report and bench track across rounds
            for name, value in compiled_attribution_gauges(
                aot_step, self._mesh_axis_sizes()
            ).items():
                self.telemetry.gauge(name).set(value)
        step_fn = aot_step if aot_step is not None else train_step

        # state.step counts micro-steps (train_step invocations): resume
        # continues the data stream exactly where it stopped, independent of
        # the accumulation factor
        start_micro = int(jax.device_get(state.step))
        micro_steps = cfg.max_steps * cfg.accumulate_grad_batches
        # chaos SIGKILL only fires in runs that started from scratch, so a
        # supervisor's relaunch (resuming past a checkpoint) survives the
        # trigger step (chaos.maybe_sigkill, the supervise-gate contract)
        fresh_start = start_micro == 0

        for cb in self.callbacks:
            if hasattr(cb, "on_fit_start"):
                cb.on_fit_start(
                    self, objective, datamodule, start_micro // cfg.accumulate_grad_batches
                )

        self.should_stop = False
        self.abort_final_save = False
        self.last_step = None
        self.last_metrics = None
        self.last_health = None
        health_compiled = False
        self.last_seq_len = (
            sample_batch["input_ids"].shape[1] if "input_ids" in sample_batch else None
        )

        skip_list = recovery.skip_list if recovery is not None else None

        def data_stream(from_micro: int):
            # the skip-list keyword only reaches datamodules when recovery
            # is on — the default stream stays byte-identical to a
            # recovery-less build (and to subclasses overriding
            # train_batches with the historical signature)
            if skip_list is not None:
                return datamodule.train_batches(
                    start_step=from_micro, skip_list=skip_list
                )
            return datamodule.train_batches(start_step=from_micro)

        def run_segment(state: TrainState, seg_start: int) -> TrainState:
            """One recoverable stretch of the micro-step loop: from
            `seg_start` to completion (or a guard raise / stop request).
            The recovery path catches NanGuard errors around this, rolls
            the state back, and re-enters with a later-start segment —
            with recovery unset there is exactly one segment and the loop
            below is the whole fit, byte-identical to before."""
            nonlocal health_compiled, step_fn
            prefetcher = None
            tracer.instant(
                "train", "segment_start", micro=seg_start,
                step=seg_start // cfg.accumulate_grad_batches,
            )
            batches = data_stream(seg_start)
            # throughput window: (start time, start step). Reset after the
            # first optimizer step of this segment so JIT compile/warmup
            # never skews steps_per_sec (compile is its own telemetry gauge
            # + goodput phase).
            start_step0 = seg_start // cfg.accumulate_grad_batches
            first_process_step = start_step0 + 1
            window_time, window_step = time.perf_counter(), start_step0
            # SLO step-cadence anchor (host-observed optimizer-step
            # intervals); reset per segment so a resume's restore/compile
            # never bills as one giant slow step
            slo_step_t: float | None = None
            try:
                # constructed inside the try so an exception anywhere after
                # the worker thread starts still reaches prefetcher.close()
                if cfg.prefetch_batches > 0:
                    from llm_training_tpu.data.prefetch import DevicePrefetcher

                    watchdog = self._watchdog
                    prefetcher = DevicePrefetcher(
                        # an iterator FACTORY, not a bare iterator: data
                        # retries can then rebuild a closed generator at the
                        # batch being retried (docs/resilience.md)
                        lambda produced: data_stream(seg_start + produced),
                        batch_shardings,
                        depth=cfg.prefetch_batches,
                        host_aux_fn=self._batch_counts,
                        registry=self.telemetry,
                        retries=cfg.resilience.data_retries,
                        retry_backoff_s=cfg.resilience.data_retry_backoff_s,
                        heartbeat=(
                            (lambda: watchdog.beat("prefetcher")) if watchdog else None
                        ),
                    )
                    batches = iter(prefetcher)
                for micro in range(seg_start, micro_steps):
                    if self._watchdog is not None:
                        self._watchdog.beat("train_loop", step=micro)
                    with jax.profiler.StepTraceAnnotation("train", step_num=micro):
                        with self.ledger.measure("data_wait"), \
                                jax.profiler.TraceAnnotation("data_load"), \
                                tracer.measure(
                                    "train", "data_load",
                                    write=trace_train, step=micro,
                                ):
                            if prefetcher is not None:
                                batch, counts = next(batches)
                            else:
                                batch = next(batches)
                                counts = self._batch_counts(batch)
                        # health cadence: the instrumented variant runs on the
                        # optimizer steps `health.every_n_steps` selects (its jit
                        # recompiles per shape natively; first compile bills to
                        # the compile phase like the AOT step's)
                        use_health = (
                            health_step is not None
                            and (micro + 1) % cfg.accumulate_grad_batches == 0
                            and ((micro + 1) // cfg.accumulate_grad_batches)
                            % health_every == 0
                        )
                        # without the AOT pre-compile, the first invocation blocks
                        # on trace+compile — bill it to the compile phase
                        first_compiling = aot_step is None and micro == seg_start
                        phase = "compile" if first_compiling else "step_compute"
                        t_step = time.perf_counter()
                        if use_health:
                            health_phase = (
                                "compile" if not health_compiled else "step_compute"
                            )
                            with self.ledger.measure(health_phase), \
                                    jax.profiler.TraceAnnotation("train_step"):
                                state, metrics = health_step(state, batch)
                            if not health_compiled and aot_step is None:
                                # no plain-step AOT ran: the health compile IS
                                # the run's train-step compile
                                self.telemetry.gauge("compile_time_s").set(
                                    time.perf_counter() - t_step
                                )
                            health_compiled = True
                            first_compiling = False
                        else:
                            try:
                                with self.ledger.measure(phase), \
                                        jax.profiler.TraceAnnotation("train_step"):
                                    state, metrics = step_fn(state, batch)
                            except TypeError:
                                # the AOT executable is pinned to sample_batch's
                                # shapes; pad-to-longest collators emit variable
                                # sequence lengths. The mismatch raises BEFORE
                                # execution (donated buffers intact), so fall back
                                # permanently to the jitted callable, which
                                # recompiles per shape like it always did. The
                                # retry (jit trace + compile) bills to the compile
                                # phase; LATER new-shape recompiles are invisible
                                # inside the jit call and land in step_compute —
                                # the warning below is the flag that this is
                                # happening
                                if step_fn is train_step:
                                    raise
                                logger.warning(
                                    "AOT train step rejected batch shapes at "
                                    "micro step %d (variable-length batches?); "
                                    "falling back to jit recompilation", micro,
                                )
                                step_fn = train_step
                                with self.ledger.measure("compile"), \
                                        jax.profiler.TraceAnnotation("train_step"):
                                    state, metrics = step_fn(state, batch)
                        if first_compiling:
                            self.telemetry.gauge("compile_time_s").set(
                                time.perf_counter() - t_step
                            )
                        tracer.span(
                            "train", "train_step", t_step, time.perf_counter(),
                            write=trace_train, step=micro,
                        )

                    self._apply_counts(counts)

                    if (micro + 1) % cfg.accumulate_grad_batches != 0:
                        continue
                    step = (micro + 1) // cfg.accumulate_grad_batches
                    self.last_step = step
                    if self._slo is not None:
                        now_step = time.perf_counter()
                        if slo_step_t is not None:
                            self._slo.observe_step(
                                now_step - slo_step_t, step=step
                            )
                        slo_step_t = now_step
                    if self._profile_trigger is not None:
                        # AFTER the SLO observe above: a breach fired there
                        # arms a request, and this poll starts its capture
                        # on the very next statement — the profiled window
                        # begins at the first step after the breach
                        self._profile_trigger.poll(step)
                    # fresh (non-donated) device arrays; callbacks that need wall-
                    # clock accuracy can jax.block_until_ready(trainer.last_metrics)
                    self.last_metrics = metrics
                    if use_health:
                        # pull the health metrics to host and publish them as
                        # registry gauges: telemetry.jsonl, W&B, and `report` get
                        # them through the registry snapshot on log steps with no
                        # extra wiring, and NaN/spike provenance (nan_guard)
                        # reads the stash. The blocking fetch drains the dispatch
                        # queue, so it bills to step_compute like the log fetch —
                        # this sync IS the overhead bench.py's
                        # health_overhead_pct measures.
                        health_keys = [k for k in metrics if k.startswith("health/")]
                        with self.ledger.measure("step_compute"):
                            host = jax.device_get({k: metrics[k] for k in health_keys})
                        for key in health_keys:
                            del metrics[key]
                        self.last_health = {k: float(v) for k, v in host.items()}
                        for key, value in self.last_health.items():
                            self.telemetry.gauge(key).set(value)
                    for cb in self.callbacks:
                        # fires EVERY optimizer step (no metrics, no device sync);
                        # on_step_end below fires only on log steps with host metrics
                        if hasattr(cb, "on_train_step"):
                            cb.on_train_step(self, step)

                    if step % cfg.log_every_n_steps == 0 or step == cfg.max_steps:
                        # ONE batched transfer: per-value device_get pays one
                        # host<->device round trip per metric, which on a
                        # remote-attached TPU leaves the chip idle between steps.
                        # The blocking fetch drains the async dispatch queue, so
                        # its wall time is accumulated device step time —
                        # goodput bills it to step_compute
                        with self.ledger.measure("step_compute"):
                            metrics = {
                                k: np.asarray(v) for k, v in jax.device_get(metrics).items()
                            }
                        # divergence injection (chaos nan_step/spike_step):
                        # poison the HOST metrics the guards read — the
                        # device state stays healthy, which is exactly what
                        # the rollback-and-skip loop needs to prove on CPU
                        chaos = get_chaos()
                        if chaos is not None:
                            chaos.maybe_poison_metrics(
                                step, metrics, fresh_start=fresh_start
                            )
                        now = time.perf_counter()
                        metrics["lr"] = np.asarray(schedule(step))
                        metrics["steps_per_sec"] = (step - window_step) / max(
                            now - window_time, 1e-9
                        )
                        metrics.update(self.counters)
                        window_time, window_step = now, step
                        # telemetry rides the metrics dict: JSONL/W&B loggers
                        # persist the goodput breakdown, device gauges, and
                        # registry snapshot (compile_time_s, data/*, checkpoint/*)
                        metrics.update(self.ledger.summary())
                        if self._slo is not None:
                            # before the snapshot below, so this log step's
                            # record carries the fresh slo/* burn gauges
                            self._slo.observe_goodput(
                                float(metrics["goodput/goodput_pct"]), step=step
                            )
                        # per-device HBM sample: publishes the hbm/* gauges
                        # (worst device + per-device rollup) AND appends to
                        # the run dir's hbm.jsonl timeline in one pass
                        if self._hbm_timeline is not None:
                            metrics.update(self._hbm_timeline.sample(step))
                        else:
                            metrics.update(hbm_gauges())
                        metrics.update(self.telemetry.snapshot())
                        logger.info(
                            "step %d | loss %.4f | grad_norm %.3f | %.2f steps/s "
                            "| goodput %.1f%%",
                            step, metrics["loss"], metrics["grad_norm"],
                            metrics["steps_per_sec"], metrics["goodput/goodput_pct"],
                        )
                        for cb in self.callbacks:
                            if hasattr(cb, "on_step_end"):
                                cb.on_step_end(self, step, metrics)

                    if step == first_process_step:
                        # drop the compile/warmup-laden first step from the next
                        # throughput window (after its possible log above)
                        window_time, window_step = time.perf_counter(), step

                    if cfg.val_check_interval and step % cfg.val_check_interval == 0:
                        with self.ledger.measure("validation"), \
                                jax.profiler.TraceAnnotation("validation"), \
                                tracer.measure("train", "validation", step=step):
                            self._run_validation(eval_step, state, datamodule, step)

                    if (
                        self.checkpointer is not None
                        and cfg.checkpoint_every_n_steps
                        and step % cfg.checkpoint_every_n_steps == 0
                        # a guard may have flagged THIS step's state as diverged
                        # (on_step_end runs first) — never persist it
                        and not self.abort_final_save
                        # guards only see metrics on log steps; the save gate must
                        # not trust log cadence — check this step's loss directly
                        and self._loss_finite(metrics, step)
                    ):
                        with self.ledger.measure("checkpoint_save"), \
                                jax.profiler.TraceAnnotation("checkpoint_save"), \
                                tracer.measure(
                                    "train", "checkpoint_save", step=step
                                ):
                            self.checkpointer.save(
                                step, state, counters=dict(self.counters),
                                extra=self._save_extra(),
                            )
                        self._last_interval_save = step

                    # simulated failures (fault injection): a REAL SIGTERM to
                    # this process, so the whole handler -> boundary-check ->
                    # emergency-save path below is the one being exercised;
                    # or a SIGKILL — the hard death only `supervise` survives
                    chaos = get_chaos()
                    if chaos is not None:
                        # slow-step first: the injected dead time lands in
                        # the NEXT boundary's SLO interval like a real
                        # sustained regression would
                        chaos.maybe_slow_step(step)
                        chaos.maybe_sigterm(step)
                        chaos.maybe_sigkill(step, fresh_start)

                    if self._shutdown is not None and self._shutdown.should_stop(
                        step, cfg.resilience.preemption_sync_every_n_steps
                    ):
                        logger.warning(
                            "preemption (%s) at step %d: committing an emergency "
                            "checkpoint, then exiting resumable",
                            self._shutdown.reason, step,
                        )
                        self.telemetry.counter("resilience/preemptions").inc()
                        self._preempted = True
                        self.should_stop = True

                    if self.should_stop:
                        logger.info("stopping at step %d (callback request)", step)
                        break
                return state
            finally:
                if prefetcher is not None:
                    prefetcher.close()

        try:
            # the recovery driver: one segment with recovery unset; with it,
            # a NanGuard raise rolls the state back to the last committed
            # checkpoint, registers the poisoned data window, optionally
            # cools the LR, and re-enters — all without leaving the process
            # (docs/resilience.md#recovery). Budget exhaustion re-raises as
            # RecoveryExhaustedError (CLI exit 76).
            while True:
                try:
                    state = run_segment(state, start_micro)
                    break
                except (NonFiniteLossError, LossSpikeError) as failure:
                    if recovery is None:
                        raise
                    # raises RecoveryExhaustedError when the budget is spent
                    plan = recovery.on_failure(failure, self.last_step or 0)
                    # the traceback frames pin the (discarded) diverged
                    # state's buffers; clear them before the restore
                    # allocates a second copy
                    import traceback as _tb

                    _tb.clear_frames(failure.__traceback__)
                    state, start_micro = self._rollback_state(init_state)
                    failed_micro_end = plan.failed_step * cfg.accumulate_grad_batches
                    win_start, win_len = recovery.register_skip(
                        failed_micro_end, start_micro
                    )
                    logger.warning(
                        "recovery rollback %d/%d after %s at step %d: restored "
                        "micro-step %d, skipping data window [%d, %d), resuming "
                        "in-process",
                        plan.rollback_index, recovery.config.max_rollbacks,
                        type(failure).__name__, plan.failed_step, start_micro,
                        win_start, win_start + win_len,
                    )
                    # flight recorder: the ring holds the steps that led
                    # into the divergence — dump them next to the guard's
                    # anomaly-<step>.json before the loop re-enters
                    tracer.instant(
                        "resilience", "rollback",
                        failed_step=plan.failed_step,
                        restored_micro=start_micro,
                        rollback_index=plan.rollback_index,
                        failure=type(failure).__name__,
                    )
                    rollback_run_dir = resolve_run_dir(self)
                    if rollback_run_dir is not None:
                        tracer.flight_dump(
                            rollback_run_dir, f"rollback-{plan.failed_step}"
                        )
                    if self._profile_trigger is not None:
                        # matching-tag device profile of the re-entered
                        # steps: did the rollback actually clear the
                        # device-side pathology, or does the replayed
                        # window stall the same way?
                        self._profile_trigger.request(
                            f"rollback-{plan.failed_step}", source="rollback"
                        )
                    for cb in self.callbacks:
                        if hasattr(cb, "on_rollback"):
                            cb.on_rollback(
                                self, start_micro // cfg.accumulate_grad_batches
                            )
                    if recovery.register_cooldown(
                        start_micro // cfg.accumulate_grad_batches
                    ):
                        # re-wrap the LR schedule and rebuild the jitted
                        # steps against it. The opt-state LAYOUT is
                        # untouched (only the schedule closure changed), so
                        # the restored state drops straight in; the rebuilt
                        # step's first call recompiles (billed to the
                        # compile phase — aot_step is dropped).
                        tx, schedule = self._build_tx(
                            objective,
                            schedule_transform=recovery.schedule_transform(),
                        )
                        train_step = jax.jit(
                            self._build_step(objective, tx),
                            in_shardings=(self.state_shardings, batch_shardings),
                            out_shardings=(self.state_shardings, None),
                            donate_argnums=0,
                        )
                        if health_every:
                            health_step = jax.jit(
                                self._build_health_step(objective, tx),
                                in_shardings=(self.state_shardings, batch_shardings),
                                out_shardings=(self.state_shardings, None),
                                donate_argnums=0,
                            )
                            health_compiled = False
                        aot_step = None
                        step_fn = train_step
        finally:
            # the watchdog patrols the LOOP; the epilogue below legitimately
            # blocks on the final save + async barrier for however long the
            # checkpoint takes — a dump (or worse, an abort) mid-commit
            # would manufacture the very partial checkpoint it guards
            # against. fit's finally makes this stop idempotent.
            if self._watchdog is not None:
                self._watchdog.stop()

        final_save_committed = False
        if (
            self.checkpointer is not None
            and self.last_step is not None
            and not self.abort_final_save
            and self._loss_finite(self.last_metrics, self.last_step)
        ):
            # label with the step actually reached: an early stop
            # (should_stop) must not masquerade as a completed run
            with self.ledger.measure("checkpoint_save"), \
                    jax.profiler.TraceAnnotation("checkpoint_save"), \
                    tracer.measure(
                        "train", "checkpoint_save", step=self.last_step
                    ):
                # force=True: this step may collide with a stale/partial
                # entry from a PREVIOUS run of the same dir (the emergency-
                # save case) — but when THIS fit's interval save already
                # wrote the identical state, re-saving would be pure waste
                if self.last_step != self._last_interval_save:
                    if self._preempted:
                        self.telemetry.counter("resilience/emergency_saves").inc()
                    self.checkpointer.save(
                        self.last_step, state, counters=dict(self.counters),
                        force=True, extra=self._save_extra(),
                    )
                # the barrier: after this, the newest save (emergency or
                # interval) is durable — safe to exit
                self.checkpointer.wait()
                final_save_committed = True
        elif self.checkpointer is not None and self._preempted:
            # the emergency save was vetoed (diverged/non-finite state) —
            # still barrier any in-flight async interval save so what the
            # relaunch restores is durable before the resumable exit
            with self.ledger.measure("checkpoint_save"):
                self.checkpointer.wait()
        # one final telemetry record: the post-loop checkpoint save/wait
        # landed after the last log step, so without this flush every
        # logger's totals would miss that tail (report reads the last
        # telemetry record as the run total)
        if self.last_step is not None:
            counts = tracer.counts()
            self.telemetry.gauge("trace/events_recorded").set(counts["recorded"])
            self.telemetry.gauge("trace/events_written").set(counts["written"])
            self.telemetry.gauge("trace/flight_dumps").set(counts["flight_dumps"])
            tracer.flush()
            record = {
                **self.ledger.summary(),
                **hbm_gauges(),
                **self.telemetry.snapshot(),
            }
            for cb in self.callbacks:
                if hasattr(cb, "on_telemetry"):
                    cb.on_telemetry(self, self.last_step, record)
        for cb in self.callbacks:
            if hasattr(cb, "on_fit_end"):
                cb.on_fit_end(self, state)
        if self._preempted:
            # after the emergency checkpoint is durable and every logger is
            # flushed/closed: hand the supervisor contract up (the CLI maps
            # this to RESUMABLE_EXIT_CODE; relaunching `fit` resumes via
            # maybe_restore)
            saved = final_save_committed
            raise PreemptionInterrupt(
                self.last_step,
                f"preempted ({self._shutdown.reason if self._shutdown else 'signal'}) "
                f"at step {self.last_step}; "
                + (
                    "emergency checkpoint committed — relaunch fit with the "
                    "same config to resume"
                    if saved
                    else "NO resumable checkpoint written by this fit — a "
                    "relaunch resumes from the newest previous one, if any"
                ),
            )
        return state

    def _run_validation(self, eval_step, state, datamodule, step) -> None:
        losses, weights = [], []
        for i, batch in enumerate(datamodule.val_batches()):
            if self.config.limit_val_batches and i >= self.config.limit_val_batches:
                break
            if self._watchdog is not None:
                # a validation epoch can legitimately outlast the no-progress
                # timeout; each batch is progress
                self._watchdog.beat("train_loop", step=step)
            out = jax.device_get(eval_step(state, batch))
            losses.append(out["loss"])
            weights.append(out["target_tokens"])
        if losses:
            val_loss = float(np.average(losses, weights=weights))
            logger.info("step %d | val_loss %.4f", step, val_loss)
            for cb in self.callbacks:
                if hasattr(cb, "on_validation_end"):
                    cb.on_validation_end(self, step, {"val_loss": val_loss})

    @staticmethod
    def _loss_finite(metrics, step) -> bool:
        """True when this step's loss can be persisted. Forces a device sync,
        so it is called only on checkpoint steps — a diverged state must never
        become the newest checkpoint regardless of log cadence."""
        if metrics is None or "loss" not in metrics:
            return True
        loss = float(jax.device_get(metrics["loss"]))
        if np.isfinite(loss):
            return True
        logger.warning(
            "skipping checkpoint at step %d: non-finite loss %s", step, loss
        )
        return False

    @staticmethod
    def _batch_counts(batch: dict) -> tuple[int, int]:
        """(samples, tokens) from the HOST-side numpy batch; handles both CLM
        batches (`input_ids`) and preference batches
        (`chosen_/rejected_input_ids`). Must run before device placement —
        on a device copy it would force a blocking sync each step."""
        id_keys = [k for k in batch if k == "input_ids" or k.endswith("_input_ids")]
        first = batch[id_keys[0]]
        samples = int(first.shape[0])
        tokens = 0
        for key in id_keys:
            prefix = key[: -len("input_ids")]
            seg = batch.get(prefix + "segment_ids")
            tokens += int((seg > 0).sum()) if seg is not None else int(batch[key].size)
        return samples, tokens

    def _apply_counts(self, counts: tuple[int, int]) -> None:
        self.counters["consumed_samples"] += counts[0]
        self.counters["consumed_tokens"] += counts[1]

    # ------------------------------------------------------------ recovery

    def _rollback_state(self, init_state_fn: Callable) -> tuple[TrainState, int]:
        """Rewind to the last committed checkpoint (consumed counters and
        callback state included — replayed steps must not double-count),
        or to a deterministic fresh init when nothing was ever committed.
        Returns (state, micro-step to resume from)."""
        if self.checkpointer is not None:
            # barrier any in-flight async save first: the newest commit is
            # the rollback target, not a half-written step
            with self.ledger.measure("checkpoint_save"):
                self.checkpointer.wait()
            restored = self.checkpointer.maybe_restore(
                self.abstract_state, self.state_shardings
            )
            if restored is not None:
                state, meta = restored
                self.counters = {"consumed_samples": 0, "consumed_tokens": 0}
                self.counters.update(meta.get("counters", {}))
                self._load_callback_state(meta)
                return state, int(jax.device_get(state.step))
        logger.warning(
            "recovery: no committed checkpoint to roll back to — "
            "re-initializing from step 0"
        )
        self.counters = {"consumed_samples": 0, "consumed_tokens": 0}
        return init_state_fn(), 0

    def _save_extra(self) -> dict:
        """JSON-serializable checkpoint-metadata riders: the recovery
        skip-list/cooldown windows (a resumed run must replay the same
        skips), the live topology + data-stream cursor (what an elastic
        relaunch plans its new mesh against — docs/resilience.md#elastic),
        and every callback's `state_dict` (NanGuard's EMA/z-score trackers
        and counters)."""
        extra: dict = {}
        if self._recovery is not None:
            extra["recovery"] = self._recovery.metadata()
        if self.mesh is not None:
            extra["topology"] = {
                "device_count": int(self.mesh.devices.size),
                "mesh": self._mesh_axis_sizes(),
            }
            if self._global_batch_size:
                micro = (self.last_step or 0) * self.config.accumulate_grad_batches
                dp_ways = int(self.mesh.shape["data"]) * int(self.mesh.shape["fsdp"])
                extra["data_state"] = {
                    # the stream key an elastic resume must hold fixed
                    "global_batch_size": int(self._global_batch_size),
                    # samples drawn from the global stream so far: the
                    # cursor is step-derived, NOT replica-derived, which is
                    # exactly why a DP resize replays the same stream
                    "sample_cursor": micro * int(self._global_batch_size),
                    # rows each data-parallel shard served under THIS
                    # topology (informational: the next segment derives its
                    # own stride from the same global batch)
                    "replica_stride": int(self._global_batch_size) // dp_ways,
                }
        cb_state: dict = {}
        for cb in self.callbacks:
            fn = getattr(cb, "state_dict", None)
            if callable(fn):
                try:
                    cb_state[type(cb).__name__] = fn()
                except Exception:
                    logger.exception(
                        "callback %s state_dict failed (not persisted)",
                        type(cb).__name__,
                    )
        if cb_state:
            extra["callbacks"] = cb_state
        return extra

    def _load_callback_state(self, meta: dict | None) -> None:
        """Restore callback state riders from checkpoint metadata (keyed by
        callback class name; absent entries and failures leave the callback
        at its fresh-construction state)."""
        states = (meta or {}).get("callbacks") or {}
        for cb in self.callbacks:
            data = states.get(type(cb).__name__)
            if data is not None and hasattr(cb, "load_state_dict"):
                try:
                    cb.load_state_dict(data)
                except Exception:
                    logger.exception(
                        "callback %s load_state_dict failed (starting fresh)",
                        type(cb).__name__,
                    )

    # ------------------------------------------------------------ validate

    def restore_for_inference(
        self,
        objective,
        resume_step: int | None = None,
        sample_batch: dict | None = None,
    ) -> TrainState:
        """READ-ONLY restore for the inference/eval CLIs (`generate`,
        `evaluate` — docs/inference.md): build the mesh and the abstract
        train state exactly as `fit` would (the optimizer-state pytree
        layout depends on the trainer settings, so the SAME TrainerConfig
        the checkpoint was written under must be used), then restore the
        newest (or given) step straight into sharded buffers with
        repair=False — an inference run must never delete or repair
        anything in the checkpoint directory. Leaves `self.mesh` /
        `self.state_shardings` populated for the caller's own jits.

        `sample_batch` feeds the objective's init_params shape evaluation;
        objectives whose init reads non-CLM keys (DPO/ORPO use
        `chosen_input_ids`) must pass a real batch — the CLM-shaped
        synthetic default only suits single-model causal-LM objectives."""
        if self.checkpointer is None:
            raise ValueError("restore_for_inference requires a checkpointer")
        self.mesh = build_mesh(self.config.mesh, self.devices)
        with self.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
            if sample_batch is None:
                # parameter shapes are sequence-length independent, so a
                # synthetic batch is enough to shape-evaluate the state
                sample_batch = {"input_ids": np.zeros((1, 8), np.int32)}
            tx, _ = self._build_tx(objective)
            abstract_boxed = self._abstract_state(objective, sample_batch, tx)
            self.state_shardings = self._state_shardings(abstract_boxed)
            abstract_state = nn.meta.unbox(abstract_boxed)
            self.abstract_state = abstract_state
            restored = self.checkpointer.maybe_restore(
                abstract_state, self.state_shardings, resume_step, repair=False
            )
            if restored is None:
                raise ValueError(
                    f"no checkpoint found in {self.checkpointer.directory}"
                )
            state, _ = restored
            return state

    def validate_from_checkpoint(
        self, objective, datamodule, resume_step: int | None = None
    ) -> dict[str, float]:
        """Restore the latest (or given) checkpoint and run validation
        (the CLI `validate` subcommand, reference `llm-training validate`)."""
        datamodule.setup()
        # a REAL batch, not the synthetic default: DPO/ORPO objectives
        # shape-evaluate from preference keys (chosen_/rejected_input_ids)
        sample_batch = next(datamodule.train_batches())
        state = self.restore_for_inference(
            objective, resume_step, sample_batch=sample_batch
        )
        with self.mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
            eval_step = jax.jit(
                self._build_eval_step(objective),
                in_shardings=(self.state_shardings, _batch_shardings(sample_batch, self.mesh)),
            )
            losses, weights = [], []
            limit = self.config.limit_val_batches
            for i, batch in enumerate(datamodule.val_batches()):
                if limit and i >= limit:
                    break
                out = jax.device_get(eval_step(state, batch))
                losses.append(out["loss"])
                weights.append(out["target_tokens"])
        if not losses:
            raise ValueError("datamodule produced no validation batches")
        result = {"val_loss": float(np.average(losses, weights=weights))}
        logger.info("validate: %s", result)
        return result

    def validate(self, objective, datamodule, state: TrainState) -> dict[str, float]:
        datamodule.setup()
        mesh = self.mesh or build_mesh(self.config.mesh, self.devices)
        # same sharding discipline as fit/validate_from_checkpoint: explicit
        # in_shardings (state shardings from fit if available, else the live
        # arrays' own shardings)
        state_shardings = (
            self.state_shardings
            if self.state_shardings is not None
            else jax.tree.map(lambda x: x.sharding, state)
        )
        with mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
            eval_step = None
            losses, weights = [], []
            for batch in datamodule.val_batches():
                if eval_step is None:
                    eval_step = jax.jit(
                        self._build_eval_step(objective),
                        in_shardings=(state_shardings, _batch_shardings(batch, mesh)),
                    )
                out = jax.device_get(eval_step(state, batch))
                losses.append(out["loss"])
                weights.append(out["target_tokens"])
        if not losses:
            raise ValueError(
                "datamodule produced no validation batches "
                "(set validation_split or provide a val dataset)"
            )
        return {"val_loss": float(np.average(losses, weights=weights))}
