"""Run harness.

Capability parity: reference `src/llm_training/lightning/` — the Lightning
Trainer + strategies collapse into a single SPMD loop: one jitted train step
over a named mesh, GSPMD doing what FSDP2Strategy/DeepSpeedStrategy did with
explicit collectives. Callbacks/loggers/checkpointing attach to this loop.
"""

from llm_training_tpu.trainer.state import TrainState
from llm_training_tpu.trainer.trainer import Trainer, TrainerConfig

__all__ = ["TrainState", "Trainer", "TrainerConfig"]
