"""Sharded checkpointing with embedded config + resume.

Capability parity: reference checkpoint subsystem (SURVEY.md §3.3/§5.4):
- sharded-native save ≙ DCP dirs (`fsdp2_strategy.py:376-386`) — orbax
  writes each host's shards; restore streams directly into sharded buffers
- `meta.pt` with loop/counter state ≙ the metadata JSON (step, consumed
  counters)
- config embedded in every checkpoint (`save_config_callback.py:43-45`) so
  export can rebuild the model without the original YAML
- mid-epoch resume: `TrainState.step` counts micro-steps and the data
  stream is a pure function of (seed, step) — no batch skipping
  (cf. `resumable_dataloader.py:20-25`, which replays O(skipped) batches)
- async save (orbax background thread) with `wait()` barrier

Durability (docs/resilience.md#durability): transient I/O errors during
save are retried with exponential backoff; async-save failures surface at
the NEXT save point instead of silently waiting for the next `wait()`.
Each committed step gets an integrity manifest (sha256 + size per payload
file, written by `resilience.durability` tmp-then-rename) and restore runs
verify-before-restore (`checkpoint.verify: off|fast|full`): a step whose
bytes disagree with its manifest is healed from the mirror
(`LLMT_CKPT_MIRROR_DIR` / `checkpoint.mirror_dir`, kept warm by a
background `MirrorDaemon`) or skipped — restore falls back
primary→mirror→older-step, each leg counted. Force-overwrites stage the
old step under `.stale/` before orbax's delete-then-save, so a SIGKILL
inside the swap leaves a promotable durable copy; and the post-fallback
repair deletes a step only when its manifest verification FAILED —
an environmental restore error (permissions, layout mismatch) on bytes
that hash clean must not destroy a good checkpoint.
"""

from __future__ import annotations

import logging
import os
from pathlib import Path
from typing import Any, Literal

import jax
import orbax.checkpoint as ocp
from pydantic import BaseModel, ConfigDict

from llm_training_tpu.trainer.state import TrainState

logger = logging.getLogger(__name__)


class CheckpointConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    dirpath: str | None = None
    max_to_keep: int = 3
    async_save: bool = True
    save_on_exit: bool = True
    # transient-I/O retries around the blocking part of save (serialize +
    # handoff; the whole write when async_save=False)
    save_retries: int = 3
    retry_backoff_s: float = 0.5
    retry_backoff_max_s: float = 30.0
    # verify-before-restore mode (docs/resilience.md#durability): `fast`
    # checks the file set + sizes against the step's manifest, `full`
    # additionally re-hashes every payload file, `off` restores blind
    # (legacy behavior). The post-fallback repair classification always
    # consults manifests regardless of this knob.
    verify: Literal["off", "fast", "full"] = "fast"
    # async mirror target (LLMT_CKPT_MIRROR_DIR overrides); None disables
    # mirroring, healing, and the scrubber
    mirror_dir: str | None = None
    mirror_interval_s: float = 2.0
    # mirror-side retention: keep the newest `mirror_keep_last` steps plus
    # every step divisible by `mirror_keep_every` — and never the newest
    # committed step or a copy that is the last intact one
    mirror_keep_last: int = 3
    mirror_keep_every: int | None = None
    # background scrubber cadence: re-verify (full) one retained step per
    # interval, alternating primary/mirror; <= 0 disables
    scrub_interval_s: float = 60.0


def _pack(state: TrainState) -> Any:
    """Typed PRNG keys are not serializable; ship raw key data."""
    return state.replace(rng=jax.random.key_data(state.rng))


def _unpack(state: TrainState) -> TrainState:
    return state.replace(rng=jax.random.wrap_key_data(state.rng))


class Checkpointer:
    def __init__(self, config: CheckpointConfig, run_config: dict | None = None):
        if config.dirpath is None:
            raise ValueError("CheckpointConfig.dirpath is required")
        self.config = config
        self.run_config = run_config or {}
        # world size / launcher env / git rev, captured once at run start
        # (reference save_config_callback.py:15-41) — embedded in every save
        from llm_training_tpu.run_metadata import collect_run_metadata

        self.run_metadata = collect_run_metadata()
        self.directory = Path(config.dirpath).absolute()
        self._primary_host = jax.process_index() == 0
        if self._primary_host:
            # a predecessor SIGKILLed inside a force-save swap leaves the
            # old step parked under `.stale/` with no committed
            # replacement — put it back BEFORE orbax scans the directory
            from llm_training_tpu.resilience import durability

            durability.promote_stale_steps(self.directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                enable_async_checkpointing=config.async_save,
            ),
            item_names=("state", "meta"),
        )
        # newest save launched but not yet confirmed committed (async mode);
        # wait() logs the commit once the barrier passes
        self._inflight_step: int | None = None
        # committed steps still owed a manifest (flushed once orbax's
        # background write finishes — a manifest must hash FINAL bytes)
        self._pending_manifest: set[int] = set()
        mirror_raw = os.environ.get("LLMT_CKPT_MIRROR_DIR") or config.mirror_dir
        self.mirror_dir = Path(mirror_raw).absolute() if mirror_raw else None
        self._mirror = None
        if self.mirror_dir is not None and self._primary_host:
            from llm_training_tpu.resilience.durability import MirrorDaemon

            self._mirror = MirrorDaemon(
                self.directory,
                self.mirror_dir,
                interval_s=config.mirror_interval_s,
                keep_last=config.mirror_keep_last,
                keep_every=config.mirror_keep_every,
                scrub_interval_s=config.scrub_interval_s,
            ).start()

    def check_errors(self) -> None:
        """Surface a failed async save NOW (orbax parks background-thread
        errors until `wait_until_finished` — without this probe a failure
        stays invisible until the next barrier, which may be the end of
        fit, silently widening the window of unpersisted work)."""
        self.manager.check_for_errors()

    def save(
        self,
        step: int,
        state: TrainState,
        counters: dict[str, int] | None = None,
        force: bool = False,
        extra: dict | None = None,
    ) -> None:
        # surface a parked async failure even when THIS call dedupes away —
        # "failures surface at the next save point" must include skipped ones
        self.check_errors()
        # a previous async save may have committed since the last barrier:
        # its manifest is writable now (and the mirror can pick it up)
        self._flush_manifests()
        if step in self.manager.all_steps() and not force:
            return  # e.g. end-of-fit save colliding with an interval save
        meta = {
            "step": step,
            "counters": counters or {},
            "config": self.run_config,
            "run_metadata": self.run_metadata,
            # JSON-serializable run-state riders: the recovery skip list /
            # cooldown windows and callback state (NanGuard EMA) — what a
            # resume needs beyond the array tree (docs/resilience.md)
            **(extra or {}),
        }
        from llm_training_tpu.resilience import RetryPolicy, chaos_point, retry_call
        from llm_training_tpu.resilience import durability
        from llm_training_tpu.resilience.chaos import get_chaos
        from llm_training_tpu.telemetry import get_registry

        registry = get_registry()
        policy = RetryPolicy(
            max_retries=self.config.save_retries,
            backoff_base_s=self.config.retry_backoff_s,
            backoff_max_s=self.config.retry_backoff_max_s,
        )

        def _save(attempt: int) -> None:
            chaos_point("checkpoint_save", step=step)
            # force-overwrite path (emergency save over a stale/partial
            # entry, or a retry after a mid-write failure): orbax refuses
            # to save over a finalized step and has no atomic replace, so
            # the old step must be dropped first. Before dropping it, park
            # a hardlink clone (+ manifest) under `.stale/<step>` — the
            # durable copy that keeps a SIGKILL inside the delete→commit
            # window from losing the step entirely; the staged copy is
            # cleared only after the replacement's commit AND manifest
            # land (`_flush_manifests`), and a relaunch promotes it back
            # when the replacement never committed (`promote_stale_steps`)
            if step in self.manager.all_steps():
                if self._primary_host:
                    durability.stage_stale_step(self.directory, step)
                self.manager.delete(step)
                chaos = get_chaos()
                if chaos is not None:
                    # the SIGKILL-in-swap chaos leg: die exactly inside
                    # the old no-durable-copy window
                    chaos.maybe_ckpt_kill_in_swap(step)
            # force here only bypasses the save-interval policy; a failed
            # attempt's partial (unfinalized) dir is cleared by orbax itself
            self.manager.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(_pack(state)),
                    meta=ocp.args.JsonSave(meta),
                ),
                force=force or attempt > 0,
            )

        # with async_save this times only the blocking handoff (serialize +
        # background-thread launch); wait() below captures the barrier
        with registry.timer("checkpoint/save").time():
            retry_call(
                _save, policy,
                label=f"checkpoint save (step {step})",
                counter=registry.counter("checkpoint/retries"),
            )
        self._pending_manifest.add(step)
        if self.config.async_save:
            self._inflight_step = step
            logger.info(
                "checkpoint save started at step %d -> %s (async; durable "
                "after the wait() barrier)", step, self.directory,
            )
        else:
            self._flush_manifests()
            logger.info(
                "checkpoint committed at step %d -> %s", step, self.directory
            )

    def _flush_manifests(self) -> None:
        """Write the manifest for every pending committed step (process 0
        only, and only while no async save is mid-write — a manifest must
        hash the step's FINAL bytes). Clears the step's staged `.stale/`
        copy (its replacement is now durable + manifested) and wakes the
        mirror daemon."""
        if not self._primary_host:
            self._pending_manifest.clear()
            return
        if not self._pending_manifest:
            return
        if self.manager.is_saving_in_progress():
            return
        from llm_training_tpu.resilience import durability
        from llm_training_tpu.resilience.chaos import get_chaos
        from llm_training_tpu.telemetry import get_registry

        registry = get_registry()
        for step in sorted(self._pending_manifest):
            sdir = durability.step_dir(self.directory, step)
            if not sdir.is_dir():
                self._pending_manifest.discard(step)  # GC'd before flush
                continue
            with registry.timer("checkpoint/manifest").time():
                manifest = durability.build_manifest(sdir, step)
                durability.write_manifest(self.directory, step, manifest)
            durability.clear_stale_step(self.directory, step)
            self._pending_manifest.discard(step)
            chaos = get_chaos()
            if chaos is not None:
                # the targeted (`mode:step`) corruption form fires here —
                # post-commit, post-manifest, BEFORE the mirror copies the
                # step, so the mirror-side re-verification must reject it
                chaos.maybe_corrupt_checkpoint(self.directory, step)
        if self._mirror is not None:
            self._mirror.notify()

    def _record_verify_failure(self, result) -> None:
        from llm_training_tpu.telemetry import get_registry

        get_registry().counter("checkpoint/verify_failures").inc()
        for finding in result.findings:
            logger.warning(
                "checkpoint verification failed in %s: %s",
                self.directory, finding,
            )

    def _heal_from_mirror(self, step: int) -> bool:
        """Replace a corrupt primary step with the mirror's copy — but only
        after the mirror copy itself passes FULL verification (healing from
        a rotten mirror would just move the corruption). Counted as the
        restore's mirror leg (`checkpoint/mirror_restores`)."""
        if self.mirror_dir is None:
            return False
        from llm_training_tpu.resilience import durability
        from llm_training_tpu.telemetry import get_registry

        mirror_check = durability.verify_step(self.mirror_dir, step, mode="full")
        if not mirror_check.ok:
            for finding in mirror_check.findings:
                logger.warning(
                    "mirror copy unusable for healing (%s): %s",
                    self.mirror_dir, finding,
                )
            return False
        try:
            tmp = self.directory / f".tmp-heal-{step}"
            durability.clone_tree(durability.step_dir(self.mirror_dir, step), tmp)
            durability._replace_dir(tmp, durability.step_dir(self.directory, step))
            manifest = durability.load_manifest(self.mirror_dir, step)
            durability.write_manifest(self.directory, step, manifest)
            self.manager.reload()  # orbax caches its directory view
        except OSError as e:
            logger.warning(
                "healing step %d from mirror %s failed: %s",
                step, self.mirror_dir, e,
            )
            return False
        get_registry().counter("checkpoint/mirror_restores").inc()
        logger.warning(
            "healed checkpoint step %d from mirror %s", step, self.mirror_dir
        )
        return True

    def maybe_restore(
        self,
        abstract_state: Any,
        shardings: Any,
        step: int | None = None,
        repair: bool = True,
    ) -> tuple[TrainState, dict] | None:
        """Restore the latest (or given) step straight into sharded buffers.
        Returns None when no checkpoint exists. When no explicit step is
        requested, each candidate is verified against its integrity
        manifest first (`checkpoint.verify`, docs/resilience.md#durability)
        and on failure healed from the mirror or skipped; a restore
        exception likewise falls back to the next older retained step —
        losing a few steps of progress beats crash-looping the relaunch.
        An EXPLICIT step request never falls back (the caller asked for
        that state, not "something close to it"); and if every retained
        step fails, the first error is re-raised so a systematic problem
        (e.g. an optimizer-layout mismatch) keeps its diagnosis.

        `repair=True` (the fit path) deletes fallen-back steps ONLY when
        their manifest verification failed — bytes that hash clean mean
        the restore error was environmental (permissions, layout
        mismatch) and deleting would destroy a good checkpoint. Steps
        with no manifest (pre-durability legacy saves) keep the old
        delete-on-fallback behavior, logged as unverifiable. Read-only
        callers (the `validate` CLI) pass False — an observation must
        not mutate the checkpoint directory."""
        explicit = step is not None
        candidates = (
            [step] if explicit else sorted(self.manager.all_steps(), reverse=True)
        )
        if not candidates:
            return None
        abstract = jax.tree.map(
            lambda leaf, sharding: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=sharding
            ),
            _strip(abstract_state),
            shardings,
        )
        abstract = _pack_abstract(abstract)
        from llm_training_tpu.resilience import RetryPolicy, durability, is_transient, retry_call
        from llm_training_tpu.telemetry import get_registry

        registry = get_registry()
        # transient I/O during restore is retried like it is during save —
        # without this, a one-off storage blip would be misclassified as
        # corruption and the (perfectly good) newest step deleted below.
        # FileNotFoundError is excluded: a missing payload file is the
        # corruption signature, and no amount of retrying conjures it back
        policy = RetryPolicy(
            max_retries=self.config.save_retries,
            backoff_base_s=self.config.retry_backoff_s,
            backoff_max_s=self.config.retry_backoff_max_s,
        )

        def _restore_transient(e: BaseException) -> bool:
            return is_transient(e) and not isinstance(e, FileNotFoundError)

        def _restore(candidate: int):
            return retry_call(
                lambda attempt: self.manager.restore(
                    candidate,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(abstract),
                        meta=ocp.args.JsonRestore(),
                    ),
                ),
                policy,
                label=f"checkpoint restore (step {candidate})",
                counter=registry.counter("checkpoint/retries"),
                transient=_restore_transient,
            )

        def _fall_back(candidate: int, why: str) -> None:
            registry.counter("resilience/restore_fallbacks").inc()
            logger.warning(
                "checkpoint step %d in %s %s; falling back to the previous "
                "retained step", candidate, self.directory, why,
            )

        first_error: Exception | None = None
        corrupt: list[int] = []  # FAILED manifest verification → repairable
        legacy: list[int] = []  # no manifest + failed restore → legacy delete
        for candidate in candidates:
            healed = False
            if self.config.verify != "off" and not explicit:
                check = durability.verify_step(
                    self.directory, candidate, mode=self.config.verify
                )
                if check.verifiable and not check.ok:
                    self._record_verify_failure(check)
                    healed = self._heal_from_mirror(candidate)
                    if not healed:
                        corrupt.append(candidate)
                        _fall_back(candidate, "failed manifest verification")
                        continue
            restored = None
            for on_healed_bytes in (False, True):
                try:
                    restored = _restore(candidate)
                    break
                except Exception as e:
                    if explicit:
                        raise
                    if first_error is None:
                        first_error = e
                    if on_healed_bytes or healed:
                        # already restoring a verified-clean mirror copy —
                        # a second failure is not a byte problem
                        logger.warning(
                            "restore of healed step %d still failed (%s)",
                            candidate, e,
                        )
                        _fall_back(candidate, f"failed restore after healing ({e})")
                        break
                    # classify before condemning: a restore error is only
                    # corruption when the bytes disagree with the manifest
                    check = durability.verify_step(
                        self.directory, candidate, mode="full"
                    )
                    if not check.verifiable:
                        legacy.append(candidate)
                        _fall_back(
                            candidate,
                            f"failed restore with no manifest to verify "
                            f"against (unverifiable legacy step; {e})",
                        )
                        break
                    if check.ok:
                        # bytes hash clean: environmental failure — the
                        # step is preserved (never deleted) and the next
                        # older step gets its chance
                        _fall_back(
                            candidate,
                            f"failed restore but verifies clean against its "
                            f"manifest (environmental error, step "
                            f"preserved: {e})",
                        )
                        break
                    self._record_verify_failure(check)
                    healed = self._heal_from_mirror(candidate)
                    if not healed:
                        corrupt.append(candidate)
                        _fall_back(candidate, "failed manifest verification")
                        break
            if restored is None:
                continue
            logger.info(
                "restored checkpoint step %d from %s", candidate, self.directory
            )
            # drop the unrestorable newer steps: left in place they would
            # (a) stay the "newest" checkpoint every later restore has to
            # fall back past, and (b) make the resumed run's interval save
            # at the same step skip via the already-exists early return —
            # the corruption would never be repaired. Delete-eligible are
            # ONLY verified-corrupt steps and unverifiable legacy steps —
            # never a step whose bytes hash clean against its manifest
            for bad in (corrupt + legacy) if repair else ():
                try:
                    self.manager.delete(bad)
                    from llm_training_tpu.resilience.durability import (
                        manifest_path,
                    )

                    mpath = manifest_path(self.directory, bad)
                    if self._primary_host and mpath.exists():
                        mpath.unlink()
                    logger.warning(
                        "deleted unrestorable checkpoint step %d (%s)",
                        bad,
                        "verified corrupt" if bad in corrupt
                        else "unverifiable legacy step",
                    )
                except Exception as e:
                    logger.warning(
                        "could not delete unrestorable checkpoint step %d "
                        "(%s); later restores will keep falling back past it",
                        bad, e,
                    )
            return _unpack(restored["state"]), restored["meta"]
        raise RuntimeError(
            f"all retained checkpoint steps {candidates} in {self.directory} "
            "failed to restore"
        ) from first_error

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def read_meta(self, step: int | None = None) -> dict | None:
        """The JSON metadata of `step` (newest when None) WITHOUT restoring
        the array state — the elastic topology planner reads the recorded
        mesh degrees before the mesh (and therefore the shardings the full
        restore needs) exists. Read-only and failure-tolerant: any error
        returns None (the planner then falls back to the config alone and
        the real restore reports the problem with full context)."""
        if step is None:
            step = self.manager.latest_step()
        if step is None:
            return None
        try:
            restored = self.manager.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
            )
            return restored["meta"]
        except Exception as e:
            logger.warning(
                "could not read checkpoint metadata for step %s in %s (%s)",
                step, self.directory, e,
            )
            return None

    def wait(self) -> None:
        from llm_training_tpu.telemetry import get_registry

        with get_registry().timer("checkpoint/wait").time():
            self.manager.wait_until_finished()
        self._flush_manifests()
        if self._mirror is not None:
            # the run must not end (or roll back) with its newest step
            # unmirrored — this is the mirror's durability barrier; timed
            # so the durability smoke can price the critical-path cost
            with get_registry().timer("checkpoint/mirror_drain").time():
                self._mirror.drain()
        from llm_training_tpu.resilience import durability
        from llm_training_tpu.resilience.chaos import get_chaos

        chaos = get_chaos()
        if chaos is not None and self._primary_host:
            steps = durability.committed_steps(self.directory)
            if steps:
                # the untargeted corruption form fires here — after the
                # mirror drained, so the restore's mirror leg has a clean
                # copy to land on
                chaos.maybe_corrupt_checkpoint(
                    self.directory, steps[-1], at_final_barrier=True
                )
        if self._inflight_step is not None:
            logger.info(
                "checkpoint committed at step %d -> %s",
                self._inflight_step, self.directory,
            )
            self._inflight_step = None

    def close(self) -> None:
        # a fast exit (preemption grace window, early return) must not drop
        # an in-flight async save — barrier first, then release resources
        try:
            self.wait()
        finally:
            if self._mirror is not None:
                self._mirror.stop()
            self.manager.close()


def _strip(abstract_state: Any) -> Any:
    """Drop flax Partitioned boxes from an eval_shape tree, keeping plain
    ShapeDtypeStructs (orbax needs the same structure as the saved tree)."""
    import flax.linen as nn

    return nn.meta.unbox(abstract_state)


def _pack_abstract(abstract_state: TrainState) -> Any:
    """Mirror _pack for the abstract tree: rng key -> raw key data shape."""
    rng = abstract_state.rng
    # key_data of a typed key scalar is uint32[4] (threefry) — derive via eval_shape
    rng_data = jax.eval_shape(jax.random.key_data, jax.random.key(0))
    sharding = getattr(rng, "sharding", None)
    return abstract_state.replace(
        rng=jax.ShapeDtypeStruct(rng_data.shape, rng_data.dtype, sharding=sharding)
    )
