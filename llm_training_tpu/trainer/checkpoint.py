"""Sharded checkpointing with embedded config + resume.

Capability parity: reference checkpoint subsystem (SURVEY.md §3.3/§5.4):
- sharded-native save ≙ DCP dirs (`fsdp2_strategy.py:376-386`) — orbax
  writes each host's shards; restore streams directly into sharded buffers
- `meta.pt` with loop/counter state ≙ the metadata JSON (step, consumed
  counters)
- config embedded in every checkpoint (`save_config_callback.py:43-45`) so
  export can rebuild the model without the original YAML
- mid-epoch resume: `TrainState.step` counts micro-steps and the data
  stream is a pure function of (seed, step) — no batch skipping
  (cf. `resumable_dataloader.py:20-25`, which replays O(skipped) batches)
- async save (orbax background thread) with `wait()` barrier

Durability (docs/resilience.md): transient I/O errors during save are
retried with exponential backoff (retries escalate to an overwrite in case
the failed attempt left a partial step dir); async-save failures surface at
the NEXT save point instead of silently waiting for the next `wait()`; and
restore falls back to the previous retained step when the newest one is
corrupt/partial — a run preempted mid-commit must not crash-loop on
relaunch.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp
from pydantic import BaseModel, ConfigDict

from llm_training_tpu.trainer.state import TrainState

logger = logging.getLogger(__name__)


class CheckpointConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    dirpath: str | None = None
    max_to_keep: int = 3
    async_save: bool = True
    save_on_exit: bool = True
    # transient-I/O retries around the blocking part of save (serialize +
    # handoff; the whole write when async_save=False)
    save_retries: int = 3
    retry_backoff_s: float = 0.5
    retry_backoff_max_s: float = 30.0


def _pack(state: TrainState) -> Any:
    """Typed PRNG keys are not serializable; ship raw key data."""
    return state.replace(rng=jax.random.key_data(state.rng))


def _unpack(state: TrainState) -> TrainState:
    return state.replace(rng=jax.random.wrap_key_data(state.rng))


class Checkpointer:
    def __init__(self, config: CheckpointConfig, run_config: dict | None = None):
        if config.dirpath is None:
            raise ValueError("CheckpointConfig.dirpath is required")
        self.config = config
        self.run_config = run_config or {}
        # world size / launcher env / git rev, captured once at run start
        # (reference save_config_callback.py:15-41) — embedded in every save
        from llm_training_tpu.run_metadata import collect_run_metadata

        self.run_metadata = collect_run_metadata()
        self.directory = Path(config.dirpath).absolute()
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                enable_async_checkpointing=config.async_save,
            ),
            item_names=("state", "meta"),
        )
        # newest save launched but not yet confirmed committed (async mode);
        # wait() logs the commit once the barrier passes
        self._inflight_step: int | None = None

    def check_errors(self) -> None:
        """Surface a failed async save NOW (orbax parks background-thread
        errors until `wait_until_finished` — without this probe a failure
        stays invisible until the next barrier, which may be the end of
        fit, silently widening the window of unpersisted work)."""
        self.manager.check_for_errors()

    def save(
        self,
        step: int,
        state: TrainState,
        counters: dict[str, int] | None = None,
        force: bool = False,
        extra: dict | None = None,
    ) -> None:
        # surface a parked async failure even when THIS call dedupes away —
        # "failures surface at the next save point" must include skipped ones
        self.check_errors()
        if step in self.manager.all_steps() and not force:
            return  # e.g. end-of-fit save colliding with an interval save
        meta = {
            "step": step,
            "counters": counters or {},
            "config": self.run_config,
            "run_metadata": self.run_metadata,
            # JSON-serializable run-state riders: the recovery skip list /
            # cooldown windows and callback state (NanGuard EMA) — what a
            # resume needs beyond the array tree (docs/resilience.md)
            **(extra or {}),
        }
        from llm_training_tpu.resilience import RetryPolicy, chaos_point, retry_call
        from llm_training_tpu.telemetry import get_registry

        registry = get_registry()
        policy = RetryPolicy(
            max_retries=self.config.save_retries,
            backoff_base_s=self.config.retry_backoff_s,
            backoff_max_s=self.config.retry_backoff_max_s,
        )

        def _save(attempt: int) -> None:
            chaos_point("checkpoint_save", step=step)
            # force-overwrite path (emergency save over a stale/partial
            # entry, or a retry after a mid-write failure): orbax refuses to
            # save over a finalized step, so drop it first. There is a
            # window between the delete and the replacement's commit where
            # this step has no durable copy — a SIGKILL inside it loses the
            # step; retention (max_to_keep) plus the restore fallback bound
            # the damage to "resume from the previous retained step", which
            # beats the alternative (StepAlreadyExistsError = no emergency
            # save at all)
            if step in self.manager.all_steps():
                self.manager.delete(step)
            # force here only bypasses the save-interval policy; a failed
            # attempt's partial (unfinalized) dir is cleared by orbax itself
            self.manager.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(_pack(state)),
                    meta=ocp.args.JsonSave(meta),
                ),
                force=force or attempt > 0,
            )

        # with async_save this times only the blocking handoff (serialize +
        # background-thread launch); wait() below captures the barrier
        with registry.timer("checkpoint/save").time():
            retry_call(
                _save, policy,
                label=f"checkpoint save (step {step})",
                counter=registry.counter("checkpoint/retries"),
            )
        if self.config.async_save:
            self._inflight_step = step
            logger.info(
                "checkpoint save started at step %d -> %s (async; durable "
                "after the wait() barrier)", step, self.directory,
            )
        else:
            logger.info(
                "checkpoint committed at step %d -> %s", step, self.directory
            )

    def maybe_restore(
        self,
        abstract_state: Any,
        shardings: Any,
        step: int | None = None,
        repair: bool = True,
    ) -> tuple[TrainState, dict] | None:
        """Restore the latest (or given) step straight into sharded buffers.
        Returns None when no checkpoint exists. When no explicit step is
        requested and the newest retained step is corrupt/partial (a
        preemption mid-commit), fall back to the next older retained step —
        losing a few steps of progress beats crash-looping the relaunch.
        An EXPLICIT step request never falls back (the caller asked for
        that state, not "something close to it"); and if every retained
        step fails, the first error is re-raised so a systematic problem
        (e.g. an optimizer-layout mismatch) keeps its diagnosis.

        `repair=True` (the fit path) deletes the unrestorable newer steps
        after a successful fallback so the resumed run re-saves them;
        read-only callers (the `validate` CLI) pass False — an observation
        must not mutate the checkpoint directory."""
        explicit = step is not None
        candidates = (
            [step] if explicit else sorted(self.manager.all_steps(), reverse=True)
        )
        if not candidates:
            return None
        abstract = jax.tree.map(
            lambda leaf, sharding: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=sharding
            ),
            _strip(abstract_state),
            shardings,
        )
        abstract = _pack_abstract(abstract)
        from llm_training_tpu.resilience import RetryPolicy, is_transient, retry_call
        from llm_training_tpu.telemetry import get_registry

        # transient I/O during restore is retried like it is during save —
        # without this, a one-off storage blip would be misclassified as
        # corruption and the (perfectly good) newest step deleted below.
        # FileNotFoundError is excluded: a missing payload file is the
        # corruption signature, and no amount of retrying conjures it back
        policy = RetryPolicy(
            max_retries=self.config.save_retries,
            backoff_base_s=self.config.retry_backoff_s,
            backoff_max_s=self.config.retry_backoff_max_s,
        )

        def _restore_transient(e: BaseException) -> bool:
            return is_transient(e) and not isinstance(e, FileNotFoundError)

        first_error: Exception | None = None
        corrupt: list[int] = []
        for candidate in candidates:
            try:
                restored = retry_call(
                    lambda attempt: self.manager.restore(
                        candidate,
                        args=ocp.args.Composite(
                            state=ocp.args.StandardRestore(abstract),
                            meta=ocp.args.JsonRestore(),
                        ),
                    ),
                    policy,
                    label=f"checkpoint restore (step {candidate})",
                    counter=get_registry().counter("checkpoint/retries"),
                    transient=_restore_transient,
                )
            except Exception as e:
                if explicit:
                    raise
                if first_error is None:
                    first_error = e
                corrupt.append(candidate)
                get_registry().counter("resilience/restore_fallbacks").inc()
                logger.warning(
                    "checkpoint step %d in %s is corrupt or partial (%s); "
                    "falling back to the previous retained step",
                    candidate, self.directory, e,
                )
                continue
            logger.info(
                "restored checkpoint step %d from %s", candidate, self.directory
            )
            # drop the unrestorable newer steps: left in place they would
            # (a) stay the "newest" checkpoint every later restore has to
            # fall back past, and (b) make the resumed run's interval save
            # at the same step skip via the already-exists early return —
            # the corruption would never be repaired
            for bad in corrupt if repair else ():
                try:
                    self.manager.delete(bad)
                    logger.warning(
                        "deleted unrestorable checkpoint step %d", bad
                    )
                except Exception as e:
                    logger.warning(
                        "could not delete unrestorable checkpoint step %d "
                        "(%s); later restores will keep falling back past it",
                        bad, e,
                    )
            return _unpack(restored["state"]), restored["meta"]
        raise RuntimeError(
            f"all retained checkpoint steps {candidates} in {self.directory} "
            "failed to restore"
        ) from first_error

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def read_meta(self, step: int | None = None) -> dict | None:
        """The JSON metadata of `step` (newest when None) WITHOUT restoring
        the array state — the elastic topology planner reads the recorded
        mesh degrees before the mesh (and therefore the shardings the full
        restore needs) exists. Read-only and failure-tolerant: any error
        returns None (the planner then falls back to the config alone and
        the real restore reports the problem with full context)."""
        if step is None:
            step = self.manager.latest_step()
        if step is None:
            return None
        try:
            restored = self.manager.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
            )
            return restored["meta"]
        except Exception as e:
            logger.warning(
                "could not read checkpoint metadata for step %s in %s (%s)",
                step, self.directory, e,
            )
            return None

    def wait(self) -> None:
        from llm_training_tpu.telemetry import get_registry

        with get_registry().timer("checkpoint/wait").time():
            self.manager.wait_until_finished()
        if self._inflight_step is not None:
            logger.info(
                "checkpoint committed at step %d -> %s",
                self._inflight_step, self.directory,
            )
            self._inflight_step = None

    def close(self) -> None:
        # a fast exit (preemption grace window, early return) must not drop
        # an in-flight async save — barrier first, then release resources
        try:
            self.wait()
        finally:
            self.manager.close()


def _strip(abstract_state: Any) -> Any:
    """Drop flax Partitioned boxes from an eval_shape tree, keeping plain
    ShapeDtypeStructs (orbax needs the same structure as the saved tree)."""
    import flax.linen as nn

    return nn.meta.unbox(abstract_state)


def _pack_abstract(abstract_state: TrainState) -> Any:
    """Mirror _pack for the abstract tree: rng key -> raw key data shape."""
    rng = abstract_state.rng
    # key_data of a typed key scalar is uint32[4] (threefry) — derive via eval_shape
    rng_data = jax.eval_shape(jax.random.key_data, jax.random.key(0))
    sharding = getattr(rng, "sharding", None)
    return abstract_state.replace(
        rng=jax.ShapeDtypeStruct(rng_data.shape, rng_data.dtype, sharding=sharding)
    )
