"""Sharded checkpointing with embedded config + resume.

Capability parity: reference checkpoint subsystem (SURVEY.md §3.3/§5.4):
- sharded-native save ≙ DCP dirs (`fsdp2_strategy.py:376-386`) — orbax
  writes each host's shards; restore streams directly into sharded buffers
- `meta.pt` with loop/counter state ≙ the metadata JSON (step, consumed
  counters)
- config embedded in every checkpoint (`save_config_callback.py:43-45`) so
  export can rebuild the model without the original YAML
- mid-epoch resume: `TrainState.step` counts micro-steps and the data
  stream is a pure function of (seed, step) — no batch skipping
  (cf. `resumable_dataloader.py:20-25`, which replays O(skipped) batches)
- async save (orbax background thread) with `wait()` barrier
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp
from pydantic import BaseModel, ConfigDict

from llm_training_tpu.trainer.state import TrainState

logger = logging.getLogger(__name__)


class CheckpointConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    dirpath: str | None = None
    max_to_keep: int = 3
    async_save: bool = True
    save_on_exit: bool = True


def _pack(state: TrainState) -> Any:
    """Typed PRNG keys are not serializable; ship raw key data."""
    return state.replace(rng=jax.random.key_data(state.rng))


def _unpack(state: TrainState) -> TrainState:
    return state.replace(rng=jax.random.wrap_key_data(state.rng))


class Checkpointer:
    def __init__(self, config: CheckpointConfig, run_config: dict | None = None):
        if config.dirpath is None:
            raise ValueError("CheckpointConfig.dirpath is required")
        self.config = config
        self.run_config = run_config or {}
        # world size / launcher env / git rev, captured once at run start
        # (reference save_config_callback.py:15-41) — embedded in every save
        from llm_training_tpu.run_metadata import collect_run_metadata

        self.run_metadata = collect_run_metadata()
        self.directory = Path(config.dirpath).absolute()
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                enable_async_checkpointing=config.async_save,
            ),
            item_names=("state", "meta"),
        )

    def save(
        self,
        step: int,
        state: TrainState,
        counters: dict[str, int] | None = None,
        force: bool = False,
    ) -> None:
        if step in self.manager.all_steps():
            return  # e.g. end-of-fit save colliding with an interval save
        meta = {
            "step": step,
            "counters": counters or {},
            "config": self.run_config,
            "run_metadata": self.run_metadata,
        }
        from llm_training_tpu.telemetry import get_registry

        # with async_save this times only the blocking handoff (serialize +
        # background-thread launch); wait() below captures the barrier
        with get_registry().timer("checkpoint/save").time():
            self.manager.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(_pack(state)),
                    meta=ocp.args.JsonSave(meta),
                ),
                force=force,
            )
        logger.info("checkpoint saved at step %d -> %s", step, self.directory)

    def maybe_restore(
        self,
        abstract_state: Any,
        shardings: Any,
        step: int | None = None,
    ) -> tuple[TrainState, dict] | None:
        """Restore the latest (or given) step straight into sharded buffers.
        Returns None when no checkpoint exists."""
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda leaf, sharding: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=sharding
            ),
            _strip(abstract_state),
            shardings,
        )
        abstract = _pack_abstract(abstract)
        restored = self.manager.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore(),
            ),
        )
        logger.info("restored checkpoint step %d from %s", step, self.directory)
        return _unpack(restored["state"]), restored["meta"]

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def wait(self) -> None:
        from llm_training_tpu.telemetry import get_registry

        with get_registry().timer("checkpoint/wait").time():
            self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()


def _strip(abstract_state: Any) -> Any:
    """Drop flax Partitioned boxes from an eval_shape tree, keeping plain
    ShapeDtypeStructs (orbax needs the same structure as the saved tree)."""
    import flax.linen as nn

    return nn.meta.unbox(abstract_state)


def _pack_abstract(abstract_state: TrainState) -> Any:
    """Mirror _pack for the abstract tree: rng key -> raw key data shape."""
    rng = abstract_state.rng
    # key_data of a typed key scalar is uint32[4] (threefry) — derive via eval_shape
    rng_data = jax.eval_shape(jax.random.key_data, jax.random.key(0))
    sharding = getattr(rng, "sharding", None)
    return abstract_state.replace(
        rng=jax.ShapeDtypeStruct(rng_data.shape, rng_data.dtype, sharding=sharding)
    )
