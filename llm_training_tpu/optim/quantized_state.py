"""Block-quantized storage codec for offloaded optimizer state.

The host-offloaded optimizer round trip is transfer-bound: at the 916M-param
bench proxy the fp32 mu/nu round trip is ~14.7 GB/step against a ~15 GB/s
host link, and the r5 chip measurement showed the per-leaf "overlapped"
chains hide none of it (0.3035 vs 0.313 MFU serialized) because the update
compute they overlap with is negligible next to the transfers. The lever
that works is shrinking the bytes: store mu as block-wise int8 and nu as
block-wise uint8 of sqrt(nu) (8-bit-Adam-style state compression — the
capability analogue of DeepSpeed's quantized ZeRO-offload knobs,
`/root/reference/src/llm_training/lightning/strategy/deepspeed/deepspeed_strategy.py:70-102`),
cutting the round trip 4x while mu/nu still never reside in HBM between
steps.

Codec design:
- symmetric int8 ("sym", for mu and any signed state): per-block scale =
  max|x|/127 over BLOCK consecutive elements of the last axis;
  dequant = q * scale. Round-to-nearest; the quantization error decays
  geometrically under the EMA (mu' = b1*dq(q(mu)) + (1-b1)g).
- sqrt-uint8 ("sqrt", for nu / adafactor v*): quantize r = sqrt(nu) —
  halves the dynamic range the linear scale must span — with CEIL
  rounding, so the dequantized nu is an upper bound of the true value
  wherever it underestimates the scale grid. Adam divides by
  sqrt(nu_hat)+eps: over-estimating nu only shrinks a coordinate's step
  (safe); under-estimating it (in particular quantizing a tiny nu to 0)
  would multiply the step by up to sqrt(nu_true)/eps — catastrophic. Ceil
  bounds every per-coordinate step from above by its true-Adam value.

Arrays whose last axis is not a multiple of the block (tiny gates/scalars)
stay fp32 — their transfer cost is noise. Scales are fp32 at 1/BLOCK the
element count (1.6% overhead at 256).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 256

# Quantization is a WHITELIST of known optimizer state fields — anything
# unrecognized stays fp32. This is what makes the codec safe under wrapper
# transforms: e.g. optax.MultiSteps' acc_grads accumulator repeatedly adds
# small per-micro-batch gradients, which block quantization would zero out;
# it is not listed, so it passes through exact.
#   sym codec: first-moment / momentum EMAs (quantization error decays
#   geometrically under the EMA update). 'ema' is adafactor's momentum
#   (optax appends optax.transform.ema when momentum is set)
_SYM_FIELDS = {"mu", "trace", "ema"}
#   sqrt codec: non-negative second-moment accumulators (adam/adamw nu,
#   adafactor v/v_row/v_col)
_NONNEG_FIELDS = {"nu", "v", "v_row", "v_col"}


@flax.struct.dataclass
class QuantArray:
    """Block-quantized stand-in for one fp32 optimizer-state array.

    q keeps the original array shape (int8 for "sym", uint8 for "sqrt") so
    it inherits the parent array's sharding spec unchanged; scale has the
    last axis divided by `block`. `kind`/`block` are treedef constants —
    checkpoints restore them from the abstract target, not from disk.
    """

    q: Any
    scale: Any
    kind: str = flax.struct.field(pytree_node=False)
    block: int = flax.struct.field(pytree_node=False)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # the logical dtype (what dequantize returns)
        return jnp.float32


def _blocked(x: jnp.ndarray, block: int) -> jnp.ndarray:
    return x.reshape(*x.shape[:-1], x.shape[-1] // block, block)


def quantize_array(x: jnp.ndarray, kind: str, block: int) -> QuantArray:
    xb = _blocked(x.astype(jnp.float32), block)
    if kind == "sym":
        scale = jnp.max(jnp.abs(xb), axis=-1) / 127.0
        q = jnp.round(xb / jnp.maximum(scale, 1e-30)[..., None])
        q = jnp.clip(q, -127, 127).astype(jnp.int8)
    elif kind == "sqrt":
        r = jnp.sqrt(xb)
        scale = jnp.max(r, axis=-1) / 254.0
        # ceil with a slack of 5e-4 grid steps: large enough to absorb the
        # fp32 rounding of a dequantize->requantize cycle (~6e-5 steps at
        # code 254), so the codec is GRID-IDEMPOTENT — re-encoding an
        # unchanged state reproduces q and scale exactly instead of
        # ratcheting codes upward (the serialized offload path re-encodes
        # every accumulation micro-step). Weakens the never-underestimate
        # guarantee by at most 5e-4 steps — noise against the sqrt(nu)/eps
        # blowup the ceil protects from
        q = jnp.ceil(r / jnp.maximum(scale, 1e-30)[..., None] - 5e-4)
        # the slack must never let a NONZERO nu encode to 0 — dequantized
        # nu = 0 is the sqrt(nu)/eps catastrophe this codec exists to
        # prevent. Floor positive inputs at code 1 (idempotent: code 1
        # dequantizes to exactly one step, which re-encodes to 1)
        q = jnp.maximum(q, (xb > 0).astype(q.dtype))
        q = jnp.clip(q, 0, 255).astype(jnp.uint8)
    else:
        raise ValueError(f"unknown quantization kind {kind!r}")
    return QuantArray(
        q=q.reshape(x.shape), scale=scale.astype(jnp.float32), kind=kind, block=block
    )


def dequantize_array(qa: QuantArray) -> jnp.ndarray:
    xb = _blocked(qa.q.astype(jnp.float32), qa.block) * qa.scale[..., None]
    if qa.kind == "sqrt":
        xb = xb * xb
    return xb.reshape(qa.q.shape)


def _codec_kind(path) -> str | None:
    """Which codec this leaf's optax state field gets (None = keep fp32).

    State trees nest as (chain idx, state-namedtuple field, *param-tree
    path): namedtuple fields flatten to GetAttrKey (which has .name), while
    param-tree keys are DictKey (.key) — so checking only .name entries
    against the field sets cannot be fooled by a model param literally
    named 'v', and survives wrapper states (MaskedState, MultiStepsState)
    that add their own GetAttrKeys around the field. A non-negative match
    wins over a sym match (no current optax state nests one inside the
    other, but under-stepping is the safe direction)."""
    names = {getattr(entry, "name", None) for entry in path}
    if names & _NONNEG_FIELDS:
        return "sqrt"
    if names & _SYM_FIELDS:
        return "sym"
    return None


def _boxed(ref, value):
    """Re-wrap value in ref's Partitioned box (sharding metadata), if any."""
    if isinstance(ref, nn.Partitioned):
        return ref.replace_boxed(value)
    return value


def _unboxed(leaf):
    return leaf.value if isinstance(leaf, nn.Partitioned) else leaf


def encode_state(state: Any, block: int = DEFAULT_BLOCK) -> Any:
    """Quantize every eligible fp32 array in an optax state tree.

    Eligible: floating arrays with ndim >= 1 whose last axis is a multiple
    of `block`, under a WHITELISTED optimizer field (mu/trace -> "sym",
    nu/v* -> "sqrt"; anything else — counts, MultiSteps grad accumulators,
    unknown fields — stays exact). Partitioned boxes are preserved AROUND
    q and scale so the abstract tree still carries per-array sharding
    metadata.
    """

    def enc(path, leaf):
        value = _unboxed(leaf)
        kind = _codec_kind(path)
        if (
            kind is None
            or not hasattr(value, "ndim")
            or value.ndim < 1
            or not jnp.issubdtype(value.dtype, jnp.floating)
            or value.shape[-1] % block != 0
        ):
            return leaf
        qa = quantize_array(value, kind, block)
        return QuantArray(
            q=_boxed(leaf, qa.q), scale=_boxed(leaf, qa.scale),
            kind=kind, block=block,
        )

    return jax.tree_util.tree_map_with_path(
        enc, state, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


def decode_state(state: Any) -> Any:
    """Inverse of encode_state: QuantArray leaves back to fp32 arrays."""

    def dec(leaf):
        if isinstance(leaf, QuantArray):
            qa = QuantArray(
                q=_unboxed(leaf.q), scale=_unboxed(leaf.scale),
                kind=leaf.kind, block=leaf.block,
            )
            return _boxed(leaf.q, dequantize_array(qa))
        return leaf

    return jax.tree.map(dec, state, is_leaf=lambda x: isinstance(x, QuantArray))


def cast_state(state: Any, dtype) -> Any:
    """Elementwise storage cast (the "bfloat16" offload dtype): floating
    arrays under whitelisted fields are stored as `dtype`; ints/scalars and
    unlisted fields (e.g. MultiSteps grad accumulators, whose repeated
    small adds need fp32) stay."""

    def cast(path, leaf):
        value = _unboxed(leaf)
        if (
            _codec_kind(path) is not None
            and hasattr(value, "ndim")
            and value.ndim >= 1
            and jnp.issubdtype(value.dtype, jnp.floating)
        ):
            return _boxed(leaf, value.astype(dtype))
        return leaf

    return jax.tree_util.tree_map_with_path(
        cast, state, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


def uncast_state(state: Any, dtype=jnp.float32) -> Any:
    return cast_state(state, dtype)
