"""Optimizers and LR schedules.

Capability parity: reference `optim/` + `lr_schedulers/` — AdamW & friends
with warmup-composed schedules (`lr_schedulers/warmup.py:7`,
`{constant,cosine,linear}.py`), grad clipping
(`optax.clip_by_global_norm` ≙ Lightning's clip + `fsdp2_precision.py:166-169`),
and master-weight semantics (`optim/master_weight_wrapper.py:10`) expressed
natively: params and optimizer state live in fp32 while the forward computes
in bf16, so no wrapper class exists.
"""

from llm_training_tpu.optim.builder import OptimConfig, build_optimizer, build_lr_schedule

__all__ = ["OptimConfig", "build_optimizer", "build_lr_schedule"]
