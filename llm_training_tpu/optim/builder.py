"""optax optimizer/schedule construction from config.

Capability parity: the reference's optimizer config surface
(`lms/base_lm_config.py:13-43`: optimizer_class/kwargs +
lr_scheduler_class/kwargs with `num_total_steps` injection,
`base_lm.py:269-288`) and its three warmup schedules
(`lr_schedulers/{constant,cosine,linear}.py`).
"""

from __future__ import annotations

import logging
import re
from typing import Any

import jax
import optax
from pydantic import BaseModel, ConfigDict

logger = logging.getLogger(__name__)

_OPTIMIZERS = {
    "adamw": optax.adamw,
    "adam": optax.adam,
    "sgd": optax.sgd,
    "adafactor": optax.adafactor,
    "lion": optax.lion,
}

_SCHEDULES = ("constant", "cosine", "linear")


class OptimConfig(BaseModel):
    """Mirrors `BaseOptimizerConfig` (`base_lm_config.py`): which optimizer,
    its kwargs, which warmup schedule, its kwargs, plus grad clipping."""

    model_config = ConfigDict(extra="forbid")

    optimizer: str = "adamw"
    learning_rate: float = 1e-4
    optimizer_kwargs: dict[str, Any] = {}
    lr_scheduler: str | None = "cosine"
    warmup_steps: int = 0
    min_lr_ratio: float = 0.0  # cosine/linear floor as a fraction of peak lr
    lr_scheduler_kwargs: dict[str, Any] = {}
    grad_clip_norm: float | None = 1.0


def build_lr_schedule(config: OptimConfig, num_total_steps: int) -> optax.Schedule:
    """Warmup composed with an inner schedule (reference `warmup.py:26-34`).

    `num_total_steps` is injected by the trainer, the analogue of
    `base_lm.py:277-279` feeding `estimated_stepping_batches` to cosine."""
    peak = config.learning_rate
    floor = peak * config.min_lr_ratio
    decay_steps = max(num_total_steps - config.warmup_steps, 1)

    name = config.lr_scheduler or "constant"
    if name == "constant":
        inner = optax.constant_schedule(peak)
    elif name == "cosine":
        inner = optax.cosine_decay_schedule(
            peak, decay_steps, alpha=config.min_lr_ratio, **config.lr_scheduler_kwargs
        )
    elif name == "linear":
        inner = optax.linear_schedule(peak, floor, decay_steps, **config.lr_scheduler_kwargs)
    else:
        raise ValueError(f"unknown lr_scheduler {name!r}; expected one of {_SCHEDULES}")

    if config.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, peak, config.warmup_steps)
        return optax.join_schedules([warmup, inner], [config.warmup_steps])
    return inner


def _freeze_mask(params: Any, frozen_patterns: list[str]) -> Any:
    """True = trainable. Reference regex freezing (`base_lm.py:234-241`)."""
    regexes = [re.compile(p) for p in frozen_patterns]

    def trainable(path, _) -> bool:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        frozen = any(r.search(name) for r in regexes)
        if frozen:
            logger.info("freezing %s", name)
        return not frozen

    return jax.tree_util.tree_map_with_path(trainable, params)


def build_optimizer(
    config: OptimConfig,
    num_total_steps: int,
    frozen_modules: list[str] | None = None,
    schedule_transform: Any | None = None,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Full chain: clip -> optimizer(schedule) [-> freeze mask].

    The freeze mask is a *callable* so it adapts to whatever tree structure
    (flax-boxed or plain) the transformation is applied to.
    `schedule_transform` wraps the built LR schedule (the recovery LR
    cooldown, `resilience/recovery.py`) — a pure function of the schedule
    count, so the optimizer-state layout is untouched."""
    schedule = build_lr_schedule(config, num_total_steps)
    if schedule_transform is not None:
        schedule = schedule_transform(schedule)
    try:
        opt_fn = _OPTIMIZERS[config.optimizer]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {config.optimizer!r}; expected one of {sorted(_OPTIMIZERS)}"
        )
    chain = []
    if config.grad_clip_norm is not None:
        chain.append(optax.clip_by_global_norm(config.grad_clip_norm))
    chain.append(opt_fn(learning_rate=schedule, **config.optimizer_kwargs))
    tx = optax.chain(*chain)
    if frozen_modules:
        patterns = list(frozen_modules)
        tx = optax.chain(
            optax.masked(tx, lambda tree: _freeze_mask(tree, patterns)),
            optax.masked(
                optax.set_to_zero(),
                lambda tree: jax.tree.map(lambda t: not t, _freeze_mask(tree, patterns)),
            ),
        )
    return tx, schedule
