"""Declarative SLOs with multi-window burn-rate alerting
(docs/observability.md#slo).

Percentiles without targets are trivia: the serving tier publishes
TTFT/TPOT percentiles and the trainer publishes step cadence + goodput,
but nothing said "this is now bad". This module evaluates a declarative
SLO config over sliding windows:

- **serve**: `ttft_p99_ms`, `tpot_p99_ms` (latency SLOs — at most 1% of
  requests may exceed the target), `error_rate` (at most this fraction of
  requests may terminate without a full completion);
- **train**: `step_time_p99_s` (latency SLO over optimizer-step wall
  intervals), `goodput_pct_min` (a level floor — goodput observations
  below it consume budget).

Alerting is the standard multi-window burn-rate scheme: each observation
is a budget *event* (violated or not); a breach fires when the violation
fraction burns the error budget at >= `fast_burn`x over the FAST window
AND >= `slow_burn`x over the SLOW window — the fast window makes the
alert respond in seconds, the slow window keeps a single straggler from
paging. Every breach

- bumps `slo/breaches_total` + per-target `slo/<key>/breaches` counters
  (routed into telemetry.jsonl, so `report` renders `== SLO ==`),
- emits a trace instant (`cat="slo"`), and
- **flight-dumps the trace ring** to `trace-flight-slo-*.jsonl` in the
  run dir, so the breach window is always post-mortemable — the same
  ring dump a hang or NaN produces.

Config comes from an explicit dict (`{"serve": {...}, "train": {...}}`)
overlaid by `LLMT_SLO_*` env vars, so a supervisor or CI job can arm SLOs
without YAML. No config -> `build_slo_monitor` returns None and every
caller stays zero-cost. Jax-free by contract: the monitor is fed from the
serve loop and the train loop and read by the exporter's scrape thread.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass

logger = logging.getLogger(__name__)

# ring dumps per breaching target: the post-mortem value is in the FIRST
# few breach windows; a day-long violation must not litter the run dir
MAX_FLIGHT_DUMPS_PER_TARGET = 3

# env overlay (docs/observability.md#slo): targets
_TARGET_ENVS = (
    ("serve", "ttft_p99_ms", "LLMT_SLO_TTFT_P99_MS"),
    ("serve", "tpot_p99_ms", "LLMT_SLO_TPOT_P99_MS"),
    ("serve", "error_rate", "LLMT_SLO_ERROR_RATE"),
    ("train", "step_time_p99_s", "LLMT_SLO_STEP_TIME_P99_S"),
    ("train", "goodput_pct_min", "LLMT_SLO_GOODPUT_PCT_MIN"),
)


@dataclass(frozen=True)
class SLOSpec:
    """One target. `kind` fixes the violation predicate and the budget:
    latency -> value > target violates, budget 1%; error_rate -> a failed
    event violates, budget = target itself; floor -> value < target
    violates, budget 1%."""

    key: str  # e.g. "serve/ttft_p99_ms" — the metric family it guards
    target: float
    kind: str  # "latency" | "error_rate" | "floor"

    @property
    def budget(self) -> float:
        if self.kind == "error_rate":
            return max(1e-9, self.target)
        return 0.01

    @property
    def domain(self) -> str:
        """Which observation stream feeds this spec: `serve/*` targets
        consume request terminals, `train/*` targets consume step/goodput
        observations. A spec never sees the other stream's events — an
        error-rate SLO armed fleet-wide must not count a training fit's
        healthy steps as healthy requests (that would dilute the real
        request-error fraction and mask a breach)."""
        return self.key.split("/", 1)[0]

    def violated(self, value: float | None, ok: bool = True) -> bool | None:
        """None = this observation carries nothing for this spec."""
        if self.kind == "error_rate":
            return not ok
        if value is None:
            return None
        if self.kind == "floor":
            return value < self.target
        return value > self.target


class _Window:
    """Sliding event window with running (total, violated) counts: append
    and horizon-eviction are amortized O(1), so the per-request serve
    emit path never rescans a 300s window per observation."""

    __slots__ = ("horizon_s", "events", "total", "violated")

    def __init__(self, horizon_s: float):
        self.horizon_s = horizon_s
        self.events: deque = deque()  # (t, violated)
        self.total = 0
        self.violated = 0

    def add(self, now: float, bad: bool) -> None:
        self.events.append((now, bad))
        self.total += 1
        self.violated += bad
        while self.events and now - self.events[0][0] > self.horizon_s:
            _, old_bad = self.events.popleft()
            self.total -= 1
            self.violated -= old_bad

    def burn(self, budget: float) -> float:
        if self.total == 0:
            return 0.0
        return (self.violated / self.total) / budget


def _env_float(name: str) -> float | None:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r (want a float)", name, raw)
        return None


def slo_config_from_env(base: dict | None = None) -> dict:
    """Overlay `LLMT_SLO_*` targets on `base` ({"serve": {...}, "train":
    {...}}); returns the merged config (possibly empty)."""
    config: dict[str, dict] = {
        "serve": dict((base or {}).get("serve") or {}),
        "train": dict((base or {}).get("train") or {}),
    }
    for section, field, env in _TARGET_ENVS:
        value = _env_float(env)
        if value is not None:
            config[section][field] = value
    return {k: v for k, v in config.items() if v}


def specs_from_config(config: dict) -> list[SLOSpec]:
    specs: list[SLOSpec] = []
    serve = config.get("serve") or {}
    train = config.get("train") or {}
    if serve.get("ttft_p99_ms") is not None:
        specs.append(SLOSpec("serve/ttft_p99_ms", float(serve["ttft_p99_ms"]), "latency"))
    if serve.get("tpot_p99_ms") is not None:
        specs.append(SLOSpec("serve/tpot_p99_ms", float(serve["tpot_p99_ms"]), "latency"))
    if serve.get("error_rate") is not None:
        specs.append(SLOSpec("serve/error_rate", float(serve["error_rate"]), "error_rate"))
    if train.get("step_time_p99_s") is not None:
        specs.append(SLOSpec("train/step_time_p99_s", float(train["step_time_p99_s"]), "latency"))
    if train.get("goodput_pct_min") is not None:
        specs.append(SLOSpec("train/goodput_pct_min", float(train["goodput_pct_min"]), "floor"))
    return specs


class SLOMonitor:
    """Sliding-window burn-rate evaluator over the armed `SLOSpec`s.

    Observations arrive from the owning loop (serve: per done event;
    train: per optimizer step + per log step) and the exporter's scrape
    thread reads `last_alert()`; all state is guarded by one lock. Breach
    side effects (registry counters, trace instant, flight dump) are
    emitted AFTER the lock is released, so the monitor adds no lock-order
    edges into the registry/trace leaves.
    """

    def __init__(
        self,
        specs: list[SLOSpec],
        registry=None,
        run_dir=None,
        clock=time.monotonic,
        fast_window_s: float | None = None,
        slow_window_s: float | None = None,
        fast_burn: float | None = None,
        slow_burn: float | None = None,
        min_events: int | None = None,
        cooldown_s: float | None = None,
    ):
        from pathlib import Path

        self.specs = list(specs)
        self._registry = registry
        self.run_dir = Path(run_dir) if run_dir else None
        self._clock = clock

        # env overlay for the evaluation knobs (explicit args win). An
        # explicit 0 is a real setting (cooldown 0 = count every breach,
        # burn 0 = page on any violation), so None-checks, never `or`
        def knob(explicit, env, default):
            if explicit is not None:
                return explicit
            value = _env_float(env)
            return value if value is not None else default

        self.fast_window_s = knob(fast_window_s, "LLMT_SLO_WINDOW_FAST_S", 60.0)
        self.slow_window_s = knob(slow_window_s, "LLMT_SLO_WINDOW_SLOW_S", 300.0)
        self.fast_burn = knob(fast_burn, "LLMT_SLO_BURN_FAST", 14.4)
        self.slow_burn = knob(slow_burn, "LLMT_SLO_BURN_SLOW", 6.0)
        self.min_events = max(
            1, int(knob(min_events, "LLMT_SLO_MIN_SAMPLES", 4))
        )
        self.cooldown_s = knob(cooldown_s, "LLMT_SLO_COOLDOWN_S", 30.0)
        self._lock = threading.Lock()
        # per-spec fast/slow windows (running-count _Window pairs) —
        # guarded by: _lock
        self._windows: dict[str, tuple[_Window, _Window]] = {
            s.key: (_Window(self.fast_window_s), _Window(self.slow_window_s))
            for s in self.specs
        }
        self._worst: dict[str, float] = {}  # guarded by: _lock
        self._breaches: dict[str, int] = {s.key: 0 for s in self.specs}  # guarded by: _lock
        self._last_alert: dict | None = None  # guarded by: _lock
        self._last_fired: dict[str, float] = {}  # guarded by: _lock
        self._requests_seen = 0  # guarded by: _lock
        self._publish_targets()

    # --------------------------------------------------------- publication

    def _publish_targets(self) -> None:
        if self._registry is None:
            return
        for spec in self.specs:
            self._registry.gauge(f"slo/{spec.key}/target").set(spec.target)

    def _gauge(self, name: str, value: float) -> None:
        if self._registry is not None:
            self._registry.gauge(name).set(value)

    # -------------------------------------------------------- observations

    def observe_request(
        self,
        ttft_ms: float | None = None,
        tpot_ms: float | None = None,
        ok: bool = True,
    ) -> None:
        """One serve terminal: latency numbers when the engine reported
        them, `ok` = a full completion (eos/max_tokens)."""
        values = {
            "serve/ttft_p99_ms": ttft_ms,
            "serve/tpot_p99_ms": tpot_ms,
        }
        with self._lock:
            self._requests_seen += 1
            n = self._requests_seen
        self._observe(values, domain="serve", ok=ok, request_n=n)

    def observe_step(self, step_time_s: float, step: int | None = None) -> None:
        """One optimizer-step wall interval (host-observed cadence)."""
        self._observe(
            {"train/step_time_p99_s": step_time_s}, domain="train", step=step
        )

    def observe_goodput(self, goodput_pct: float, step: int | None = None) -> None:
        self._observe(
            {"train/goodput_pct_min": goodput_pct}, domain="train", step=step
        )

    def _observe(
        self,
        values: dict[str, float | None],
        domain: str,
        ok: bool = True,
        step: int | None = None,
        request_n: int | None = None,
    ) -> None:
        fired: list[dict] = []
        gauges: dict[str, float] = {}
        now = self._clock()
        with self._lock:
            for spec in self.specs:
                if spec.domain != domain:
                    continue  # a spec never eats the other stream's events
                violated = spec.violated(values.get(spec.key), ok=ok)
                if violated is None:
                    continue
                value = values.get(spec.key)
                if value is not None:
                    worst = self._worst.get(spec.key)
                    if worst is None:
                        self._worst[spec.key] = value
                    elif spec.kind == "floor":
                        self._worst[spec.key] = min(worst, value)
                    else:
                        self._worst[spec.key] = max(worst, value)
                fast, slow = self._windows[spec.key]
                fast.add(now, bool(violated))
                slow.add(now, bool(violated))
                alert = self._evaluate_locked(spec, now, step, request_n, gauges)
                if alert is not None:
                    fired.append(alert)
        # registry publication happens AFTER _lock is released: the monitor
        # introduces no slo->registry lock nesting at all
        for name, value in gauges.items():
            self._gauge(name, value)
        for alert in fired:
            self._emit(alert)

    # ---------------------------------------------------------- evaluation

    def _evaluate_locked(self, spec, now, step, request_n, gauges) -> dict | None:  # guarded by: _lock
        fast, slow = self._windows[spec.key]
        burn_fast, n_fast = fast.burn(spec.budget), fast.total
        burn_slow, n_slow = slow.burn(spec.budget), slow.total
        # gauge values are computed here but PUBLISHED by the caller after
        # _lock is released (no slo->registry lock nesting)
        gauges[f"slo/{spec.key}/burn_fast"] = burn_fast
        gauges[f"slo/{spec.key}/burn_slow"] = burn_slow
        if spec.key in self._worst:
            gauges[f"slo/{spec.key}/worst"] = self._worst[spec.key]
        # min_events gates the SLOW window only — it is the straggler
        # guard. The fast window just needs recent evidence (>= 1 event):
        # requiring a full sample count there would leave sparse streams
        # (goodput on log steps, multi-second optimizer steps) permanently
        # inert — burn gauges showing the violation but an alert that can
        # never arm. Size the windows to cover >= min_events observation
        # intervals (docs/observability.md#slo).
        if n_fast < 1 or n_slow < self.min_events:
            return None
        if burn_fast < self.fast_burn or burn_slow < self.slow_burn:
            return None
        last = self._last_fired.get(spec.key)
        if last is not None and now - last < self.cooldown_s:
            return None
        self._last_fired[spec.key] = now
        self._breaches[spec.key] += 1
        alert = {
            "key": spec.key,
            "target": spec.target,
            "worst": self._worst.get(spec.key),
            "burn_fast": burn_fast,
            "burn_slow": burn_slow,
            "n": self._breaches[spec.key],
            "step": step,
            "request_n": request_n,
        }
        self._last_alert = alert
        return alert

    def _emit(self, alert: dict) -> None:
        """Breach side effects, OUTSIDE the monitor lock: counters, trace
        instant, and the flight dump that makes the breach window
        post-mortemable."""
        key = alert["key"]
        if self._registry is not None:
            self._registry.counter("slo/breaches_total").inc()
            self._registry.counter(f"slo/{key}/breaches").inc()
            if alert.get("step") is not None:
                self._registry.gauge("slo/last_breach_step").set(float(alert["step"]))
            if alert.get("request_n") is not None:
                self._registry.gauge("slo/last_breach_request_n").set(
                    float(alert["request_n"])
                )
        logger.warning(
            "SLO breach: %s target %s worst %s — burn %.1fx (fast) / "
            "%.1fx (slow)", key, alert["target"], alert.get("worst"),
            alert["burn_fast"], alert["burn_slow"],
        )
        # lazy import mirrors watchdog.dump: the monitor stays importable
        # without the tracer, and flight_dump itself never raises
        from llm_training_tpu.telemetry.trace import get_tracer

        tracer = get_tracer()
        tracer.instant(
            "slo", "breach", target=key, slo_target=alert["target"],
            worst=alert.get("worst"), burn_fast=round(alert["burn_fast"], 2),
            burn_slow=round(alert["burn_slow"], 2),
            **({"step": alert["step"]} if alert.get("step") is not None else {}),
            **({"request_n": alert["request_n"]}
               if alert.get("request_n") is not None else {}),
        )
        # flight dumps are capped per target (unlike counters/instants,
        # which always record): a persistently breaching run re-alerts
        # every cooldown, and after the first few ring dumps the rest are
        # near-identical disk churn — the HangWatchdog's one-shot latch,
        # relaxed to N shots
        if self.run_dir is not None and alert["n"] <= MAX_FLIGHT_DUMPS_PER_TARGET:
            tag = "slo-" + key.replace("/", "-") + f"-{alert['n']}"
            tracer.flight_dump(self.run_dir, tag)
            # arm a device-profile capture with the SAME tag, so the host
            # ring dump and the device trace of one breach correlate by
            # name (docs/observability.md#profiling). Request-side only —
            # still jax-free; the owning loop performs the capture, and
            # the trigger's own budget/cooldown (not ours) decides
            from llm_training_tpu.telemetry.profiling import (
                get_profile_trigger,
            )

            trigger = get_profile_trigger()
            if trigger is not None:
                trigger.request(tag, source="slo")

    # ------------------------------------------------------------- queries

    def last_alert(self) -> dict | None:
        with self._lock:
            return dict(self._last_alert) if self._last_alert else None

    def breach_count(self) -> int:
        with self._lock:
            return sum(self._breaches.values())


def build_slo_monitor(
    base_config: dict | None = None,
    registry=None,
    run_dir=None,
    **kwargs,
) -> SLOMonitor | None:
    """The one-call entry the trainer / serve CLI use: env-overlaid config
    -> monitor, or None when no target is armed (zero cost)."""
    config = slo_config_from_env(base_config)
    specs = specs_from_config(config)
    if not specs:
        return None
    return SLOMonitor(specs, registry=registry, run_dir=run_dir, **kwargs)
