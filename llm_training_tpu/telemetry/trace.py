"""Request-lifecycle tracing + crash flight recorder
(docs/observability.md#tracing).

The metrics layer (registry gauges, goodput ledger, TTFT/TPOT percentiles)
answers *how much*; this layer answers *where the time went* for one
request or one step. A process-wide `TraceRecorder` collects structured
span/instant events — monotonic timestamps, category, name, and
correlation ids (`request_id` for serving, `step` for training) — into

- a **bounded ring buffer** that always records (a few microseconds per
  event), so the last N events are available as a *flight recorder* when
  something dies: `HangWatchdog` hang dumps, NaN-guard anomaly dumps, and
  recovery rollbacks each flush it next to their existing dump files; and
- an optional **`trace.jsonl` sink** in the run directory, fed only by
  *sampled* events (`LLMT_TRACE_SAMPLE`-th serve request; per-step train
  spans only with `LLMT_TRACE_TRAIN=1`), so steady-state overhead stays
  negligible while coarse lifecycle events (compile, checkpoint_save,
  validation, segment boundaries) are always persisted.

`llm-training-tpu trace <run_dir>` exports the sink as Chrome-trace-format
JSON viewable in Perfetto (ui.perfetto.dev): one track per request, one
for the serving engine's steps, one for the trainer's phases.

This module is deliberately **jax-free** (enforced by graftlint's
jax-free-import contract): the serve scheduler — pure host policy — emits
lifecycle spans at module level, and the export/report paths must run
anywhere the run dir is mounted.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator

logger = logging.getLogger(__name__)

# flush the sink every N written events: bounds both the syscall rate on
# hot paths and how much a crash can tear off the tail
_FLUSH_EVERY = 64

# serve request-lifecycle phase names, in order (docs/observability.md)
REQUEST_PHASES = ("queue", "prefill", "decode")


def _env_int(name: str, default: int, minimum: int = 1) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return max(minimum, int(raw))
    except ValueError:
        logger.warning("ignoring malformed %s=%r (want an int)", name, raw)
        return default


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw != "0"


def clock_anchor(clock=time.perf_counter) -> dict:
    """One wall↔monotonic anchor pair: trace timestamps are monotonic
    process time (unalignable across processes on their own), so each
    sink/flight-dump leads with this sample. `wall_s ≈ wall(mono_s)`
    within `err_s` — the wall read is bracketed by two monotonic reads
    and the half-width bounds the pairing error, which is exactly the
    |skew| bound `trace --merge` alignment inherits (test-pinned)."""
    m0 = clock()
    wall = time.time()
    m1 = clock()
    try:
        attempt = int(os.environ.get("LLMT_SUPERVISOR_ATTEMPT") or 0)
    except ValueError:
        attempt = 0
    return {
        "wall_s": wall,
        "mono_s": 0.5 * (m0 + m1),
        "err_s": max(0.0, 0.5 * (m1 - m0)),
        "pid": os.getpid(),
        "attempt": attempt,
    }


class TraceRecorder:
    """Bounded ring of span/instant events + an optional jsonl sink.

    Every `record` lands in the ring (the flight recorder); only events
    with `write=True` reach the sink — callers gate that flag on sampling
    (`sample_request()`) or the train-step switch (`train_steps`). All
    mutation goes through one lock, so any thread may record.
    """

    def __init__(
        self,
        capacity: int | None = None,
        sample_every: int | None = None,
        train_steps: bool | None = None,
        enabled: bool | None = None,
        clock=time.perf_counter,
    ):
        # env overlay (docs/observability.md#tracing-env): explicit args win
        self.capacity = capacity or _env_int("LLMT_TRACE_RING", 2048)
        self.sample_every = sample_every or _env_int("LLMT_TRACE_SAMPLE", 1)
        self.train_steps = (
            train_steps if train_steps is not None
            else _env_flag("LLMT_TRACE_TRAIN", False)
        )
        self.enabled = (
            enabled if enabled is not None else _env_flag("LLMT_TRACE", True)
        )
        self.clock = clock
        self._ring: deque[dict] = deque(maxlen=self.capacity)  # guarded by: _lock
        self._lock = threading.Lock()
        self._sink = None  # guarded by: _lock
        self._sink_path: Path | None = None  # guarded by: _lock
        self._unflushed = 0  # guarded by: _lock
        self._recorded = 0  # guarded by: _lock
        self._written = 0  # guarded by: _lock
        self._flight_dumps = 0  # guarded by: _lock
        self._requests_seen = 0  # guarded by: _lock
        self._requests_sampled = 0  # guarded by: _lock

    # ------------------------------------------------------------ sink

    def attach_sink(self, path: str | Path) -> bool:
        """Open `path` for appending sampled events; False when tracing is
        disabled or a sink is already attached (the first owner keeps it —
        a fit must not steal the sink a bench stage opened)."""
        if not self.enabled:
            return False
        with self._lock:
            if self._sink is not None:
                return False
            path = Path(path)
            try:
                path.parent.mkdir(parents=True, exist_ok=True)
                self._sink = open(path, "a")
            except OSError:
                logger.exception("trace sink %s unavailable — ring only", path)
                self._sink = None
                return False
            self._sink_path = path
            self._unflushed = 0
        # one-time wall↔monotonic anchor so cross-process merges can align
        # this file (docs/observability.md#fleet); emitted OUTSIDE the
        # attach lock — instant() takes it again
        anchor = clock_anchor(self.clock)
        self.instant("meta", "clock_anchor", ts=anchor["mono_s"], **anchor)
        self.flush()
        return True

    def detach_sink(self) -> None:
        with self._lock:
            sink, self._sink, self._sink_path = self._sink, None, None
        if sink is not None:
            try:
                sink.flush()
                sink.close()
            except OSError:
                logger.exception("trace sink close failed")

    def flush(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.flush()
                except OSError:
                    logger.exception("trace sink flush failed")
                self._unflushed = 0

    @property
    def sink_path(self) -> Path | None:
        return self._sink_path

    # ------------------------------------------------------------ record

    def _record(self, event: dict, write: bool) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(event)
            self._recorded += 1
            if write and self._sink is not None:
                try:
                    self._sink.write(json.dumps(event) + "\n")
                except (OSError, TypeError, ValueError):
                    logger.exception("trace sink write failed (event dropped)")
                    return
                self._written += 1
                self._unflushed += 1
                if self._unflushed >= _FLUSH_EVERY:
                    try:
                        self._sink.flush()
                    except OSError:
                        pass
                    self._unflushed = 0

    def span(
        self, cat: str, name: str, t0: float, t1: float,
        write: bool = True, **args,
    ) -> None:
        """One complete span [t0, t1) (Chrome-trace 'X' phase). Timestamps
        are this recorder's clock (monotonic seconds)."""
        event = {"ts": t0, "dur": max(0.0, t1 - t0), "ph": "X",
                 "cat": cat, "name": name}
        if args:
            event["args"] = args
        self._record(event, write)

    def instant(
        self, cat: str, name: str, ts: float | None = None,
        write: bool = True, **args,
    ) -> None:
        event = {"ts": self.clock() if ts is None else ts, "ph": "i",
                 "cat": cat, "name": name}
        if args:
            event["args"] = args
        self._record(event, write)

    @contextmanager
    def measure(
        self, cat: str, name: str, write: bool = True, **args
    ) -> Iterator[None]:
        t0 = self.clock()
        try:
            yield
        finally:
            self.span(cat, name, t0, self.clock(), write=write, **args)

    # ---------------------------------------------------------- sampling

    def sample_request(self) -> bool:
        """Admission decision for one serve request's sink events: every
        `sample_every`-th submitted request is traced (the ring records
        all of them regardless)."""
        with self._lock:
            nth = self._requests_seen
            self._requests_seen += 1
            sampled = self.enabled and nth % self.sample_every == 0
            if sampled:
                self._requests_sampled += 1
            return sampled

    # ----------------------------------------------------- flight recorder

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def flight_dump(self, run_dir: str | Path, tag: str) -> Path | None:
        """Write the ring's last-N events to `trace-flight-<tag>.jsonl` in
        `run_dir` — the crash flight recorder. Returns the path, or None on
        failure; never raises (a dump error must not mask the failure being
        dumped)."""
        try:
            events = self.snapshot()
            run_dir = Path(run_dir)
            run_dir.mkdir(parents=True, exist_ok=True)
            path = run_dir / f"trace-flight-{tag}.jsonl"
            # lead with a fresh anchor: flight dumps are exactly the files
            # that get merged across replicas post-mortem
            anchor = clock_anchor(self.clock)
            anchor_event = {
                "ts": anchor["mono_s"], "ph": "i", "cat": "meta",
                "name": "clock_anchor", "args": anchor,
            }
            with open(path, "w") as f:
                f.write(json.dumps(anchor_event) + "\n")
                for event in events:
                    f.write(json.dumps(event) + "\n")
            with self._lock:
                self._flight_dumps += 1
            logger.warning(
                "flight recorder: %d trace events dumped to %s",
                len(events), path,
            )
            return path
        except Exception:
            logger.exception("flight dump failed (tag %s)", tag)
            return None

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {
                "recorded": self._recorded,
                "written": self._written,
                "flight_dumps": self._flight_dumps,
                "requests_seen": self._requests_seen,
                "requests_sampled": self._requests_sampled,
            }


# ---------------------------------------------------------------- current
# A plain module global (same rationale as registry.py): worker threads and
# independently constructed components (scheduler, watchdog, NaN guard) must
# find the process tracer without plumbing.
_current_tracer: TraceRecorder | None = None  # guarded by: _current_lock
_current_lock = threading.Lock()


def get_tracer() -> TraceRecorder:
    """The process tracer (constructed from env on first use)."""
    global _current_tracer
    with _current_lock:
        if _current_tracer is None:
            _current_tracer = TraceRecorder()
        return _current_tracer


def set_tracer(tracer: TraceRecorder) -> TraceRecorder | None:
    """Install `tracer` as current; returns the previous one (tests restore
    it in a finally)."""
    global _current_tracer
    with _current_lock:
        previous = _current_tracer
        _current_tracer = tracer
        return previous


# ---------------------------------------------------------------- reading


def resolve_trace_file(source: str | Path) -> Path | None:
    """`source` may be a trace.jsonl (or flight dump) file itself or a run
    directory holding trace.jsonl."""
    source = Path(source)
    if source.is_file():
        return source
    if source.is_dir():
        candidate = source / "trace.jsonl"
        if candidate.is_file():
            return candidate
    return None


def read_trace_events(path: str | Path) -> list[dict]:
    """Tolerant jsonl read: torn/malformed lines and non-dict records are
    skipped — a killed run's trace must still export."""
    events: list[dict] = []
    try:
        text = Path(path).read_text()
    except OSError:
        return events
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(record, dict) and "ts" in record and "name" in record:
            events.append(record)
    return events


# ----------------------------------------------------------------- export

_PIDS = {"serve": 1, "train": 2, "resilience": 3}
_ENGINE_TID = 1
_REQUEST_TID_BASE = 10


def to_chrome_trace(
    events: list[dict],
    ts_offset_s: float = 0.0,
    pid_base: int = 0,
    label: str | None = None,
) -> dict:
    """Chrome-trace-format JSON (the Perfetto/about:tracing schema):
    serving requests become one track each (tid per request id, named),
    engine steps one track, trainer phases one track, resilience events
    their own track. Timestamps convert to microseconds (the format's
    unit); by default they are monotonic process time, so Perfetto shows
    a relative timeline.

    The merge hooks: `ts_offset_s` shifts every timestamp (wall-aligned
    callers pre-rebase and pass 0), `pid_base` namespaces this source's
    process ids so merged replicas never collide, and `label` prefixes
    every process_name (`replica-0/serve`). `cat == "meta"` events
    (clock anchors) steer alignment but never render."""
    out: list[dict] = []
    request_tids: dict[str, int] = {}
    prefix = f"{label}/" if label else ""
    for name, pid in _PIDS.items():
        out.append({"ph": "M", "pid": pid_base + pid, "tid": 0,
                    "name": "process_name", "args": {"name": prefix + name}})
    out.append({"ph": "M", "pid": pid_base + _PIDS["serve"],
                "tid": _ENGINE_TID,
                "name": "thread_name", "args": {"name": "engine"}})
    out.append({"ph": "M", "pid": pid_base + _PIDS["train"], "tid": 1,
                "name": "thread_name", "args": {"name": "trainer phases"}})
    out.append({"ph": "M", "pid": pid_base + _PIDS["resilience"], "tid": 1,
                "name": "thread_name", "args": {"name": "events"}})
    for event in events:
        try:
            cat = str(event.get("cat", "other"))
            if cat == "meta":
                continue
            pid = pid_base + _PIDS.get(cat, 9)
            args = event.get("args") or {}
            request_id = args.get("request_id")
            if cat == "serve" and request_id is not None:
                rid = str(request_id)
                tid = request_tids.get(rid)
                if tid is None:
                    tid = _REQUEST_TID_BASE + len(request_tids)
                    request_tids[rid] = tid
                    out.append({
                        "ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": f"req {rid}"},
                    })
            else:
                tid = _ENGINE_TID if cat == "serve" else 1
            converted = {
                "name": str(event.get("name", "?")),
                "cat": cat,
                "ph": "X" if event.get("ph") == "X" else "i",
                "ts": (float(event["ts"]) + ts_offset_s) * 1e6,
                "pid": pid,
                "tid": tid,
            }
            if converted["ph"] == "X":
                converted["dur"] = float(event.get("dur", 0.0)) * 1e6
            else:
                converted["s"] = "t"  # thread-scoped instant
            if args:
                converted["args"] = args
            out.append(converted)
        except (TypeError, ValueError, KeyError):
            continue  # one malformed record must not sink the export
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ------------------------------------------------------------------ merge


def _extract_anchors(events: list[dict]) -> list[tuple[float, float, float]]:
    """Sorted `(mono_s, wall_offset_s, err_s)` triples from the file's
    `clock_anchor` meta events; `ts + wall_offset_s` is wall time."""
    anchors: list[tuple[float, float, float]] = []
    for event in events:
        if event.get("cat") != "meta" or event.get("name") != "clock_anchor":
            continue
        args = event.get("args") or {}
        try:
            mono = float(args["mono_s"])
            wall = float(args["wall_s"])
            err = float(args.get("err_s", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        anchors.append((mono, wall - mono, err))
    anchors.sort()
    return anchors


def wall_align(events: list[dict]) -> tuple[list[dict], float] | None:
    """Rebase every event's monotonic `ts` to wall seconds, SEGMENT-WISE:
    each event uses the nearest preceding anchor (a supervised relaunch
    appends a fresh anchor to the same trace.jsonl, and its events must
    align by the new process's clock pair, not the dead one's). Events
    before the first anchor use the first. Returns `(aligned, max_err_s)`
    — the per-file contribution to the merge skew bound — or None when
    the file holds no anchor at all (pre-anchor traces cannot merge)."""
    import bisect

    anchors = _extract_anchors(events)
    if not anchors:
        return None
    monos = [a[0] for a in anchors]
    aligned: list[dict] = []
    for event in events:
        if event.get("cat") == "meta":
            continue
        try:
            ts = float(event["ts"])
        except (KeyError, TypeError, ValueError):
            continue
        i = max(0, bisect.bisect_right(monos, ts) - 1)
        rebased = dict(event)
        rebased["ts"] = ts + anchors[i][1]
        aligned.append(rebased)
    return aligned, max(a[2] for a in anchors)


def merge_traces(sources: list[str | Path]) -> tuple[dict, dict]:
    """Merge N runs' traces into ONE wall-aligned Chrome-trace document:
    per-source events rebase monotonic→wall via their anchors, the global
    earliest event becomes t=0, and each source gets its own pid
    namespace + label so two replicas' request tracks render side by
    side. Raises ValueError (naming every offending path) on a missing
    trace file or an anchorless one. The cross-replica |skew| is bounded
    by the SUM of the two worst anchor half-widths — `info['skew_bound_s']`,
    pinned by the round-trip test."""
    resolved: list[tuple[Path, Path]] = []
    missing: list[str] = []
    for source in sources:
        path = resolve_trace_file(source)
        if path is None:
            missing.append(
                f"{source} (searched {source} and "
                f"{Path(source) / 'trace.jsonl'})"
            )
        else:
            resolved.append((Path(source), path))
    if missing:
        raise ValueError("no trace file for: " + "; ".join(missing))
    aligned_all: list[tuple[str, list[dict]]] = []
    labels_seen: set[str] = set()
    errs: list[float] = []
    for index, (src, path) in enumerate(resolved):
        events = read_trace_events(path)
        if not events:
            raise ValueError(f"{path} holds no parseable events")
        aligned = wall_align(events)
        if aligned is None:
            raise ValueError(
                f"{path} holds no clock_anchor meta event — cannot "
                "wall-align (anchors are emitted at sink attach; re-record "
                "with the current tracer)"
            )
        events_wall, err = aligned
        if not events_wall:
            raise ValueError(f"{path} holds only meta events")
        label = src.name if src.is_dir() else (src.parent.name or src.stem)
        if label in labels_seen:
            label = f"{label}#{index}"
        labels_seen.add(label)
        aligned_all.append((label, events_wall))
        errs.append(err)
    t0 = min(e["ts"] for _, evs in aligned_all for e in evs)
    merged: list[dict] = []
    for index, (label, evs) in enumerate(aligned_all):
        rebased = [dict(e, ts=e["ts"] - t0) for e in evs]
        document = to_chrome_trace(
            rebased, pid_base=(index + 1) * 100, label=label
        )
        merged.extend(document["traceEvents"])
    worst_pair = sorted(errs, reverse=True)[:2]
    info = {
        "sources": [str(path) for _, path in resolved],
        "labels": [label for label, _ in aligned_all],
        "events": sum(len(evs) for _, evs in aligned_all),
        "t0_wall_s": t0,
        "skew_bound_s": sum(worst_pair),
    }
    return {"traceEvents": merged, "displayTimeUnit": "ms"}, info


# ---------------------------------------------------------------- summary


def summarize_trace(events: list[dict], top_k: int = 3) -> dict:
    """Aggregates for `report`'s `== Trace ==` section and the JSON report:
    per-(category, name) span totals, plus the top-k slowest completed
    serve requests with their queue/prefill/decode breakdowns. ttft_ms per
    request comes from its `first_token` instant — the same value the
    engine put in the protocol's done event."""
    spans: dict[str, dict] = {}
    requests: dict[str, dict] = {}
    # per-stop_reason terminal counts: under the resilience layer
    # (docs/serving.md#resilience) deadline/overloaded terminations are
    # normal operation, and "every request one honest terminal" is exactly
    # what a trace reader wants to audit
    terminal_reasons: dict[str, int] = {}
    # trace.jsonl appends across runs (like metrics.jsonl), and callers
    # (the loadgen) reuse ids like req-0 per run — a `submit` for an id
    # whose previous incarnation already completed starts a NEW logical
    # request (keyed id#N), so phases never merge across runs
    live: dict[str, str] = {}

    def request_for(rid: str, is_submit: bool) -> dict:
        key = live.get(rid)
        if key is None or (
            is_submit and requests[key].get("stop_reason") is not None
        ):
            n = sum(
                1 for k in requests if k == rid or k.startswith(rid + "#")
            )
            key = rid if n == 0 else f"{rid}#{n + 1}"
            live[rid] = key
            requests[key] = {"id": key, "phase_s": {}, "evictions": 0}
        return requests[key]

    for event in events:
        try:
            args = event.get("args") or {}
            name = str(event.get("name", "?"))
            cat = str(event.get("cat", "other"))
            rid = args.get("request_id")
            if rid is not None:
                request = request_for(str(rid), name == "submit")
            if event.get("ph") == "X":
                dur = float(event.get("dur", 0.0))
                agg = spans.setdefault(
                    f"{cat}/{name}",
                    {"count": 0, "total_s": 0.0, "max_s": 0.0},
                )
                agg["count"] += 1
                agg["total_s"] += dur
                agg["max_s"] = max(agg["max_s"], dur)
                if rid is not None and name in REQUEST_PHASES:
                    phases = request["phase_s"]
                    phases[name] = phases.get(name, 0.0) + dur
            elif rid is not None:
                if name == "first_token" and "ttft_ms" in args:
                    request["ttft_ms"] = float(args["ttft_ms"])
                elif name == "evicted":
                    request["evictions"] += 1
                elif name == "done":
                    request["stop_reason"] = args.get("stop_reason")
                    if "n_tokens" in args:
                        request["n_tokens"] = int(args["n_tokens"])
                    reason = str(args.get("stop_reason"))
                    terminal_reasons[reason] = terminal_reasons.get(reason, 0) + 1
        except (TypeError, ValueError):
            continue
    completed = [
        r for r in requests.values()
        if r.get("stop_reason") in ("eos", "max_tokens")
    ]
    for request in requests.values():
        request["wall_s"] = sum(request["phase_s"].values())
    slowest = sorted(completed, key=lambda r: -r["wall_s"])[:top_k]
    return {
        "events": len(events),
        "spans": spans,
        "requests_traced": len(requests),
        "requests_completed": len(completed),
        "terminal_reasons": terminal_reasons,
        "slowest_requests": [
            {
                "id": r["id"],
                "wall_ms": round(1000.0 * r["wall_s"], 3),
                **{
                    f"{phase}_ms": round(1000.0 * r["phase_s"].get(phase, 0.0), 3)
                    for phase in REQUEST_PHASES
                },
                "ttft_ms": r.get("ttft_ms"),
                "n_tokens": r.get("n_tokens"),
                "evictions": r["evictions"],
            }
            for r in slowest
        ],
    }


# -------------------------------------------------------------------- CLI


def trace_main(
    source: str | None = None,
    out: str | None = None,
    merge: list[str] | None = None,
) -> int:
    """`llm-training-tpu trace <run_dir|trace.jsonl> [--out file]`: export
    the trace sink as Chrome-trace JSON for Perfetto (ui.perfetto.dev →
    Open trace file). `--merge <dir>...` instead wall-aligns N runs into
    one file (per-replica pid namespaces — docs/observability.md#fleet).
    Exit 2 — naming every path searched — when no trace file is
    reachable."""
    import sys

    if merge:
        try:
            document, info = merge_traces(list(merge))
        except ValueError as e:
            print(f"trace: {e}", file=sys.stderr)
            return 2
        first = Path(merge[0])
        out_path = Path(out) if out else (
            first / "trace-merged.json" if first.is_dir()
            else first.with_name("trace-merged.json")
        )
        out_path.write_text(json.dumps(document))
        print(
            f"trace: merged {info['events']} events from "
            f"{len(info['sources'])} source(s) "
            f"({', '.join(info['labels'])}) -> {out_path} "
            f"(|skew| <= {1e3 * info['skew_bound_s']:.3f}ms)"
        )
        print("open in Perfetto: https://ui.perfetto.dev (Open trace file)")
        return 0
    if source is None:
        print("trace: need a source (or --merge <dir>...)", file=sys.stderr)
        return 2
    path = resolve_trace_file(source)
    if path is None:
        print(
            f"trace: no trace file found — searched {source} and "
            f"{Path(source) / 'trace.jsonl'} — run with tracing "
            "enabled first (docs/observability.md#tracing)",
            file=sys.stderr,
        )
        return 2
    events = read_trace_events(path)
    if not events:
        print(f"trace: {path} holds no parseable events", file=sys.stderr)
        return 2
    document = to_chrome_trace(events)
    out_path = Path(out) if out else path.with_name("trace-export.json")
    out_path.write_text(json.dumps(document))
    summary = summarize_trace(events)
    print(
        f"trace: exported {summary['events']} events "
        f"({summary['requests_traced']} request track(s)) from {path} "
        f"-> {out_path}"
    )
    print("open in Perfetto: https://ui.perfetto.dev (Open trace file)")
    return 0
