"""Perf-regression ledger over the committed BENCH rounds
(docs/performance.md#perf-ledger).

The repo's `BENCH_r*.json` files are the performance trajectory — but a
board of seven JSON blobs is a trajectory nobody machine-reads. This
module parses the history (both record shapes: a raw bench.py summary and
the driver wrapper `{n, cmd, rc, tail, parsed}`), renders a trend table,
and implements `bench.py --check-regression`: compare the newest round
against the previous round on the SAME backend+model (TPU rounds never
gate CPU rounds and vice versa — the numbers differ by orders of
magnitude) and exit nonzero when a headline metric moved the wrong way by
more than the tolerance:

- `value` (MFU) and `decode_tokens_per_sec`: lower is worse;
- `serve_ttft_p50_ms`: higher is worse (p50, not p99 — at bench-scale
  request counts p99 is one sample).

Tolerance defaults to 40% (`BENCH_REGRESSION_TOLERANCE_PCT`): bench
rounds on a shared container carry real run-to-run noise — PR 11 measured
±30% swings under concurrent load, and the r06→r07 pair (both honest,
quiet-container rounds) differ 25% on MFU purely from machine day-to-day —
and the ledger exists to catch step-function regressions (a dead fast
path, a serialized decode: 2-10x, not 1.3x), not slow-container days.
TPU rounds are far tighter (r01→r02 repeated within 0.3%), so tighten the
tolerance via env when gating hardware rounds. Jax-free and stdlib-only,
like every file the bench PARENT may import.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path

ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")

TOLERANCE_ENV = "BENCH_REGRESSION_TOLERANCE_PCT"
DEFAULT_TOLERANCE_PCT = 40.0

# (record key, human label, direction: -1 lower-is-worse / +1 higher-is-worse)
REGRESSION_METRICS = (
    ("value", "mfu", -1),
    ("decode_tokens_per_sec", "decode tokens/s", -1),
    ("serve_ttft_p50_ms", "serve ttft p50 ms", +1),
)

# the trend table's columns (key, header, format)
_TREND_COLUMNS = (
    ("value", "mfu", "{:.4f}"),
    ("tokens_per_sec_per_chip", "tok/s/chip", "{:,.1f}"),
    ("decode_tokens_per_sec", "decode t/s", "{:,.1f}"),
    ("serve_ttft_p50_ms", "ttft p50", "{:,.2f}"),
    ("health_overhead_pct", "health %", "{:.2f}"),
    ("trace_overhead_pct", "trace %", "{:.2f}"),
    ("exporter_overhead_pct", "exporter %", "{:.2f}"),
)


def resolve_tolerance_pct(explicit: float | None = None) -> float:
    if explicit is not None:
        return float(explicit)
    raw = os.environ.get(TOLERANCE_ENV)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_TOLERANCE_PCT


def normalize_record(record: dict) -> dict:
    """Unwrap the driver's `{n, cmd, rc, tail, parsed}` shape to the raw
    bench summary; a crashed round (parsed null/non-dict) normalizes to an
    honest `{"value": None, "error": ...}` record."""
    if "parsed" in record:
        parsed = record.get("parsed")
        if not isinstance(parsed, dict):
            return {
                "value": None,
                "error": f"bench crashed before emitting a record "
                         f"(rc {record.get('rc')})",
            }
        return parsed
    return record


def load_history(root: str | Path) -> list[dict]:
    """Every `BENCH_rNN.json` under `root`, sorted by round number, each
    normalized and tagged with `round`/`file`. Unreadable files become
    error rounds rather than disappearing from the trend."""
    root = Path(root)
    rounds: list[tuple[int, dict]] = []
    if not root.is_dir():
        return []
    for path in root.iterdir():
        match = ROUND_RE.match(path.name)
        if not match:
            continue
        n = int(match.group(1))
        try:
            record = json.loads(path.read_text())
            if not isinstance(record, dict):
                raise ValueError("not a JSON object")
            record = normalize_record(record)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            record = {"value": None, "error": f"unreadable round: {e}"}
        record = dict(record)
        record["round"] = n
        record["file"] = path.name
        rounds.append((n, record))
    return [record for _, record in sorted(rounds, key=lambda item: item[0])]


def trend_table(history: list[dict]) -> str:
    """Human trend table over the rounds (one line per round; absent
    metrics render as '-')."""
    header = f"{'round':<6} {'backend':<8} {'model':<10}"
    for _, title, _fmt in _TREND_COLUMNS:
        header += f" {title:>11}"
    lines = [header]
    for record in history:
        line = (
            f"r{record['round']:02d}    "
            f"{str(record.get('backend') or '?'):<8} "
            f"{str(record.get('model') or '?'):<10}"
        )
        for key, _title, fmt in _TREND_COLUMNS:
            value = record.get(key)
            try:
                cell = fmt.format(float(value)) if value is not None else "-"
            except (TypeError, ValueError):
                cell = "-"
            line += f" {cell:>11}"
        if record.get("error"):
            line += f"  [{record['error']}]"
        lines.append(line)
    return "\n".join(lines)


def _comparable(record: dict) -> bool:
    return record.get("value") is not None


def find_comparison(history: list[dict]) -> tuple[dict, dict] | None:
    """(previous, newest): the newest round with a headline value and the
    most recent earlier round on the same backend+model. None when the
    history holds no such pair — that is 'nothing to compare', not a
    failure."""
    usable = [r for r in history if _comparable(r)]
    if len(usable) < 2:
        return None
    newest = usable[-1]
    for record in reversed(usable[:-1]):
        if (
            record.get("backend") == newest.get("backend")
            and record.get("model") == newest.get("model")
        ):
            return record, newest
    return None


def check_regression(
    history: list[dict], tolerance_pct: float | None = None
) -> dict:
    """The `--check-regression` verdict: {status, findings, checked,
    baseline, candidate, tolerance_pct}. `status` is "ok" (no regression
    or nothing comparable) or "regression"."""
    tolerance_pct = resolve_tolerance_pct(tolerance_pct)
    # the round being COMMITTED is the newest by number; one that crashed
    # before reporting a headline is itself a gate failure — silently
    # comparing the two previous healthy rounds would green-light exactly
    # the broken round the gate exists to catch
    if history and not _comparable(history[-1]):
        newest = history[-1]
        return {
            "status": "regression",
            "findings": [
                f"newest round {newest['file']} has no headline value "
                f"({newest.get('error', 'no value recorded')}) — a round "
                "too broken to report MFU must not pass the perf gate"
            ],
            "checked": [],
            "candidate": newest["file"],
            "tolerance_pct": tolerance_pct,
        }
    pair = find_comparison(history)
    if pair is None:
        return {
            "status": "ok",
            "findings": [],
            "checked": [],
            "note": (
                "no same-backend round pair with headline values — "
                "nothing to compare"
            ),
            "tolerance_pct": tolerance_pct,
        }
    baseline, candidate = pair
    findings: list[str] = []
    checked: list[dict] = []
    for key, label, direction in REGRESSION_METRICS:
        try:
            old = float(baseline[key])
            new = float(candidate[key])
        except (KeyError, TypeError, ValueError):
            continue  # metric absent on one side: skipped, not failed
        if old == 0:
            continue
        delta_pct = 100.0 * (new - old) / abs(old)
        regressed = direction * delta_pct > tolerance_pct
        checked.append({
            "metric": key,
            "label": label,
            "baseline": old,
            "candidate": new,
            "delta_pct": round(delta_pct, 2),
            "regressed": regressed,
        })
        if regressed:
            findings.append(
                f"{label}: r{baseline['round']:02d} {old:g} -> "
                f"r{candidate['round']:02d} {new:g} "
                f"({delta_pct:+.1f}%, tolerance {tolerance_pct:g}%)"
            )
    return {
        "status": "regression" if findings else "ok",
        "findings": findings,
        "checked": checked,
        "baseline": baseline["file"],
        "candidate": candidate["file"],
        "tolerance_pct": tolerance_pct,
    }


def ledger_main(
    root: str | Path = ".", tolerance_pct: float | None = None
) -> int:
    """`bench.py --check-regression [--bench-dir DIR]` entry: print the
    trend table + the verdict JSON (last line, machine-readable like every
    bench record); exit 0 ok / 3 regression / 2 empty history."""
    history = load_history(root)
    if not history:
        print(f"perf-ledger: no BENCH_r*.json rounds under {root}")
        return 2
    print(trend_table(history))
    verdict = check_regression(history, tolerance_pct)
    print(json.dumps({"stage": "regression_check", **verdict}))
    return 3 if verdict["status"] == "regression" else 0
