"""Goodput ledger: classify fit wall time into phases.

Every second between `start()` and now is attributed to exactly one of
{compile, data_wait, step_compute, checkpoint_save, validation, other}:
the trainer brackets each activity with `measure(phase)` and `other` is
the unexplained remainder (setup, host-side bookkeeping), so the phases
always sum to the total by construction. Goodput is the step-compute share
of the total — the fraction of wall time the run spent doing the work it
exists to do. JAX dispatch is asynchronous, so host-side brackets attribute
*blocking* time: the device_get on log steps bills accumulated device step
time to `step_compute`, and a stalled input pipeline surfaces as
`data_wait` (the host blocking on the prefetcher queue).

The clock is injectable so phase classification is unit-testable without
real sleeps (see tests/test_telemetry.py).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Iterator

PHASES = ("compile", "data_wait", "step_compute", "checkpoint_save", "validation")


class GoodputLedger:
    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._t0: float | None = None  # guarded by: _lock
        self._phase_s: dict[str, float] = {p: 0.0 for p in PHASES}  # guarded by: _lock
        # stack of currently-open measure() phases: the hang watchdog reads
        # the innermost one to say what the loop was stuck inside
        self._open: list[str] = []  # guarded by: _lock
        # cost basis (elastic accounting, docs/resilience.md#elastic): the
        # chip count this segment runs on and its $/chip-hour; None keeps
        # summary() byte-identical to the pre-elastic schema
        self._chip_count: int | None = None  # guarded by: _lock
        self._price_per_chip_hour: float | None = None  # guarded by: _lock

    def set_cost_basis(
        self,
        chip_count: int | None = None,
        price_per_chip_hour: float | None = None,
    ) -> None:
        """Tag this ledger segment with its topology cost: `chip_count`
        adds chip-hour gauges to summary(); a price additionally adds
        cost_dollars and goodput_per_dollar (productive chip-hours bought
        per dollar). The trainer calls this once per fit with the mesh's
        device count — elastic segments on different pools aggregate in
        `report` (== Elastic ==)."""
        with self._lock:
            self._chip_count = int(chip_count) if chip_count else None
            self._price_per_chip_hour = (
                float(price_per_chip_hour) if price_per_chip_hour else None
            )

    def start(self) -> None:
        """Begin (or restart) accounting; zeroes all phases."""
        with self._lock:
            self._t0 = self._clock()
            self._phase_s = {p: 0.0 for p in PHASES}

    def note(self, phase: str, seconds: float) -> None:
        """Attribute externally measured seconds to a phase."""
        if phase not in self._phase_s:
            raise KeyError(f"unknown goodput phase {phase!r}; expected one of {PHASES}")
        with self._lock:
            self._phase_s[phase] += seconds

    @contextmanager
    def measure(self, phase: str) -> Iterator[None]:
        """Time the enclosed block into `phase`."""
        t0 = self._clock()
        with self._lock:
            self._open.append(phase)
        try:
            yield
        finally:
            with self._lock:
                for i in range(len(self._open) - 1, -1, -1):
                    if self._open[i] == phase:
                        del self._open[i]
                        break
            self.note(phase, self._clock() - t0)

    @property
    def current_phase(self) -> str | None:
        """The innermost phase currently being measured (None outside any
        bracket) — a hang dump's 'what was the loop doing' line."""
        with self._lock:
            return self._open[-1] if self._open else None

    def elapsed(self) -> float:
        with self._lock:
            return 0.0 if self._t0 is None else self._clock() - self._t0

    def summary(self) -> dict[str, float]:
        """`goodput/<phase>_s` for every phase (incl. the `other` remainder),
        `goodput/total_s`, and `goodput/goodput_pct`. Phases sum to total
        exactly."""
        with self._lock:
            total = 0.0 if self._t0 is None else self._clock() - self._t0
            tracked = sum(self._phase_s.values())
            out = {f"goodput/{p}_s": s for p, s in self._phase_s.items()}
            out["goodput/other_s"] = max(0.0, total - tracked)
            out["goodput/total_s"] = total
            out["goodput/goodput_pct"] = (
                100.0 * self._phase_s["step_compute"] / total if total > 0 else 0.0
            )
            if self._chip_count:
                chips = self._chip_count
                out["goodput/chip_count"] = float(chips)
                out["goodput/chip_hours"] = total * chips / 3600.0
                out["goodput/productive_chip_hours"] = (
                    self._phase_s["step_compute"] * chips / 3600.0
                )
                if self._price_per_chip_hour:
                    out["goodput/price_per_chip_hour"] = self._price_per_chip_hour
                    cost = out["goodput/chip_hours"] * self._price_per_chip_hour
                    out["goodput/cost_dollars"] = cost
                    # productive chip-hours bought per dollar: for a single
                    # segment this is goodput_pct/100/price, but aggregated
                    # across segments with different chip counts it weights
                    # each segment by what it actually cost
                    out["goodput/goodput_per_dollar"] = (
                        out["goodput/productive_chip_hours"] / cost
                        if cost > 0 else 0.0
                    )
            return out
