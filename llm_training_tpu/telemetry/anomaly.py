"""Host-side anomaly detection and provenance dumps.

Two pieces, both consumed by `callbacks.NanGuard` (the run-health guard):

- `EmaZScore`: an exponential-moving-average mean/variance tracker that
  scores each new loss / grad-norm sample in standard deviations. It turns
  the NaN guard into a general *spike* guard — a loss that jumps 8 sigma is
  a divergence precursor worth stopping on long before anything goes
  non-finite (arXiv 2204.06514 §5 stops-and-rewinds on exactly this
  signal). A warmup sample count gates scoring so early-training noise
  never false-positives, and spiking samples are NOT folded into the EMA
  (the tracker models the healthy process, not the excursion).

- anomaly dumps: on a non-finite or spiking step the guard writes
  `anomaly-<step>.json` into the run directory — the offending metric
  snapshot, the per-layer health gauges from the trainer's most recent
  health step (`trainer.last_health`), and the offending layer paths —
  so post-mortem starts from a file instead of a scrollback hunt.
"""

from __future__ import annotations

import json
import logging
import math
from pathlib import Path

logger = logging.getLogger(__name__)


class EmaZScore:
    """EMA mean/variance with z-scoring, for host-side scalar streams.

    `score(x)` returns the SIGNED z-score (x - mean) / std — positive means
    above the tracked mean (None until `warmup` samples have been folded
    in). Spike guards trip on positive z only: a sharp loss IMPROVEMENT
    (LR drop, curriculum boundary) is a large negative z and must never
    abort a converging run. `update(x)` folds a sample in (non-finite
    samples are ignored — the non-finite path has its own guard). The
    variance uses the standard EMA recurrence (West); the std is floored
    at 1% of |mean| so a plateaued loss does not z-score numeric jitter to
    infinity.
    """

    def __init__(self, beta: float = 0.98, warmup: int = 20):
        if not 0.0 < beta < 1.0:
            raise ValueError(f"beta must be in (0, 1), got {beta}")
        self.beta = beta
        self.warmup = warmup
        self.count = 0
        self.mean = 0.0
        self.var = 0.0

    def score(self, value: float) -> float | None:
        if self.count < self.warmup:
            return None
        if not math.isfinite(value):
            return math.inf
        # debias the EMA variance (it starts at 0, so the raw recurrence
        # underestimates early and would inflate z right after warmup)
        correction = 1.0 - self.beta ** max(self.count - 1, 1)
        var = self.var / correction
        std = max(math.sqrt(max(var, 0.0)), 0.01 * abs(self.mean), 1e-12)
        return (value - self.mean) / std

    def update(self, value: float) -> None:
        if not math.isfinite(value):
            return
        self.count += 1
        if self.count == 1:
            self.mean = value
            self.var = 0.0
            return
        delta = value - self.mean
        self.mean += (1.0 - self.beta) * delta
        self.var = self.beta * (self.var + (1.0 - self.beta) * delta * delta)


def offending_layers(health: dict | None, limit: int = 5) -> list[str]:
    """Layer groups whose gradients went non-finite in the most recent
    health snapshot — the NaN provenance list. Ordered as emitted (layer
    order), truncated to `limit` with a '... (+N more)' tail entry."""
    if not health:
        return []
    bad = [
        key.split("/", 2)[2]
        for key, value in health.items()
        if key.startswith("health/grad_norm/") and not math.isfinite(value)
    ]
    if len(bad) > limit:
        bad = bad[:limit] + [f"... (+{len(bad) - limit} more)"]
    return bad


def top_layers(
    health: dict | None, metric: str = "update_ratio", k: int = 3
) -> list[str]:
    """The k layer groups ranked worst by `health/<metric>/` — the spike
    provenance list (a spiking step's grads are finite; the suspects are
    the groups moving fastest relative to their weights)."""
    if not health:
        return []
    prefix = f"health/{metric}/"
    ranked = sorted(
        (
            (value, key[len(prefix):])
            for key, value in health.items()
            if key.startswith(prefix) and math.isfinite(value)
        ),
        reverse=True,
    )
    return [name for _, name in ranked[:k]]


def resolve_run_dir(trainer) -> Path | None:
    """Where anomaly dumps land: the first logger callback exposing a
    `run_dir` (JsonlLogger), else the checkpoint directory, else None —
    a guard with no run artifacts skips the dump rather than littering
    the working directory."""
    for cb in getattr(trainer, "callbacks", None) or []:
        run_dir = getattr(cb, "run_dir", None)
        if run_dir:
            return Path(run_dir)
    directory = getattr(getattr(trainer, "checkpointer", None), "directory", None)
    if directory:
        return Path(str(directory))
    return None


def _primary_host() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def _jsonable(value):
    try:
        f = float(value)
    except (TypeError, ValueError):
        return str(value)
    # json.dump rejects inf/nan by default; keep the record readable
    return f if math.isfinite(f) else str(f)


def dump_anomaly(
    run_dir: Path,
    step: int,
    reason: str,
    metrics: dict,
    offending: list[str] | None = None,
    health: dict | None = None,
    extra: dict | None = None,
) -> Path | None:
    """Write `anomaly-<step>.json` (process 0 only). Returns the path, or
    None when skipped/failed — the guard's abort must never be masked by a
    dump error."""
    if not _primary_host():
        return None
    try:
        run_dir = Path(run_dir)
        run_dir.mkdir(parents=True, exist_ok=True)
        path = run_dir / f"anomaly-{step}.json"
        payload = {
            "step": int(step),
            "reason": reason,
            "offending_layers": offending or [],
            "metrics": {k: _jsonable(v) for k, v in (metrics or {}).items()},
            "health": {k: _jsonable(v) for k, v in (health or {}).items()},
        }
        if extra:
            payload.update({k: _jsonable(v) if not isinstance(v, (dict, list)) else v
                            for k, v in extra.items()})
        path.write_text(json.dumps(payload, indent=2))
        # flight recorder (docs/observability.md#tracing): the trace ring's
        # last events — the steps/requests leading into the anomaly — land
        # next to the metric snapshot; flight_dump never raises
        from llm_training_tpu.telemetry.trace import get_tracer

        get_tracer().flight_dump(run_dir, f"anomaly-{step}")
        # matching-tag device profile: if the guard lets the run continue
        # (warn/rollback paths), the next steps get captured under the
        # same `anomaly-<step>` name as this host-side dump; on an abort
        # the armed request simply never gets polled
        from llm_training_tpu.telemetry.profiling import get_profile_trigger

        trigger = get_profile_trigger()
        if trigger is not None:
            trigger.request(f"anomaly-{step}", source="anomaly")
        return path
    except Exception:
        logger.exception("anomaly dump failed (step %d, reason %s)", step, reason)
        return None
