"""Fleet observability plane: replica discovery, multi-target aggregation,
federation + /fleetz surfaces (docs/observability.md#fleet).

The PR 14 exporter answers "is THIS process healthy right now" — one
`/metrics` port per process. Nothing answers the fleet questions a router
or an operator actually asks: how many replicas exist, which are red,
what is the total queue depth, did every accepted request complete
*somewhere*. This module is that layer, in three parts:

- **replica discovery** — every armed `MetricsExporter` drops a
  `replica-<pid>.json` card into the `LLMT_FLEET_DIR` directory (port,
  role train|serve|bench, supervisor attempt, and a wall↔monotonic start
  anchor) and removes it on clean stop. A SIGKILLed replica cannot remove
  its card, so discovery flags cards whose pid is dead as **stale**
  instead of scraping a corpse forever. Static `--targets host:port,...`
  skips discovery entirely (remote replicas have no shared filesystem).
- **aggregator** — `FleetAggregator` sweeps every discovered/configured
  replica's `/metrics` (the shared strict Prometheus parser — format
  drift fails loudly) and `/healthz`, composing ONE consistent snapshot:
  per-replica series, fleet rollups (counters summed; gauges as
  min/mean/max; explicit summed serve queue/completed views for the
  census cross-check), and a fleet health verdict that names red replicas
  and stale cards. A fleet-level `SLOMonitor` (PR 14) can ride the merged
  serve stream: each sweep feeds every serve replica's rolling TTFT/TPOT
  as one observation.
- **surfaces** — the aggregator re-exports `/metrics` (federation: the
  per-replica series labeled `{replica="<id>"}` plus unlabeled
  `llmt_fleet_*` rollups), `/fleetz` (a one-pager), and `/healthz`
  (fleet verdict); the `fleet` CLI subcommand wraps it (one-shot
  `--json`, polling watch dashboard, exit 2 — naming the searched paths
  — when no replicas are found).

Design contracts (mirrors the exporter's):

- **jax-free** (graftlint contract): the aggregator is a scrape *parent*
  like the loadgen — it must keep sweeping while replicas own backends,
  and it must run on machines that have none.
- **no new lock-order edges**: sweeps compose ENTIRELY outside
  `FleetAggregator._lock` (network I/O, parsing, rollups, the SLO feed)
  and only the finished snapshot swap happens under it; HTTP handler
  threads read that snapshot without calling into other subsystems while
  holding it.
- a dead/unreachable replica degrades to a red entry in the verdict,
  never an exception out of the sweep loop.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from llm_training_tpu.telemetry.exporter import (
    parse_prometheus_kinds,
    parse_prometheus_text,
)

logger = logging.getLogger(__name__)

FLEET_DIR_ENV = "LLMT_FLEET_DIR"
SCRAPE_INTERVAL_ENV = "LLMT_FLEET_SCRAPE_S"
CARD_SCHEMA = 1
ROLES = ("train", "serve", "bench", "router")

# serve gauges that roll up as FLEET SUMS (queue depth / in-flight /
# completed are "how much work, fleet-wide" — the census cross-check and
# the router's least-loaded pick read exactly these)
_SERVE_SUM_KEYS = (
    "llmt_serve_queue_depth",
    "llmt_serve_running",
    "llmt_serve_requests_completed",
    "llmt_serve_requests_failed",
    "llmt_serve_requests_shed",
    "llmt_serve_tokens_generated",
)

# router gauges that roll up the same way (the loadgen's --router census
# cross-check reads the fleet sums after a failover)
_ROUTER_SUM_KEYS = (
    "llmt_router_queue_depth",
    "llmt_router_inflight",
    "llmt_router_requests_total",
    "llmt_router_requests_completed",
    "llmt_router_requests_failed",
    "llmt_router_replays",
)


def resolve_fleet_dir() -> Path | None:
    """The discovery directory from `LLMT_FLEET_DIR` (unset/empty = fleet
    discovery off)."""
    raw = os.environ.get(FLEET_DIR_ENV)
    if not raw:
        return None
    return Path(raw)


def supervisor_attempt() -> int:
    """The 1-based supervised-relaunch attempt this process runs as, 0
    when unsupervised (`LLMT_SUPERVISOR_ATTEMPT` is set by the supervisor
    before each launch — docs/resilience.md)."""
    raw = os.environ.get("LLMT_SUPERVISOR_ATTEMPT")
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


# ------------------------------------------------------------------ cards


def write_replica_card(
    fleet_dir: str | Path,
    port: int,
    role: str = "train",
    host: str = "127.0.0.1",
) -> Path | None:
    """Drop this process's `replica-<pid>.json` discovery card. The card
    carries a wall+monotonic start anchor pair so fleet consumers can
    align replica uptimes the same way `trace --merge` aligns events.
    Never raises — discovery is observability, not the run's problem."""
    pid = os.getpid()
    attempt = supervisor_attempt()
    card = {
        "schema": CARD_SCHEMA,
        "replica_id": f"{role}-{attempt}-{pid}",
        "pid": pid,
        "host": host,
        "port": int(port),
        "role": role if role in ROLES else "train",
        "attempt": attempt,
        "start_wall_s": time.time(),
        "start_mono_s": time.monotonic(),
    }
    try:
        fleet_dir = Path(fleet_dir)
        fleet_dir.mkdir(parents=True, exist_ok=True)
        path = fleet_dir / f"replica-{pid}.json"
        # write-then-rename so a sweeping aggregator never reads a torn card
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(card) + "\n")
        tmp.replace(path)
    except OSError:
        logger.exception("fleet card write failed (discovery disabled)")
        return None
    logger.info("fleet: replica card %s (%s)", path.name, card["replica_id"])
    return path


def remove_replica_card(path: str | Path | None) -> None:
    if path is None:
        return
    try:
        Path(path).unlink(missing_ok=True)
    except OSError:
        logger.exception("fleet card remove failed: %s", path)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but not ours
    return True


def discover_replicas(fleet_dir: str | Path) -> list[dict]:
    """Read every `replica-*.json` card under `fleet_dir`. Each returned
    descriptor carries `stale=True` when the card's pid is dead — the
    SIGKILL signature (a clean stop removes the card). Torn/malformed
    cards are skipped, never raised."""
    replicas: list[dict] = []
    try:
        paths = sorted(Path(fleet_dir).glob("replica-*.json"))
    except OSError:
        return replicas
    for path in paths:
        try:
            card = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue  # torn mid-write or vanished mid-sweep
        if not isinstance(card, dict) or "port" not in card:
            continue
        pid = card.get("pid")
        card = dict(card)
        card.setdefault("host", "127.0.0.1")
        card.setdefault("role", "train")
        card.setdefault(
            "replica_id", f"{card['role']}-?-{pid if pid else path.stem}"
        )
        card["card_path"] = str(path)
        card["stale"] = not (isinstance(pid, int) and _pid_alive(pid))
        replicas.append(card)
    return replicas


def parse_targets(raw: str) -> list[dict]:
    """`host:port,host:port` -> static replica descriptors (role unknown:
    a static target has no card; its series still label by replica id)."""
    out: list[dict] = []
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        host, _, port_s = item.rpartition(":")
        try:
            port = int(port_s)
        except ValueError:
            logger.warning("fleet: ignoring malformed target %r", item)
            continue
        out.append({
            "replica_id": f"target-{host or '127.0.0.1'}:{port}",
            "host": host or "127.0.0.1",
            "port": port,
            "role": "serve",
            "stale": False,
            "static": True,
        })
    return out


def resolve_scrape_interval(default: float = 2.0) -> float:
    """The sweep cadence from `LLMT_FLEET_SCRAPE_S` (malformed/<=0 falls
    back to the default — observability never crashes the owner)."""
    raw = os.environ.get(SCRAPE_INTERVAL_ENV)
    if not raw:
        return default
    try:
        interval = float(raw)
    except ValueError:
        logger.warning(
            "ignoring malformed %s=%r (want seconds)", SCRAPE_INTERVAL_ENV, raw
        )
        return default
    return interval if interval > 0 else default


# ------------------------------------------------------------- aggregator


class FleetAggregator:
    """Background multi-target scrape loop -> one consistent fleet
    snapshot (per-replica series + rollups + health verdict), re-exported
    over HTTP (/metrics federation, /fleetz, /healthz).

    Sweeps compose outside `_lock` (every scrape, parse, rollup, and the
    optional SLO feed) and swap the finished snapshot under it; handler
    threads and `snapshot()` readers take the lock only for the swap-out.
    """

    def __init__(
        self,
        fleet_dir: str | Path | None = None,
        targets: str = "",
        interval_s: float | None = None,
        slo=None,
        timeout_s: float = 2.0,
        clock=time.monotonic,
    ):
        self.fleet_dir = Path(fleet_dir) if fleet_dir else None
        self.static_targets = parse_targets(targets)
        self.interval_s = (
            interval_s if interval_s else resolve_scrape_interval()
        )
        self.slo = slo
        self.timeout_s = timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._snapshot: dict = _empty_snapshot()  # guarded by: _lock
        self._sweeps = 0  # guarded by: _lock
        self._server: ThreadingHTTPServer | None = None  # guarded by: _lock
        self._http_thread: threading.Thread | None = None  # guarded by: _lock
        self._sweep_thread: threading.Thread | None = None  # guarded by: _lock
        self._stop = threading.Event()
        self.port: int | None = None  # bound federation port; guarded by: _lock

    # ------------------------------------------------------------- sweep

    def _scrape(self, host: str, port: int, path: str) -> tuple[int, str]:
        """(status, body) for one replica endpoint; raises OSError family
        on unreachable — callers turn that into a red entry."""
        import urllib.error
        import urllib.request

        url = f"http://{host}:{port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                return resp.status, resp.read().decode("utf-8", "replace")
        except urllib.error.HTTPError as e:
            # /healthz answers 503 WITH a body — that is an answer, not
            # an unreachable replica
            return e.code, e.read().decode("utf-8", "replace")

    def sweep(self) -> dict:
        """One full fleet sweep: discover, scrape every live replica,
        compose the snapshot, feed the fleet SLO — all outside `_lock` —
        then publish. Returns the fresh snapshot."""
        discovered = (
            discover_replicas(self.fleet_dir) if self.fleet_dir else []
        )
        replicas = discovered + list(self.static_targets)
        entries: dict[str, dict] = {}
        stale_cards: list[str] = []
        red: list[str] = []
        slo_feed: list[tuple[float | None, float | None, bool]] = []
        for card in replicas:
            rid = str(card["replica_id"])
            entry = {
                "role": card.get("role", "train"),
                "host": card["host"],
                "port": card["port"],
                "attempt": card.get("attempt"),
                "stale": bool(card.get("stale")),
                "healthy": False,
                "error": None,
                "metrics": {},
                "kinds": {},
            }
            if entry["stale"]:
                # a SIGKILLed replica's card: flagged, never scraped —
                # scraping a dead pid's port forever is how aggregators
                # rot (the port may have been reused by anything)
                stale_cards.append(rid)
                entry["error"] = "stale card (pid dead, card not removed)"
                entries[rid] = entry
                continue
            try:
                status, body = self._scrape(
                    card["host"], card["port"], "/metrics"
                )
                if status != 200:
                    raise OSError(f"/metrics answered {status}")
                entry["metrics"] = parse_prometheus_text(body)
                entry["kinds"] = parse_prometheus_kinds(body)
                h_status, h_body = self._scrape(
                    card["host"], card["port"], "/healthz"
                )
                entry["healthy"] = h_status == 200
                try:
                    entry["health_detail"] = json.loads(h_body)
                except (json.JSONDecodeError, ValueError):
                    entry["health_detail"] = {"raw": h_body[:200]}
                if not entry["healthy"]:
                    red.append(rid)
            except (OSError, ValueError) as e:
                entry["error"] = str(e)
                red.append(rid)
            entries[rid] = entry
            if entry["role"] == "serve" and not entry["stale"]:
                metrics = entry["metrics"]
                slo_feed.append((
                    metrics.get("llmt_serve_ttft_p99_ms"),
                    metrics.get("llmt_serve_tpot_p99_ms"),
                    entry["healthy"],
                ))
        verdict = "empty" if not entries else (
            "red" if (red or stale_cards) else "green"
        )
        snapshot = {
            "verdict": verdict,
            "replicas": entries,
            "red": red,
            "stale_cards": stale_cards,
            "rollup": _rollup(entries),
            "fleet_dir": str(self.fleet_dir) if self.fleet_dir else None,
        }
        # the fleet SLO rides the merged serve stream: one observation per
        # serve replica per sweep (rolling p99s as the latency sample, the
        # health verdict as ok) — outside _lock like everything above
        slo = self.slo
        if slo is not None:
            for ttft, tpot, ok in slo_feed:
                slo.observe_request(ttft_ms=ttft, tpot_ms=tpot, ok=ok)
            snapshot["slo_breaches"] = slo.breach_count()
        with self._lock:
            self._sweeps += 1
            snapshot["sweeps"] = self._sweeps
            self._snapshot = snapshot
        return snapshot

    def snapshot(self) -> dict:
        with self._lock:
            return self._snapshot

    def sweep_count(self) -> int:
        with self._lock:
            return self._sweeps

    # --------------------------------------------------------- lifecycle

    def start(self, port: int | None = None, host: str = "") -> bool:
        """Arm the background sweep loop and (when `port` is not None)
        the federation HTTP server. Bind failure degrades to a logged
        warning with the sweep loop still running — same never-the-run's-
        problem posture as the exporter."""
        aggregator = self
        server = None
        if port is not None:
            try:
                server = ThreadingHTTPServer((host, port), _FleetHandler)
            except OSError as e:
                logger.warning(
                    "fleet federation endpoint disabled: cannot bind "
                    "port %d (%s) — sweeps continue unexported", port, e,
                )
                server = None
        sweep_thread = threading.Thread(
            target=self._sweep_loop, name="fleet-sweep", daemon=True
        )
        http_thread = None
        if server is not None:
            server.daemon_threads = True
            server.aggregator = aggregator  # type: ignore[attr-defined]
            http_thread = threading.Thread(
                target=server.serve_forever, name="fleet-federation",
                daemon=True, kwargs={"poll_interval": 0.2},
            )
        with self._lock:
            self._server = server
            self._http_thread = http_thread
            self._sweep_thread = sweep_thread
            self.port = server.server_address[1] if server else None
        sweep_thread.start()
        if http_thread is not None:
            http_thread.start()
            logger.info(
                "fleet aggregator listening on port %d "
                "(/metrics /fleetz /healthz)", self.port,
            )
        return True

    def _sweep_loop(self) -> None:
        # sweep-then-wait: the first snapshot exists one sweep after
        # start(), not one interval after
        while True:
            try:
                self.sweep()
            except Exception:
                logger.exception("fleet sweep failed (loop continues)")
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            server, self._server = self._server, None
            http_thread, self._http_thread = self._http_thread, None
            sweep_thread, self._sweep_thread = self._sweep_thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if http_thread is not None:
            http_thread.join(timeout=5.0)
        if sweep_thread is not None:
            sweep_thread.join(timeout=5.0)

    # ----------------------------------------------------------- surfaces

    def render_metrics(self) -> str:
        """Federation text: every replica's series re-exported with a
        strict `{replica="<id>"}` label block, then the unlabeled
        `llmt_fleet_*` rollups. Output round-trips through
        `parse_prometheus_text(labels=True)` — pinned by the fleet smoke."""
        snapshot = self.snapshot()
        lines: list[str] = []
        typed: set[str] = set()
        for rid in sorted(snapshot["replicas"]):
            entry = snapshot["replicas"][rid]
            metrics = entry.get("metrics", {})
            kinds = entry.get("kinds", {})
            for name in sorted(metrics):
                if name not in typed:
                    typed.add(name)
                    lines.append(
                        f"# TYPE {name} {kinds.get(name, 'gauge')}"
                    )
                lines.append(
                    f'{name}{{replica="{rid}"}} {float(metrics[name])!r}'
                )
        rollup = snapshot["rollup"]
        for name in sorted(rollup):
            # rollups are derived views of the moment's sweep — gauges all
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(rollup[name])!r}")
        lines.append("# TYPE llmt_fleet_sweeps counter")
        lines.append(f"llmt_fleet_sweeps {float(snapshot.get('sweeps', 0))!r}")
        lines.append("")
        return "\n".join(lines)

    def health(self) -> tuple[bool, dict]:
        snapshot = self.snapshot()
        detail = {
            "status": "ok" if snapshot["verdict"] == "green" else "unhealthy",
            "verdict": snapshot["verdict"],
            "replicas": len(snapshot["replicas"]),
            "red": snapshot["red"],
            "stale_cards": snapshot["stale_cards"],
        }
        return snapshot["verdict"] == "green", detail

    def render_fleetz(self) -> str:
        """The one-pager: verdict first, red replicas and stale cards BY
        NAME, then one line per replica and the serve rollup."""
        snapshot = self.snapshot()
        rollup = snapshot["rollup"]
        lines = [
            "llm-training-tpu fleetz",
            "",
            f"verdict: {snapshot['verdict'].upper()}  "
            f"({len(snapshot['replicas'])} replica(s), "
            f"sweep #{snapshot.get('sweeps', 0)})",
        ]
        for rid in snapshot["red"]:
            entry = snapshot["replicas"].get(rid, {})
            lines.append(f"  RED: {rid} — {entry.get('error') or 'unhealthy'}")
        for rid in snapshot["stale_cards"]:
            lines.append(f"  STALE CARD: {rid} (pid dead; card not removed)")
        lines.append("")
        for rid in sorted(snapshot["replicas"]):
            entry = snapshot["replicas"][rid]
            state = (
                "stale" if entry["stale"]
                else "up" if entry["healthy"] else "RED"
            )
            parts = [
                f"{rid:<28s} {entry['role']:<5s} "
                f"{entry['host']}:{entry['port']:<6d} {state}"
            ]
            metrics = entry.get("metrics", {})
            if entry["role"] == "serve" and metrics:
                parts.append(
                    f"queue={metrics.get('llmt_serve_queue_depth', 0):.0f} "
                    f"running={metrics.get('llmt_serve_running', 0):.0f} "
                    f"done={metrics.get('llmt_serve_requests_completed', 0):.0f}"
                )
                ttft = metrics.get("llmt_serve_ttft_p99_ms")
                if ttft is not None:
                    parts.append(f"ttft_p99={ttft:.1f}ms")
            lines.append("  " + "  ".join(parts))
        serve_keys = [
            k for k in sorted(rollup) if k.startswith("llmt_fleet_serve_")
        ]
        if serve_keys:
            lines.append("")
            lines.append("serve rollup:")
            for key in serve_keys:
                lines.append(f"  {key} = {rollup[key]:.3f}")
        if "slo_breaches" in snapshot:
            lines.append("")
            lines.append(f"fleet slo breaches: {snapshot['slo_breaches']}")
        lines.append("")
        return "\n".join(lines)


def _empty_snapshot() -> dict:
    return {
        "verdict": "empty", "replicas": {}, "red": [], "stale_cards": [],
        "rollup": {}, "sweeps": 0, "fleet_dir": None,
    }


def _rollup(entries: dict[str, dict]) -> dict[str, float]:
    """Fleet rollups over the live, scrape-successful replicas: counters
    sum (`llmt_X` -> `llmt_fleet_X`), gauges spread to
    `llmt_fleet_X_min/_mean/_max`, and the serve work gauges ALSO sum
    unsuffixed (`_SERVE_SUM_KEYS` — queue/in-flight/completed are
    fleet-total questions; the census cross-check reads
    `llmt_fleet_serve_requests_completed`). Replica-count meta gauges ride
    along."""
    rollup: dict[str, float] = {}
    series: dict[str, list[float]] = {}
    kinds: dict[str, str] = {}
    live = 0
    healthy = 0
    stale = 0
    for entry in entries.values():
        if entry.get("stale"):
            stale += 1
            continue
        live += 1
        if entry.get("healthy"):
            healthy += 1
        for name, value in entry.get("metrics", {}).items():
            series.setdefault(name, []).append(float(value))
            kind = entry.get("kinds", {}).get(name, "gauge")
            if kinds.get(name, kind) == kind:
                kinds[name] = kind
    for name, values in series.items():
        fleet_name = "llmt_fleet_" + name.removeprefix("llmt_")
        if kinds.get(name) == "counter":
            rollup[fleet_name] = sum(values)
        else:
            rollup[f"{fleet_name}_min"] = min(values)
            rollup[f"{fleet_name}_mean"] = sum(values) / len(values)
            rollup[f"{fleet_name}_max"] = max(values)
        if name in _SERVE_SUM_KEYS or name in _ROUTER_SUM_KEYS:
            rollup[fleet_name] = sum(values)
    rollup["llmt_fleet_replicas"] = float(len(entries))
    rollup["llmt_fleet_replicas_live"] = float(live)
    rollup["llmt_fleet_replicas_healthy"] = float(healthy)
    rollup["llmt_fleet_replicas_red"] = float(live - healthy)
    rollup["llmt_fleet_stale_cards"] = float(stale)
    return rollup


class _FleetHandler(BaseHTTPRequestHandler):
    """Routes /metrics (federation), /fleetz, /healthz; anything else is
    404. Same posture as the exporter's handler: per-request daemon
    threads, content composed without the aggregator's lock held."""

    server_version = "llmt-fleet/1"

    def _send(self, code: int, content_type: str, body: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        aggregator: FleetAggregator = self.server.aggregator  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(
                    200, "text/plain; version=0.0.4; charset=utf-8",
                    aggregator.render_metrics(),
                )
            elif path == "/healthz":
                healthy, detail = aggregator.health()
                self._send(
                    200 if healthy else 503, "application/json",
                    json.dumps(detail) + "\n",
                )
            elif path == "/fleetz":
                self._send(
                    200, "text/plain; charset=utf-8",
                    aggregator.render_fleetz(),
                )
            else:
                self._send(404, "text/plain", "not found\n")
        except BrokenPipeError:
            pass  # scraper hung up mid-reply
        except Exception:
            logger.exception("fleet request failed (%s)", self.path)
            try:
                self._send(500, "text/plain", "internal error\n")
            except OSError:
                pass

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        logger.debug("fleet: " + format, *args)


# -------------------------------------------------------------------- CLI


def fleet_main(
    fleet_dir: str | None = None,
    targets: str = "",
    interval_s: float | None = None,
    port: int | None = None,
    host: str = "127.0.0.1",
    once: bool = False,
    as_json: bool = False,
    out: str | None = None,
    slo=None,
) -> int:
    """`llm-training-tpu fleet [--dir D | --targets h:p,...]`: sweep the
    fleet and render `/fleetz` (or `--json`). `--once` exits after one
    sweep — exit 2, naming every path searched, when no replicas were
    found. Without `--once` it polls like `watch`; `--port` additionally
    serves the federation endpoint. `--out` writes the snapshot JSON
    (what `report --format json` picks up as its `fleet` block)."""
    import sys

    resolved_dir = Path(fleet_dir) if fleet_dir else resolve_fleet_dir()
    if resolved_dir is None and not targets:
        print(
            f"fleet: nowhere to look — pass --dir/--targets or set "
            f"{FLEET_DIR_ENV} (docs/observability.md#fleet)",
            file=sys.stderr,
        )
        return 2
    aggregator = FleetAggregator(
        fleet_dir=resolved_dir, targets=targets,
        interval_s=interval_s, slo=slo,
    )

    def _render(snapshot: dict) -> str:
        if as_json:
            return json.dumps(snapshot, indent=2, sort_keys=True)
        return aggregator.render_fleetz().rstrip("\n")

    def _write_out(snapshot: dict) -> None:
        if out:
            try:
                Path(out).write_text(
                    json.dumps(snapshot, indent=2, sort_keys=True) + "\n"
                )
            except OSError as e:
                print(f"fleet: --out {out} unwritable ({e})", file=sys.stderr)

    if once:
        snapshot = aggregator.sweep()
        if not snapshot["replicas"]:
            searched = []
            if resolved_dir is not None:
                searched.append(
                    f"{resolved_dir}/replica-*.json"
                    + ("" if resolved_dir.is_dir() else " (dir absent)")
                )
            if targets:
                searched.append(f"targets [{targets}]")
            print(
                "fleet: no replicas found — searched "
                + " and ".join(searched)
                + " (arm exporters with LLMT_FLEET_DIR, or pass live "
                "--targets; docs/observability.md#fleet)",
                file=sys.stderr,
            )
            return 2
        print(_render(snapshot))
        _write_out(snapshot)
        return 0

    aggregator.start(port=port, host="" if port is not None else host)
    try:
        while True:
            time.sleep(aggregator.interval_s)
            snapshot = aggregator.snapshot()
            print(_render(snapshot), flush=True)
            _write_out(snapshot)
            if not as_json:
                print("---", flush=True)
    except KeyboardInterrupt:
        return 0
    finally:
        aggregator.stop()
