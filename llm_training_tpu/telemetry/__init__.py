"""Telemetry & goodput subsystem.

One registry + one goodput ledger per fit (owned by the Trainer), device
gauges sampled on log steps, `jax.profiler` annotations naming the same
phases, and a `report` CLI that renders the persisted artifacts. See
docs/observability.md for the schema and phase definitions.
"""

from llm_training_tpu.telemetry.device import compiled_cost_gauges, hbm_gauges
from llm_training_tpu.telemetry.goodput import PHASES, GoodputLedger
from llm_training_tpu.telemetry.registry import (
    TelemetryRegistry,
    get_registry,
    set_registry,
)

__all__ = [
    "PHASES",
    "GoodputLedger",
    "TelemetryRegistry",
    "compiled_cost_gauges",
    "get_registry",
    "hbm_gauges",
    "set_registry",
]
