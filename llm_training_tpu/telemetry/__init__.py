"""Telemetry & goodput subsystem.

One registry + one goodput ledger per fit (owned by the Trainer), device
gauges sampled on log steps, `jax.profiler` annotations naming the same
phases, a model-health layer (per-layer grad/update norms, MoE router
health, host-side spike detection + anomaly dumps), and a `report` CLI that
renders the persisted artifacts. See docs/observability.md for the schema
and phase definitions.
"""

from llm_training_tpu.telemetry.anomaly import (
    EmaZScore,
    dump_anomaly,
    offending_layers,
    resolve_run_dir,
    top_layers,
)
from llm_training_tpu.telemetry.device import compiled_cost_gauges, hbm_gauges
from llm_training_tpu.telemetry.goodput import PHASES, GoodputLedger
from llm_training_tpu.telemetry.health import (
    HealthConfig,
    build_param_groups,
    layer_health_metrics,
    moe_router_health,
)
from llm_training_tpu.telemetry.registry import (
    TelemetryRegistry,
    get_registry,
    set_registry,
)

__all__ = [
    "PHASES",
    "EmaZScore",
    "GoodputLedger",
    "HealthConfig",
    "TelemetryRegistry",
    "build_param_groups",
    "compiled_cost_gauges",
    "dump_anomaly",
    "get_registry",
    "hbm_gauges",
    "layer_health_metrics",
    "moe_router_health",
    "offending_layers",
    "resolve_run_dir",
    "set_registry",
    "top_layers",
]
