"""Telemetry & goodput subsystem.

One registry + one goodput ledger per fit (owned by the Trainer), device
gauges sampled on log steps, `jax.profiler` annotations naming the same
phases, a model-health layer (per-layer grad/update norms, MoE router
health, host-side spike detection + anomaly dumps), a request/step trace
recorder with a crash flight recorder (`telemetry/trace.py`), and a
`report` CLI that renders the persisted artifacts. See
docs/observability.md for the schema and phase definitions.

The package surface stays jax-free at import time: the health layer (the
one jax-importing submodule) loads lazily through ``__getattr__``, so the
serve scheduler — a graftlint jax-free contract — can import the tracer
through this package without pulling a backend.
"""

from llm_training_tpu.telemetry.anomaly import (
    EmaZScore,
    dump_anomaly,
    offending_layers,
    resolve_run_dir,
    top_layers,
)
from llm_training_tpu.telemetry.device import (
    HBMTimeline,
    compiled_attribution_gauges,
    compiled_cost_gauges,
    hbm_gauges,
)
from llm_training_tpu.telemetry.exporter import (
    MetricsExporter,
    resolve_metrics_port,
    start_exporter,
)
from llm_training_tpu.telemetry.goodput import PHASES, GoodputLedger
from llm_training_tpu.telemetry.profiling import (
    ProfileTrigger,
    build_profile_trigger,
    get_profile_trigger,
    set_profile_trigger,
)
from llm_training_tpu.telemetry.slo import (
    SLOMonitor,
    build_slo_monitor,
    slo_config_from_env,
)
from llm_training_tpu.telemetry.registry import (
    TelemetryRegistry,
    get_registry,
    set_registry,
)
from llm_training_tpu.telemetry.trace import (
    TraceRecorder,
    get_tracer,
    set_tracer,
)

# health imports jax at module level; resolve these names on first access so
# the package import graph stays backend-free (PEP 562)
_LAZY_HEALTH = (
    "HealthConfig",
    "build_param_groups",
    "layer_health_metrics",
    "moe_router_health",
)


def __getattr__(name):
    if name in _LAZY_HEALTH:
        from llm_training_tpu.telemetry import health

        return getattr(health, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "PHASES",
    "EmaZScore",
    "GoodputLedger",
    "HBMTimeline",
    "HealthConfig",
    "MetricsExporter",
    "ProfileTrigger",
    "SLOMonitor",
    "TelemetryRegistry",
    "TraceRecorder",
    "build_param_groups",
    "build_profile_trigger",
    "build_slo_monitor",
    "compiled_attribution_gauges",
    "compiled_cost_gauges",
    "dump_anomaly",
    "get_profile_trigger",
    "get_registry",
    "get_tracer",
    "hbm_gauges",
    "set_profile_trigger",
    "layer_health_metrics",
    "moe_router_health",
    "offending_layers",
    "resolve_metrics_port",
    "resolve_run_dir",
    "set_registry",
    "set_tracer",
    "slo_config_from_env",
    "start_exporter",
    "top_layers",
]
