"""Device gauges: HBM occupancy, XLA cost/memory analysis, and static
compute-vs-collective attribution (docs/observability.md#device-plane).

`hbm_gauges()` reads `device.memory_stats()` (PJRT allocator stats — the
source of truth for how close a run is to the HBM cliff) across ALL local
devices: the `hbm/*` family reports the WORST device (the one that OOMs
first — a single-device read hides the skewed shard that actually dies),
plus a mean and per-device gauges when more than one device is local.
Backends without allocator stats (the CPU test mesh) fall back to host
RSS so the gauges — and the tests/smoke runs that assert on them —
always exist; the `hbm/` prefix then means "process memory", which
docs/observability.md spells out.

`HBMTimeline` turns the same sample into a bounded `hbm.jsonl` timeline
in the run dir with trace instants when any device crosses a high-water
fraction — the post-mortem record for "which device filled up, when".

`compiled_cost_gauges()` pulls XLA's own FLOPs estimate and buffer sizes
from an AOT-compiled step — the cross-check for the analytic 6N+attention
MFU model in callbacks/time_estimator.py (XLA counts what was actually
compiled, including remat recompute; the analytic model deliberately
doesn't credit recompute).

`compiled_attribution_gauges()` walks the same Compiled object's HLO text
and splits the program into compute (FLOPs) vs collective bytes per op
family (all-reduce / all-gather / reduce-scatter / collective-permute)
and per mesh axis — the static comm-fraction estimate the pjit/TPUv4
paper's scaling methodology is built on, and the compute-vs-collective
split the pipeline-bubble work needs. It is a STATIC estimate: payload =
result-shape bytes per collective instruction, with no overlap model.

jax is imported lazily so `llm_training_tpu report` (which imports this
package) stays usable without touching an accelerator backend.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from pathlib import Path

logger = logging.getLogger(__name__)

_MEMORY_STAT_KEYS = (
    "bytes_in_use",
    "peak_bytes_in_use",
    "bytes_limit",
    "largest_alloc_size",
)


def _host_rss_bytes() -> tuple[float | None, float | None]:
    """(current, peak) resident set size of this process, or Nones."""
    current = peak = None
    try:
        import resource
        import sys

        # ru_maxrss is KiB on Linux but bytes on macOS
        scale = 1.0 if sys.platform == "darwin" else 1024.0
        peak = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * scale
    except Exception:  # pragma: no cover - non-POSIX
        pass
    try:
        import os

        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        current = float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:  # pragma: no cover - non-Linux
        pass
    return current, peak


def local_device_memory_stats() -> list[tuple[int, dict]]:
    """[(device_id, memory_stats)] for every local device that exposes
    allocator stats; [] when the backend has none (CPU) or jax is not
    importable/initialized."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception as e:  # backend not initialized / no devices
        logger.debug("local_devices unavailable: %s", e)
        return []
    out: list[tuple[int, dict]] = []
    for device in devices:
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — per-device probe must not raise
            stats = None
        if stats:
            out.append((int(getattr(device, "id", len(out))), dict(stats)))
    return out


def _device_pressure(stats: dict) -> float:
    """How close a device is to ITS OWN cliff: bytes_in_use/bytes_limit
    when a limit exists, raw bytes_in_use otherwise (still orders devices
    on a homogeneous slice)."""
    used = float(stats.get("bytes_in_use", 0.0) or 0.0)
    limit = float(stats.get("bytes_limit", 0.0) or 0.0)
    return used / limit if limit > 0 else used


def _gauges_from_stats(per_device: list[tuple[int, dict]]) -> dict[str, float]:
    """`hbm/*` gauges from a per-device stats sample: worst device under
    the legacy flat keys (back-compatible single-device view, coherent —
    every `hbm/<key>` comes from the SAME device), plus rollups and
    per-device gauges when the host has more than one device."""
    out: dict[str, float] = {}
    if per_device:
        worst_id, worst = max(per_device, key=lambda kv: _device_pressure(kv[1]))
        for key in _MEMORY_STAT_KEYS:
            if key in worst:
                out[f"hbm/{key}"] = float(worst[key])
        out["hbm/devices"] = float(len(per_device))
        if len(per_device) > 1:
            in_use = [
                float(s.get("bytes_in_use", 0.0) or 0.0) for _, s in per_device
            ]
            out["hbm/worst_device"] = float(worst_id)
            out["hbm/mean_bytes_in_use"] = sum(in_use) / len(in_use)
            for device_id, stats in per_device:
                for key in ("bytes_in_use", "peak_bytes_in_use"):
                    if key in stats:
                        out[f"hbm/device{device_id}/{key}"] = float(stats[key])
        return out
    current, peak = _host_rss_bytes()
    if current is not None:
        out["hbm/bytes_in_use"] = current
    if peak is not None:
        out["hbm/peak_bytes_in_use"] = peak
    if out:
        out["hbm/host_fallback"] = 1.0
    return out


def hbm_gauges() -> dict[str, float]:
    """`hbm/*` gauges aggregated across all local devices (worst device
    first-class — it OOMs first), with a host-RSS fallback when the
    backend exposes no allocator stats."""
    return _gauges_from_stats(local_device_memory_stats())


class HBMTimeline:
    """Bounded per-device HBM timeline in the run dir
    (docs/observability.md#device-plane).

    Sampled from the owning loop on log steps (single-threaded by design
    — no locking): each sample publishes the `hbm/*` rollup gauges,
    appends one record to `<run_dir>/hbm.jsonl` (capped at
    `LLMT_HBM_TIMELINE_MAX` records so a week-long run cannot grow the
    file unboundedly), and emits a trace instant the first time any
    device crosses `LLMT_HBM_HIGHWATER_FRAC` of its own limit (re-armed
    when it drops back below)."""

    def __init__(
        self,
        run_dir=None,
        registry=None,
        max_records: int | None = None,
        highwater_frac: float | None = None,
        clock=time.time,
    ):
        self.path = Path(run_dir) / "hbm.jsonl" if run_dir else None
        self._registry = registry
        self._clock = clock
        if max_records is None:
            max_records = int(os.environ.get("LLMT_HBM_TIMELINE_MAX") or 2048)
        self.max_records = max(1, max_records)
        if highwater_frac is None:
            highwater_frac = float(
                os.environ.get("LLMT_HBM_HIGHWATER_FRAC") or 0.9
            )
        self.highwater_frac = highwater_frac
        self._records = 0
        self._truncated = False
        self._over: set[int] = set()  # devices currently above high water
        self._highwater_events = 0

    def sample(self, step: int) -> dict[str, float]:
        """One timeline sample; returns the `hbm/*` gauges for the log-step
        metrics merge (plus `hbm_timeline/*` meta-gauges)."""
        per_device = local_device_memory_stats()
        gauges = _gauges_from_stats(per_device)
        self._check_highwater(step, per_device)
        self._append(step, per_device, gauges)
        gauges["hbm_timeline/records"] = float(self._records)
        if self._truncated:
            gauges["hbm_timeline/truncated"] = 1.0
        if self._highwater_events:
            gauges["hbm_timeline/highwater_events"] = float(
                self._highwater_events
            )
        return gauges

    def _check_highwater(self, step, per_device) -> None:
        from llm_training_tpu.telemetry.trace import get_tracer

        for device_id, stats in per_device:
            limit = float(stats.get("bytes_limit", 0.0) or 0.0)
            if limit <= 0:
                continue
            frac = float(stats.get("bytes_in_use", 0.0) or 0.0) / limit
            if frac >= self.highwater_frac and device_id not in self._over:
                self._over.add(device_id)
                self._highwater_events += 1
                if self._registry is not None:
                    self._registry.counter("hbm_timeline/highwater_events").inc()
                get_tracer().instant(
                    "hbm", "highwater", device=device_id, step=step,
                    frac=round(frac, 4), limit_bytes=limit,
                )
                logger.warning(
                    "device %d HBM high water: %.1f%% of %.2f GiB at step %d",
                    device_id, frac * 100, limit / 2**30, step,
                )
            elif frac < self.highwater_frac:
                self._over.discard(device_id)

    def _append(self, step, per_device, gauges) -> None:
        if self.path is None:
            return
        if self._records >= self.max_records:
            if not self._truncated:
                self._truncated = True
                logger.warning(
                    "hbm timeline capped at %d records (%s); later samples "
                    "keep the gauges but stop appending", self.max_records,
                    self.path,
                )
            return
        record: dict = {"step": int(step), "t": self._clock()}
        if per_device:
            record["devices"] = [
                {
                    "id": device_id,
                    **{k: stats[k] for k in _MEMORY_STAT_KEYS if k in stats},
                }
                for device_id, stats in per_device
            ]
        else:
            # host-RSS fallback sample (CPU): still a timeline, the docs
            # caveat on what `hbm/` means there applies here too
            record["host_fallback"] = True
            for key in ("hbm/bytes_in_use", "hbm/peak_bytes_in_use"):
                if key in gauges:
                    record[key.split("/", 1)[1]] = gauges[key]
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(record) + "\n")
            self._records += 1
        except OSError as e:
            logger.warning("hbm timeline append failed: %s", e)


def compiled_cost_gauges(compiled) -> dict[str, float]:
    """`xla/*` gauges from a `jax.stages.Compiled` train step."""
    out: dict[str, float] = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        for key, name in (
            ("flops", "xla/flops_per_step"),
            ("bytes accessed", "xla/bytes_accessed_per_step"),
        ):
            value = float(cost.get(key, 0.0) or 0.0)
            if value > 0:
                out[name] = value
    except Exception as e:
        logger.debug("cost_analysis unavailable: %s", e)
    try:
        mem = compiled.memory_analysis()
        for attr in (
            "temp_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "generated_code_size_in_bytes",
        ):
            value = getattr(mem, attr, None)
            if value is not None:
                out[f"xla/{attr}"] = float(value)
    except Exception as e:
        logger.debug("memory_analysis unavailable: %s", e)
    return out


# ------------------------------------------- compiled-program attribution

# HLO collective instruction heads. `-start` async variants count once;
# their `-done` halves carry no new payload and never match (the regex
# requires `(` right after the optional `-start`).
_COLLECTIVE_KINDS = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "collective-permute": "collective_permute",
}

_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute)"
    r"(?:-start)?\("
)

# `{dtype}[{dims}]` occurrences inside a result-shape string
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# `replica_groups={{0,1},{2,3}}` (explicit) — first group's cardinality
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9, ]*)\}")
# `replica_groups=[4,2]<=[8]` (iota form) — [n_groups, group_size]
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2": 1, "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e8m0fnu": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def _shape_bytes(shape_text: str) -> float:
    total = 0.0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:  # token/opaque/unknown: carries no payload
            continue
        count = 1
        for dim in dims.split(","):
            dim = dim.strip()
            if dim:
                count *= int(dim)
        total += width * count
    return total


def parse_hlo_collectives(hlo_text: str) -> list[dict]:
    """Every collective instruction in an HLO dump:
    `{"kind", "bytes", "group_size"}` per instruction. Pure text walk —
    unit-testable without a backend. `bytes` is the result-shape payload
    (the static transfer estimate); `group_size` is the participant count
    per replica group (None when the instruction does not say, e.g.
    collective-permute's source_target_pairs form)."""
    out: list[dict] = []
    for line in hlo_text.splitlines():
        match = _COLLECTIVE_RE.search(line)
        if match is None:
            continue
        group_size = None
        groups = _GROUPS_LIST_RE.search(line)
        if groups is not None:
            ids = [t for t in groups.group(1).replace(" ", "").split(",") if t]
            group_size = len(ids) or None
        else:
            iota = _GROUPS_IOTA_RE.search(line)
            if iota is not None:
                group_size = int(iota.group(2))
        out.append({
            "kind": _COLLECTIVE_KINDS[match.group("op")],
            "bytes": _shape_bytes(match.group("shape")),
            "group_size": group_size,
        })
    return out


def _axis_for_group(group_size, mesh_axes: dict[str, int] | None) -> str | None:
    """Attribute a collective to a mesh axis by matching its replica-group
    cardinality against the axis sizes. Ambiguous (two axes of equal size)
    or unmatched groups stay unattributed — an honest 'unknown' beats a
    coin flip — except on a mesh with exactly one non-trivial axis, where
    every collective can only belong to it."""
    if not mesh_axes:
        return None
    nontrivial = [name for name, size in mesh_axes.items() if size > 1]
    if group_size is not None:
        matches = [
            name for name, size in mesh_axes.items()
            if size == group_size and size > 1
        ]
        if len(matches) == 1:
            return matches[0]
    if len(nontrivial) == 1:
        return nontrivial[0]
    return None


def compiled_attribution_gauges(
    compiled, mesh_axes: dict[str, int] | None = None
) -> dict[str, float]:
    """`attr/*` gauges from a `jax.stages.Compiled` step: static FLOPs vs
    collective bytes, split per collective family and per mesh axis, plus
    the comm-fraction headline (`collective bytes / bytes accessed`,
    clamped to [0,1]) that report and bench track round-over-round.

    Always publishes the full family set (zeros included) so a mesh with
    no collectives — the single-device CPU smoke — still writes a stable
    `attr/` record a trend tracker can diff against."""
    out: dict[str, float] = {}
    flops = bytes_accessed = 0.0
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0) or 0.0)
        bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
    except Exception as e:
        logger.debug("cost_analysis unavailable for attribution: %s", e)
    try:
        hlo_text = compiled.as_text()
    except Exception as e:
        logger.debug("HLO text unavailable; no attr/ gauges: %s", e)
        return out
    collectives = parse_hlo_collectives(hlo_text or "")
    by_kind = {kind: 0.0 for kind in _COLLECTIVE_KINDS.values()}
    by_axis: dict[str, float] = {}
    total = 0.0
    for coll in collectives:
        by_kind[coll["kind"]] += coll["bytes"]
        total += coll["bytes"]
        axis = _axis_for_group(coll["group_size"], mesh_axes) or "unattributed"
        by_axis[axis] = by_axis.get(axis, 0.0) + coll["bytes"]
    out["attr/flops_per_step"] = flops
    out["attr/collective_bytes_per_step"] = total
    out["attr/collective_ops"] = float(len(collectives))
    out["attr/comm_fraction"] = (
        min(1.0, total / bytes_accessed) if bytes_accessed > 0 else 0.0
    )
    for kind, value in by_kind.items():
        out[f"attr/collective/{kind}_bytes"] = value
    for name, size in (mesh_axes or {}).items():
        if size > 1:
            out[f"attr/mesh/{name}/collective_bytes"] = by_axis.get(name, 0.0)
    if by_axis.get("unattributed"):
        out["attr/mesh/unattributed/collective_bytes"] = by_axis["unattributed"]
    return out
