"""Device gauges: HBM occupancy and XLA cost/memory analysis.

`hbm_gauges()` reads `device.memory_stats()` (PJRT allocator stats — the
source of truth for how close a run is to the HBM cliff). Backends without
allocator stats (the CPU test mesh) fall back to host RSS so the gauges —
and the tests/smoke runs that assert on them — always exist; the `hbm/`
prefix then means "process memory", which docs/observability.md spells out.

`compiled_cost_gauges()` pulls XLA's own FLOPs estimate and buffer sizes
from an AOT-compiled step — the cross-check for the analytic 6N+attention
MFU model in callbacks/time_estimator.py (XLA counts what was actually
compiled, including remat recompute; the analytic model deliberately
doesn't credit recompute).

jax is imported lazily so `llm_training_tpu report` (which imports this
package) stays usable without touching an accelerator backend.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

_MEMORY_STAT_KEYS = (
    "bytes_in_use",
    "peak_bytes_in_use",
    "bytes_limit",
    "largest_alloc_size",
)


def _host_rss_bytes() -> tuple[float | None, float | None]:
    """(current, peak) resident set size of this process, or Nones."""
    current = peak = None
    try:
        import resource
        import sys

        # ru_maxrss is KiB on Linux but bytes on macOS
        scale = 1.0 if sys.platform == "darwin" else 1024.0
        peak = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * scale
    except Exception:  # pragma: no cover - non-POSIX
        pass
    try:
        import os

        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        current = float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:  # pragma: no cover - non-Linux
        pass
    return current, peak


def hbm_gauges() -> dict[str, float]:
    """`hbm/*` gauges from the first local device's allocator stats, with a
    host-RSS fallback when the backend exposes none."""
    out: dict[str, float] = {}
    stats = None
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception as e:  # backend not initialized / no devices
        logger.debug("memory_stats unavailable: %s", e)
    if stats:
        for key in _MEMORY_STAT_KEYS:
            if key in stats:
                out[f"hbm/{key}"] = float(stats[key])
        return out
    current, peak = _host_rss_bytes()
    if current is not None:
        out["hbm/bytes_in_use"] = current
    if peak is not None:
        out["hbm/peak_bytes_in_use"] = peak
    if out:
        out["hbm/host_fallback"] = 1.0
    return out


def compiled_cost_gauges(compiled) -> dict[str, float]:
    """`xla/*` gauges from a `jax.stages.Compiled` train step."""
    out: dict[str, float] = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        for key, name in (
            ("flops", "xla/flops_per_step"),
            ("bytes accessed", "xla/bytes_accessed_per_step"),
        ):
            value = float(cost.get(key, 0.0) or 0.0)
            if value > 0:
                out[name] = value
    except Exception as e:
        logger.debug("cost_analysis unavailable: %s", e)
    try:
        mem = compiled.memory_analysis()
        for attr in (
            "temp_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "generated_code_size_in_bytes",
        ):
            value = getattr(mem, attr, None)
            if value is not None:
                out[f"xla/{attr}"] = float(value)
    except Exception as e:
        logger.debug("memory_analysis unavailable: %s", e)
    return out
