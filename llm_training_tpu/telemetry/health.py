"""On-device model-health metrics: per-layer-group gradient/parameter/update
norms and MoE router statistics.

Large-scale TPU training treats per-layer norm monitoring as the primary
tool for catching instabilities before they burn accelerator-hours (arXiv
2204.06514 §5): a run whose scalar loss still looks healthy can already have
one layer's gradients exploding. This module computes that signal INSIDE the
jitted train step (no extra forward, no host round trip beyond the one
`device_get` the trainer issues on health steps) at a configurable cadence —
`HealthConfig.every_n_steps`, default off, in which case the compiled train
step is byte-identical to the uninstrumented one.

Metric cardinality is bounded by grouping parameters per *layer group*
rather than per tensor: scanned decoder stacks (the flax `nn.scan` 'layers'
stacking axis) yield one group per layer index along the stack; unscanned
`layers_<i>` module paths group per block; everything else (embeddings,
final norm, lm_head) groups under its top-level module name. The grouping
spec is derived host-side from the *boxed* abstract parameter tree (the
`nn.Partitioned` logical-axis metadata identifies stacked leaves), so the
jitted metric computation is pure array math over a static plan.

Key schema (all fp32 scalars; see docs/observability.md):

- ``health/grad_norm/<group>``      — L2 norm of the group's gradients
- ``health/param_norm/<group>``     — L2 norm of the group's parameters
- ``health/update_norm/<group>``    — L2 norm of the optimizer update
- ``health/update_ratio/<group>``   — update_norm / param_norm (the classic
  "effective learning rate" stability signal; ~1e-3 is healthy, >>1e-2
  flags a layer about to blow up)
- ``health/moe/router_entropy/layer_<i>``  — normalized entropy of the
  layer's expert load distribution (1.0 = perfectly balanced, →0 = collapse)
- ``health/moe/max_expert_share/layer_<i>`` / ``min_expert_share`` — hottest
  / coldest expert's share of the layer's routed assignments
- ``health/moe/aux_loss/layer_<i>`` — per-layer Switch/Mixtral balancing
  loss E·Σ(f·P) (the pooled scalar the objective optimizes hides per-layer
  imbalance)
- ``health/moe/load_frac/expert_<e>`` — per-expert load fraction averaged
  over MoE layers (emitted only when num_experts <= MAX_EXPERT_KEYS)
- ``health/moe/dropped_rows`` / ``dropped_frac`` — (token, expert)
  assignments lost to capacity buffers (EP rank buffers / bucketed capacity)
"""

from __future__ import annotations

import math
import re

import flax.linen as nn
import jax
import jax.numpy as jnp
from pydantic import BaseModel, ConfigDict, Field

# per-expert load_frac keys are emitted only up to this expert count —
# beyond it the per-layer entropy/share scalars carry the signal without
# exploding metric cardinality (DeepSeek-V3 has 256 routed experts)
MAX_EXPERT_KEYS = 32

# scan-stacked parameter axes named by nn.scan's metadata_params
# (models use PARTITION_NAME 'layers'); pipeline parallelism adds a
# 'stages' vmap axis OUTSIDE it — per-layer keys must span (stage, layer)
# so provenance names one real decoder layer, not the same within-stage
# index of every stage
_STACK_AXIS_NAME = "layers"
_STAGE_AXIS_NAME = "stages"
_BLOCK_RE = re.compile(r"^(.+?)_(\d+)$")


class HealthConfig(BaseModel):
    """Trainer-level cadence for the model-health layer.

    `every_n_steps: None` (the default) disables it entirely — no health
    step is built and the compiled train step is unchanged. When set, every
    N-th optimizer step runs the instrumented step variant and the trainer
    publishes the host-fetched metrics into the telemetry registry (so
    `telemetry.jsonl`, W&B, and `report` pick them up with no extra wiring).
    The fetch forces one device sync per health step; `bench.py` tracks the
    cost as `health_overhead_pct` (sub-1% at every_n_steps >= 10 on the
    bench shapes — see docs/observability.md for guidance).
    """

    model_config = ConfigDict(extra="forbid")

    every_n_steps: int | None = Field(None, ge=1)


class ParamGroups:
    """Static per-leaf grouping plan: `leaves[i] = (group, axes, length)`
    aligned with the flatten order of the (unboxed) parameter tree. `axes`
    is the tuple of stacking axis indices for stacked leaves — ('stages',
    'layers') order under pipeline parallelism, so the flattened per-index
    norms enumerate GLOBAL decoder layers (stage s, within-stage i ⇒
    s·L/S + i) — and None for plain leaves; `length` is the flattened
    per-group index count."""

    def __init__(self, leaves: list[tuple[str, tuple[int, ...] | None, int | None]]):
        self.leaves = leaves

    def __len__(self) -> int:
        return len(self.leaves)


def _path_components(path) -> list[str]:
    comps = []
    for entry in path:
        key = getattr(entry, "key", None)
        if key is None:
            key = getattr(entry, "idx", None)
        if key is None:
            key = getattr(entry, "name", entry)
        comps.append(str(key))
    return comps


def _stack_base(prefix: list[str]) -> str:
    """Group base for a scan-stacked leaf: the path down to (and including)
    the scan module — 'layers'/'*_layers' by this repo's naming convention,
    falling back to the top component (the pipeline's 'pipeline/ticks'
    nesting). Multi-model objectives (DPO's policy/ref pair) keep their
    subtree prefix, so 'policy/layers' and 'ref/layers' never collide."""
    for i, comp in enumerate(prefix):
        if comp == _STACK_AXIS_NAME or comp.endswith("_" + _STACK_AXIS_NAME):
            return "/".join(prefix[: i + 1])
    return prefix[0] if prefix else "root"


def build_param_groups(boxed_params) -> ParamGroups:
    """Derive the layer-group plan from the BOXED abstract parameter tree
    (`jax.eval_shape` of init, before `nn.meta.unbox`): `nn.Partitioned`
    leaves whose logical names contain the scan stacking axis ('layers')
    group per index along that axis under their scan-module path
    (`layers_00`, `moe_layers_03`, `policy/layers_01`, ...); unscanned
    `<module>_<i>` path components group per block; everything else groups
    under its (subtree-qualified) module name. Boxed and unboxed trees
    flatten in the same leaf order, so the plan indexes straight into the
    step's params/grads/updates leaves."""
    flat, _ = jax.tree_util.tree_flatten_with_path(
        boxed_params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )
    leaves: list[tuple[str, int | None, int | None]] = []
    for path, leaf in flat:
        comps = [c for c in _path_components(path) if c != "params"]
        prefix = comps[:-1] if len(comps) > 1 else comps
        names = tuple(leaf.names) if isinstance(leaf, nn.Partitioned) else ()
        shape = leaf.value.shape if isinstance(leaf, nn.Partitioned) else leaf.shape
        if _STACK_AXIS_NAME in names:
            # stage axis (pipeline) first so the flattened index is the
            # global decoder-layer number
            axes = tuple(
                names.index(n) for n in (_STAGE_AXIS_NAME, _STACK_AXIS_NAME)
                if n in names
            )
            length = 1
            for axis in axes:
                length *= int(shape[axis])
            leaves.append((_stack_base(prefix), axes, length))
            continue
        group = None
        for i, comp in enumerate(prefix):
            match = _BLOCK_RE.match(comp)
            if match:
                stem, idx = match.groups()
                group = "/".join(prefix[:i] + [f"{stem}_{int(idx):02d}"])
                break
        if group is None:
            group = "/".join(prefix[:2]) if prefix else (comps[0] if comps else "root")
        leaves.append((group, None, None))
    return ParamGroups(leaves)


def _sq(x: jnp.ndarray, axes: tuple[int, ...] | None) -> jnp.ndarray:
    """Sum of squares reduced over everything but `axes`, returned FLAT in
    `axes` order (stage-major under PP ⇒ global layer order)."""
    x = x.astype(jnp.float32)
    if axes is None:
        return jnp.sum(x * x)
    out = jnp.sum(x * x, axis=tuple(i for i in range(x.ndim) if i not in axes))
    # the reduction keeps surviving dims in array order; permute to `axes`
    # order before flattening
    kept = sorted(axes)
    out = out.transpose([kept.index(a) for a in axes])
    return out.reshape(-1)


def layer_health_metrics(
    groups: ParamGroups, params, grads, updates, prefix: str = "health"
) -> dict[str, jnp.ndarray]:
    """Per-layer-group grad/param/update norms + update-to-param ratios,
    computed inside the jitted step (tiny reductions — XLA fuses them into
    the backward). Stacked groups emit one key per layer index
    (`<base>_<i:02d>`); the key set is static, the values are traced.

    Under gradient accumulation the health step runs on the boundary
    micro-step: grad norms reflect that single micro-batch's gradients —
    the SAME semantics as the headline `grad_norm` metric — while
    update norms reflect the full accumulated MultiSteps update (so
    update_ratio is the real per-optimizer-step movement)."""
    trees = (params, grads, updates)
    flat = [jax.tree.leaves(t) for t in trees]
    if any(len(f) != len(groups) for f in flat):
        raise ValueError(
            f"param-group plan covers {len(groups)} leaves but trees have "
            f"{[len(f) for f in flat]} — was the plan built from a different "
            "model?"
        )
    acc: dict[str, list] = {}
    meta: dict[str, int | None] = {}
    for i, (group, axes, length) in enumerate(groups.leaves):
        sqs = [_sq(f[i], axes) for f in flat]
        if group in acc:
            if meta[group] != length:
                # a scalar+vector (or mismatched-stack) mix would silently
                # broadcast into garbage norms — the grouping rule must keep
                # stacked and plain leaves in distinct groups
                raise ValueError(
                    f"param group {group!r} mixes leaves with stack lengths "
                    f"{meta[group]} and {length}"
                )
            acc[group] = [a + s for a, s in zip(acc[group], sqs)]
        else:
            acc[group] = sqs
            meta[group] = length
    out: dict[str, jnp.ndarray] = {}

    def emit(key: str, p_sq, g_sq, u_sq) -> None:
        p_n, g_n, u_n = jnp.sqrt(p_sq), jnp.sqrt(g_sq), jnp.sqrt(u_sq)
        out[f"{prefix}/param_norm/{key}"] = p_n
        out[f"{prefix}/grad_norm/{key}"] = g_n
        out[f"{prefix}/update_norm/{key}"] = u_n
        out[f"{prefix}/update_ratio/{key}"] = u_n / (p_n + 1e-12)

    for group, (p_sq, g_sq, u_sq) in acc.items():
        length = meta[group]
        if length is None:
            emit(group, p_sq, g_sq, u_sq)
        else:
            for i in range(length):
                emit(f"{group}_{i:02d}", p_sq[i], g_sq[i], u_sq[i])
    return out


def moe_router_health(router_stats, n_tokens: int) -> dict[str, jnp.ndarray]:
    """Per-MoE-layer router health from `CausalLMOutput.router_stats`
    (sel_frac [L, E], mean_prob [L, E], dropped scalar, static layer_ids).

    sel_frac rows sum to ~top_k (each of the K selections per token counts,
    HF load_balancing_loss_func scale), so the load distribution is the row
    normalized to 1. Entropy is normalized by log(E) → 1.0 when perfectly
    balanced. dropped_frac approximates dropped / total assignments using
    `n_tokens` for the token count (padding-token bias is negligible at the
    cadences this runs at)."""
    sel = router_stats.sel_frac.astype(jnp.float32)  # [L, E]
    prob = router_stats.mean_prob.astype(jnp.float32)
    n_layers, n_experts = sel.shape
    ids = router_stats.layer_ids or tuple(range(n_layers))
    load = sel / jnp.maximum(sel.sum(axis=-1, keepdims=True), 1e-9)
    entropy = -(load * jnp.log(load + 1e-9)).sum(axis=-1) / math.log(max(n_experts, 2))
    aux = n_experts * (sel * prob).sum(axis=-1)
    out: dict[str, jnp.ndarray] = {}
    for j, layer_id in enumerate(ids):
        key = f"layer_{int(layer_id):02d}"
        out[f"health/moe/router_entropy/{key}"] = entropy[j]
        out[f"health/moe/max_expert_share/{key}"] = load[j].max()
        out[f"health/moe/min_expert_share/{key}"] = load[j].min()
        out[f"health/moe/aux_loss/{key}"] = aux[j]
    if n_experts <= MAX_EXPERT_KEYS:
        mean_load = load.mean(axis=0)
        for e in range(n_experts):
            out[f"health/moe/load_frac/expert_{e:02d}"] = mean_load[e]
    dropped = jnp.asarray(router_stats.dropped, jnp.float32)
    total_rows = jnp.maximum(sel.sum() * float(n_tokens), 1.0)
    out["health/moe/dropped_rows"] = dropped
    out["health/moe/dropped_frac"] = dropped / total_rows
    return out
