"""Pull-based live-telemetry exporter: `/metrics`, `/statusz`, `/healthz`
(docs/observability.md#live-telemetry).

Every other observability signal in the repo is push-at-the-end
(telemetry.jsonl, trace.jsonl, `report`); nothing answers "is this run
healthy *right now*?" — table stakes for a serving fleet and for operating
long elastic runs. This module is the fleet-facing answer: a background
daemon thread runs a tiny stdlib HTTP server (enabled by
`LLMT_METRICS_PORT`, 0 = off) exposing

- **`/metrics`** — Prometheus text format rendered from ONE consistent
  `TelemetryRegistry` snapshot (`snapshot_with_kinds()` holds the registry
  lock for the whole flatten, so a scrape landing mid-write can never see
  a torn counter — pinned by the interleave harness), merged with the
  goodput ledger summary and any live per-subsystem gauges the owner
  wires in (the serve CLI's queue depth / rolling TTFT percentiles);
- **`/statusz`** — a human one-pager: goodput phase currently open,
  current step/segment (or serve queue depth + in-flight requests),
  watchdog beat age, and the SLO monitor's last alert;
- **`/healthz`** — liveness keyed off the `HangWatchdog` heartbeat: when
  the primary beat goes stale past `stale_after_s` (default HALF the
  watchdog timeout) the probe answers 503 **before** the watchdog aborts,
  so an external supervisor sees a wedged step while the process is still
  alive to scrape. The payload names the open goodput phase — what the
  loop is stuck inside.

Design contracts:

- **jax-free** (graftlint jax-free-import contract): scrape handler
  threads must never own device work — a handler that triggers a jax call
  could block behind the exact wedged dispatch `/healthz` exists to
  report. Everything rendered here is host-side state.
- **never the run's problem**: a port collision (or any bind failure)
  degrades to a logged warning and a disabled exporter, not a crash; a
  handler exception answers 500 and bumps `exporter/render_errors`.
- the scrape thread is registered in `contracts.THREAD_SHARED_CONTRACTS`
  and handler code composes its response WITHOUT holding the exporter's
  own lock while calling into other subsystems — each source (registry,
  ledger, watchdog, SLO monitor) does its own locking, so the exporter
  introduces no new lock-order edges.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)

METRICS_PORT_ENV = "LLMT_METRICS_PORT"

# Prometheus metric-name charset; everything else becomes '_'
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_PREFIX = "llmt_"


def find_free_port(host: str = "127.0.0.1") -> int:
    """Bind-then-release an OS-assigned ephemeral port — the shared probe
    for callers that must know the port BEFORE the exporter owner starts
    (bench's exporter stage, the precommit smokes). Inherently racy
    against other port grabbers, but the loser degrades to the exporter's
    logged-warning path, never a crash."""
    import socket

    probe = socket.socket()
    probe.bind((host, 0))
    port = probe.getsockname()[1]
    probe.close()
    return port


def resolve_metrics_port() -> int:
    """The exporter port from `LLMT_METRICS_PORT` (0/unset/malformed =
    disabled; malformed values warn once here rather than crash a fit)."""
    raw = os.environ.get(METRICS_PORT_ENV)
    if not raw:
        return 0
    try:
        port = int(raw)
    except ValueError:
        logger.warning(
            "ignoring malformed %s=%r (want an int port, 0=off)",
            METRICS_PORT_ENV, raw,
        )
        return 0
    return max(0, port)


def prometheus_name(key: str) -> str:
    """`goodput/total_s` -> `llmt_goodput_total_s` (Prometheus charset)."""
    return _PROM_PREFIX + _NAME_RE.sub("_", key)


def _prom_value(value: float) -> str:
    value = float(value)
    if value != value:  # NaN
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    return repr(value)


# strict label block: `{key="value",...}` — no spaces, no escapes, no
# trailing comma; exactly what the fleet federation endpoint emits
_LABELS_RE = re.compile(
    r"\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\]*\""
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\]*\")*\}"
)


def parse_prometheus_text(text: str, labels: bool = False) -> dict[str, float]:
    """Strict inverse of `render_prometheus`: {key_name: value}. Raises
    ValueError on ANY malformed line, so scrape validators (the loadgen
    cross-check, the precommit smokes, the unit tests) all fail loudly —
    and identically — on format drift. Stdlib-only like the rest of this
    module; the jax-free script parents import it.

    Per-process exporters emit no labels, so the default rejects them.
    `labels=True` (the fleet aggregator's federation output) accepts a
    strict `name{key="value",...}` block and keys the result by the FULL
    labeled name — distinct replicas stay distinct samples."""
    metrics: dict[str, float] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if not line.startswith(("# TYPE ", "# HELP ")):
                raise ValueError(f"bad comment line: {line!r}")
            continue
        parts = line.split()
        if len(parts) != 2:
            raise ValueError(f"bad sample line: {line!r}")
        name, raw = parts
        bare, brace, label_block = name.partition("{")
        if brace:
            if not labels or not _LABELS_RE.fullmatch(brace + label_block):
                raise ValueError(f"bad label block: {name!r}")
        if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", bare):
            raise ValueError(f"bad metric name: {name!r}")
        try:
            metrics[name] = float(raw)
        except ValueError:
            raise ValueError(f"bad sample value: {line!r}") from None
    if not metrics:
        raise ValueError("scrape held no samples")
    return metrics


def parse_prometheus_kinds(text: str) -> dict[str, str]:
    """{metric_name: 'counter'|'gauge'} from the `# TYPE` lines — the
    fleet aggregator needs kinds to roll up correctly (counters sum,
    gauges spread min/mean/max). Same strictness posture: a malformed
    TYPE line raises."""
    kinds: dict[str, str] = {}
    for line in text.splitlines():
        if not line.startswith("# TYPE "):
            continue
        parts = line.split()
        if len(parts) != 4 or parts[3] not in ("counter", "gauge"):
            raise ValueError(f"bad TYPE line: {line!r}")
        kinds[parts[2]] = parts[3]
    return kinds


def render_prometheus(
    values: dict[str, float], kinds: dict[str, str] | None = None
) -> str:
    """Prometheus text exposition (format version 0.0.4) for a flat metric
    dict. `kinds` maps source keys to 'counter'/'gauge'; unknown keys
    render as gauges. Keys whose values are not numeric are skipped — one
    bad gauge must not sink the whole scrape."""
    kinds = kinds or {}
    lines: list[str] = []
    seen: set[str] = set()
    for key in sorted(values):
        try:
            rendered = _prom_value(values[key])
        except (TypeError, ValueError):
            continue
        name = prometheus_name(key)
        if name in seen:  # sanitization collision: first key wins
            continue
        seen.add(name)
        kind = kinds.get(key, "gauge")
        if kind not in ("counter", "gauge"):
            kind = "gauge"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {rendered}")
    lines.append("")
    return "\n".join(lines)


class MetricsExporter:
    """Background-thread HTTP exporter over the run's live telemetry.

    Sources are all optional and polled per request (never cached — a
    scrape is a *now* question): `registry` (snapshot_with_kinds),
    `ledger` (goodput summary + open phase), `watchdog` (beat age ->
    /healthz), `slo` (an SLOMonitor: last alert for /statusz), `extra_fn`
    (live gauges merged into /metrics, e.g. serve queue depth), and
    `status_fn` (extra key:value lines for /statusz, e.g. current step).
    """

    def __init__(
        self,
        port: int,
        registry=None,
        ledger=None,
        watchdog=None,
        slo=None,
        profile=None,
        extra_fn=None,
        status_fn=None,
        stale_after_s: float | None = None,
        host: str = "",
        role: str = "train",
        clock=time.monotonic,
    ):
        self.requested_port = int(port)
        self.registry = registry
        self.ledger = ledger
        self.watchdog = watchdog
        self.slo = slo
        # a ProfileTrigger's jax-free REQUEST surface: /profilez arms a
        # capture window for the owning loop; the handler thread itself
        # never touches the device (docs/observability.md#profiling)
        self.profile = profile
        self.extra_fn = extra_fn
        self.status_fn = status_fn
        self.host = host
        # fleet discovery role (train|serve|bench) stamped on the replica
        # card when LLMT_FLEET_DIR is armed (docs/observability.md#fleet)
        self.role = role
        self._clock = clock
        # /healthz turns red at HALF the watchdog window by default: early
        # enough that a scraper sees the wedge before the SIGABRT
        if stale_after_s is None and watchdog is not None:
            stale_after_s = float(watchdog.timeout_s) / 2.0
        self.stale_after_s = stale_after_s
        self._started_at = clock()
        self._lock = threading.Lock()
        self._server: ThreadingHTTPServer | None = None  # guarded by: _lock
        self._thread: threading.Thread | None = None  # guarded by: _lock
        self.port: int | None = None  # bound port; guarded by: _lock
        self._scrapes = 0  # guarded by: _lock
        self._errors = 0  # guarded by: _lock
        self._card_path = None  # fleet discovery card; guarded by: _lock

    # ----------------------------------------------------------- lifecycle

    def start(self) -> bool:
        """Bind and serve; False (with a logged warning) when the port is
        taken or the bind fails any other way — the run must keep going
        without its exporter rather than die for observability."""
        exporter = self
        try:
            server = ThreadingHTTPServer(
                (self.host, self.requested_port), _Handler
            )
        except OSError as e:
            logger.warning(
                "metrics exporter disabled: cannot bind port %d (%s) — "
                "the run continues unscrapeable", self.requested_port, e,
            )
            return False
        server.daemon_threads = True
        server.exporter = exporter  # type: ignore[attr-defined]
        thread = threading.Thread(
            target=server.serve_forever, name="metrics-exporter", daemon=True,
            kwargs={"poll_interval": 0.2},
        )
        with self._lock:
            self._server = server
            self._thread = thread
            self.port = server.server_address[1]
        thread.start()
        logger.info(
            "metrics exporter listening on port %d "
            "(/metrics /statusz /healthz)", self.port,
        )
        # fleet discovery (docs/observability.md#fleet): an armed exporter
        # announces itself by card so an aggregator can find the fleet
        # without static config. Lazy import — fleet imports THIS module
        # at module level; both stay jax-free either way.
        from llm_training_tpu.telemetry.fleet import (
            resolve_fleet_dir,
            write_replica_card,
        )

        fleet_dir = resolve_fleet_dir()
        card = None
        if fleet_dir is not None:
            card = write_replica_card(fleet_dir, port=self.port, role=self.role)
        with self._lock:
            self._card_path = card
        return True

    def stop(self) -> None:
        # swap under the lock, shutdown/join outside it (the serve thread
        # never takes _lock, but symmetry with HangWatchdog.stop keeps the
        # pattern auditable)
        with self._lock:
            server, self._server = self._server, None
            thread, self._thread = self._thread, None
            card, self._card_path = self._card_path, None
        if card is not None:
            # clean stop removes the discovery card; a SIGKILL cannot, and
            # the aggregator's stale-pid check is what covers that hole
            from llm_training_tpu.telemetry.fleet import remove_replica_card

            remove_replica_card(card)
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    # ------------------------------------------------------------- content

    def metrics_snapshot(self) -> tuple[dict[str, float], dict[str, str]]:
        """(values, kinds) for /metrics: one consistent registry snapshot,
        the goodput summary, and the owner's live extras. Each source does
        its own locking; the exporter holds nothing while composing."""
        values: dict[str, float] = {}
        kinds: dict[str, str] = {}
        if self.registry is not None:
            snap, snap_kinds = self.registry.snapshot_with_kinds()
            values.update(snap)
            kinds.update(snap_kinds)
        if self.ledger is not None:
            values.update(self.ledger.summary())
        if self.extra_fn is not None:
            try:
                values.update(self.extra_fn())
            except Exception:  # a live-gauge bug must not kill the scrape
                logger.exception("exporter extra_fn failed (gauges dropped)")
        values["exporter/uptime_s"] = self._clock() - self._started_at
        with self._lock:
            values["exporter/scrapes"] = float(self._scrapes)
            values["exporter/render_errors"] = float(self._errors)
        kinds["exporter/scrapes"] = "counter"
        kinds["exporter/render_errors"] = "counter"
        return values, kinds

    def render_metrics(self) -> str:
        with self._lock:
            self._scrapes += 1
        if self.registry is not None:
            # the fit's registry carries the scrape counters into
            # telemetry.jsonl, so `report` shows whether anyone scraped
            self.registry.counter("exporter/scrapes").inc()
        values, kinds = self.metrics_snapshot()
        return render_prometheus(values, kinds)

    def health(self) -> tuple[bool, dict]:
        """(healthy, detail) for /healthz. Unhealthy when the watchdog's
        primary beat is older than `stale_after_s` — i.e. the step loop is
        wedged but the watchdog has not yet aborted. With no watchdog the
        probe only asserts the process answers (which the reply proves)."""
        detail: dict = {"status": "ok"}
        if self.ledger is not None:
            detail["phase"] = self.ledger.current_phase
        watchdog = self.watchdog
        if watchdog is not None:
            age = watchdog.beat_age()
            detail["beat_age_s"] = round(age, 3) if age is not None else None
            detail["watchdog_timeout_s"] = watchdog.timeout_s
            if (
                self.stale_after_s is not None
                and age is not None
                and age > self.stale_after_s
            ):
                detail["status"] = "unhealthy"
                detail["reason"] = (
                    f"no {watchdog.primary_source} heartbeat for "
                    f"{age:.1f}s (> {self.stale_after_s:.1f}s; watchdog "
                    f"aborts at {watchdog.timeout_s:.1f}s)"
                )
                return False, detail
        else:
            detail["watchdog"] = "none"
        return True, detail

    def _durability_status(self) -> tuple[str | None, str | None]:
        """(health-line warning, detail line) from the registry's ckpt/*
        gauges + verify counters — a red mirror or a failed scrub must be
        visible on /statusz BEFORE a restore needs the copy. (None, None)
        when the run has no durability surface armed."""
        if self.registry is None:
            return None, None
        values, _ = self.registry.snapshot_with_kinds()
        watched = (
            "checkpoint/verify_failures", "ckpt/mirror_lag_steps",
            "ckpt/mirrored_steps", "ckpt/mirror_verify_rejects",
            "ckpt/scrub_failures", "ckpt/scrub_last_ok",
        )
        if not any(key in values for key in watched):
            return None, None
        verify_failures = int(values.get("checkpoint/verify_failures", 0))
        rejects = int(values.get("ckpt/mirror_verify_rejects", 0))
        lag = values.get("ckpt/mirror_lag_steps")
        scrub_failures = int(values.get("ckpt/scrub_failures", 0))
        scrub_last_ok = values.get("ckpt/scrub_last_ok")
        problems: list[str] = []
        if verify_failures:
            problems.append(f"{verify_failures} verify failure(s)")
        if rejects:
            problems.append(f"{rejects} mirror reject(s)")
        if lag:
            problems.append(f"mirror {int(lag)} step(s) behind")
        if scrub_failures or scrub_last_ok == 0.0:
            problems.append(
                f"scrub failing ({scrub_failures} failure(s), last step "
                f"{int(values.get('ckpt/scrub_last_step', -1))})"
            )
        scrub = (
            "n/a" if scrub_last_ok is None
            else ("ok" if scrub_last_ok else "FAILED")
        )
        line = (
            f"durability: verify failures {verify_failures}  mirror lag "
            f"{int(lag) if lag is not None else 'n/a'} step(s) "
            f"({int(values.get('ckpt/mirrored_steps', 0))} mirrored)  "
            f"scrub last {scrub}"
        )
        return ("; ".join(problems) or None), line

    def render_statusz(self) -> str:
        lines = ["llm-training-tpu statusz", ""]
        healthy, detail = self.health()
        durability_warn, durability_line = self._durability_status()
        health_line = f"health: {'ok' if healthy else 'UNHEALTHY'}"
        if durability_warn:
            health_line += f"  [durability: {durability_warn}]"
        lines.append(health_line)
        if detail.get("reason"):
            lines.append(f"  {detail['reason']}")
        if self.ledger is not None:
            summary = self.ledger.summary()
            lines.append(
                f"goodput phase: {self.ledger.current_phase or '<none>'}  "
                f"({summary.get('goodput/goodput_pct', 0.0):.1f}% of "
                f"{summary.get('goodput/total_s', 0.0):.1f}s wall)"
            )
        if detail.get("beat_age_s") is not None:
            lines.append(
                f"watchdog: beat {detail['beat_age_s']:.1f}s ago "
                f"(timeout {detail['watchdog_timeout_s']:.1f}s)"
            )
        if durability_line is not None:
            lines.append(durability_line)
        if self.status_fn is not None:
            try:
                for key, value in self.status_fn().items():
                    lines.append(f"{key}: {value}")
            except Exception:
                logger.exception("exporter status_fn failed")
                lines.append("status provider failed (see log)")
        slo = self.slo
        if slo is not None:
            alert = slo.last_alert()
            if alert is not None:
                lines.append(
                    f"last alert: {alert['key']} burn "
                    f"{alert['burn_fast']:.1f}x/{alert['burn_slow']:.1f}x "
                    f"(breach #{alert['n']})"
                )
            else:
                lines.append("slo: no breaches")
        with self._lock:
            scrapes = self._scrapes
        lines.append(f"scrapes: {scrapes}")
        lines.append("")
        return "\n".join(lines)

    def render_profilez(self, query: str = "") -> tuple[int, str]:
        """(status, json body) for /profilez: arm an on-demand device
        profile through the trigger's jax-free request surface. `?tag=`
        names the capture (sanitized into the artifact name); the default
        tag counts requests so repeated pokes stay distinguishable. A
        suppressed request (budget/cooldown/busy) answers 429 — the
        refusal IS the budget working, not a server error."""
        trigger = self.profile
        if trigger is None:
            return 404, json.dumps(
                {"error": "no profile trigger armed on this process"}
            ) + "\n"
        params = urllib.parse.parse_qs(query)
        tag = params.get("tag", [None])[0]
        if not tag:
            tag = f"profilez-{trigger.status()['requested'] + 1}"
        result = trigger.request(tag, source="profilez")
        body = {**result, "status": trigger.status()}
        return (200 if result["accepted"] else 429), json.dumps(body) + "\n"

    def _note_error(self) -> None:
        with self._lock:
            self._errors += 1
        if self.registry is not None:
            # like exporter/scrapes: the registry copy rides into
            # telemetry.jsonl, so `report` shows render failures even
            # though the failing surface itself couldn't
            self.registry.counter("exporter/render_errors").inc()


class _Handler(BaseHTTPRequestHandler):
    """Routes /metrics, /statusz, /healthz, /profilez; anything else is
    404. Runs on the server's per-request daemon threads — all content
    comes from MetricsExporter methods that never touch jax (/profilez
    only ARMS a capture; the owning loop performs it)."""

    server_version = "llmt-exporter/1"

    def _send(self, code: int, content_type: str, body: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        exporter: MetricsExporter = self.server.exporter  # type: ignore[attr-defined]
        path, _, query = self.path.partition("?")
        try:
            if path == "/metrics":
                self._send(
                    200, "text/plain; version=0.0.4; charset=utf-8",
                    exporter.render_metrics(),
                )
            elif path == "/healthz":
                healthy, detail = exporter.health()
                self._send(
                    200 if healthy else 503, "application/json",
                    json.dumps(detail) + "\n",
                )
            elif path == "/statusz":
                self._send(
                    200, "text/plain; charset=utf-8", exporter.render_statusz()
                )
            elif path == "/profilez":
                code, body = exporter.render_profilez(query)
                self._send(code, "application/json", body)
            else:
                self._send(404, "text/plain", "not found\n")
        except BrokenPipeError:
            pass  # scraper hung up mid-reply
        except Exception:
            exporter._note_error()
            logger.exception("exporter request failed (%s)", self.path)
            try:
                self._send(500, "text/plain", "internal error\n")
            except OSError:
                pass

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        # scrape-per-second access logs belong in debug, not the run log
        logger.debug("exporter: " + format, *args)


def start_exporter(port: int | None = None, **sources) -> MetricsExporter | None:
    """Construct + start an exporter when enabled; None when the port is 0
    (`LLMT_METRICS_PORT` unset) or the bind fails. The one-call entry the
    trainer / serve CLI / bench stages use."""
    if port is None:
        port = resolve_metrics_port()
    if not port:
        return None
    exporter = MetricsExporter(port, **sources)
    return exporter if exporter.start() else None


# ------------------------------------------------------------------ profile


def profile_main(
    port: int | None = None,
    host: str = "127.0.0.1",
    tag: str | None = None,
    timeout_s: float = 5.0,
) -> int:
    """`llm-training-tpu profile [--port N] [--tag T]`: fire a live run's
    `/profilez` endpoint so the owning loop captures a device profile over
    its next steps (docs/observability.md#profiling). Stdlib-only like
    `watch` — runs from any operator machine. Exit 0 when the capture was
    armed, 3 when the trigger suppressed it (budget/cooldown/busy — the
    response says which), 2 when the exporter is unreachable."""
    import sys
    import urllib.error
    import urllib.request

    if port is None:
        port = resolve_metrics_port()
    if not port:
        print(
            "profile: no port — pass --port or set LLMT_METRICS_PORT "
            "(the run must export; docs/observability.md#profiling)",
            file=sys.stderr,
        )
        return 2
    url = f"http://{host}:{port}/profilez"
    if tag:
        url += "?" + urllib.parse.urlencode({"tag": tag})
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            body = resp.read().decode("utf-8", "replace")
            code = resp.status
    except urllib.error.HTTPError as e:  # 429 (suppressed) / 404 carry JSON
        body = e.read().decode("utf-8", "replace")
        code = e.code
    except (urllib.error.URLError, OSError) as e:
        print(f"profile: {url} unreachable ({e})", file=sys.stderr)
        return 2
    print(body.rstrip("\n"), flush=True)
    return 0 if code == 200 else 3


# -------------------------------------------------------------------- watch


def watch_main(
    port: int | None = None,
    host: str = "127.0.0.1",
    interval_s: float = 2.0,
    once: bool = False,
    timeout_s: float = 3.0,
) -> int:
    """`llm-training-tpu watch [--port N]`: poll a live run's `/statusz`
    and print each snapshot — a terminal dashboard over the exporter.
    Exit 2 when --once cannot reach the exporter; Ctrl-C exits 0."""
    import sys
    import urllib.error
    import urllib.request

    if port is None:
        port = resolve_metrics_port()
    if not port:
        print(
            "watch: no port — pass --port or set LLMT_METRICS_PORT "
            "(the run must export; docs/observability.md#live-telemetry)",
            file=sys.stderr,
        )
        return 2
    url = f"http://{host}:{port}/statusz"
    try:
        while True:
            try:
                with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                    body = resp.read().decode("utf-8", "replace")
                print(body.rstrip("\n"), flush=True)
            except (urllib.error.URLError, OSError) as e:
                print(f"watch: {url} unreachable ({e})", file=sys.stderr)
                if once:
                    return 2
            if once:
                return 0
            print("---", flush=True)
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0
