"""Triggered on-device profiler captures (docs/observability.md#profiling).

The device plane's flight recorder: when the host side decides something
is wrong — an SLO burn-rate breach, a hang watchdog about to SIGABRT, an
anomaly or rollback, an operator hitting `/profilez` or the serve
`{"type": "profile"}` control line — the NEXT few steps are exactly the
ones worth a device profile; a capture started any later records a
healthy program. `ProfileTrigger` splits the work across the two sides
of the repo's jax-free boundary:

- The **request surface** (`request()`, `schedule()`, `status()`) is
  jax-free and callable from any thread: the SLO monitor's breach path,
  the watchdog's dump path, the exporter's scrape handler threads, the
  serve stdin reader. It only records intent — enforcing the capture
  budget and cooldown (`LLMT_PROFILE_*` envs) so a burn-rate storm
  cannot profile-storm the run dir — and bumps `profile/*` counters.
- The **capture side** (`poll()`, `teardown()`) runs ONLY in the loop
  that owns the device (the trainer's optimizer-step loop, the serve
  engine loop). It imports jax lazily and drives
  `jax.profiler.start_trace`/`stop_trace` over a short step window. jax
  forbids nested captures, so a request arriving while a window is open
  is counted `profile/suppressed` instead of racing a second start —
  and the watchdog's pre-SIGABRT request can only ever be the marker
  half: its poll thread must never touch jax (a capture call there
  would block behind the very wedged dispatch it is reporting), so a
  hang profile materializes only if the loop limps through another
  step.

Artifacts land beside the correlated host flight dumps with MATCHING
tags: breach `n` of SLO target `train/step_time_p99_s` produces
`trace-flight-slo-train-step_time_p99_s-n.jsonl` (the host trace ring)
and `profile-slo-train-step_time_p99_s-n/` (the device trace) in the
same run dir, plus a `profile-<tag>.json` manifest that `report`
renders as the `== Profiling ==` section.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from pathlib import Path

from llm_training_tpu.telemetry.trace import get_tracer

logger = logging.getLogger(__name__)

# fallback artifact root when no run dir is known (mirrors the old
# ProfilerCallback default, so unconfigured captures stay findable)
DEFAULT_TRACE_ROOT = "runs/profile"

_TAG_SANITIZE = re.compile(r"[^A-Za-z0-9._-]+")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r (want a float)", name, raw)
        return default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


def sanitize_tag(tag: str) -> str:
    """Tags become file/dir names next to the flight dumps; collapse
    anything path-hostile instead of refusing the capture."""
    return _TAG_SANITIZE.sub("-", str(tag)).strip("-") or "capture"


class ProfileTrigger:
    """On-demand `jax.profiler` capture windows with budget + cooldown.

    One instance per process, owned by the loop that owns the device and
    published through `set_profile_trigger` so the jax-free layers (SLO
    monitor, watchdog, exporter handlers, serve reader) can reach the
    request surface without importing anything device-shaped.
    """

    def __init__(
        self,
        run_dir=None,
        registry=None,
        budget: int | None = None,
        cooldown_s: float | None = None,
        window_steps: int | None = None,
        clock=time.monotonic,
    ):
        self.run_dir = Path(run_dir) if run_dir else None
        self._registry = registry
        self._clock = clock
        # env knobs (docs/observability.md#profiling); explicit args win
        self.budget = (
            budget if budget is not None
            else _env_int("LLMT_PROFILE_BUDGET", 4)
        )
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else _env_float("LLMT_PROFILE_COOLDOWN_S", 120.0)
        )
        self.window_steps = max(1, (
            window_steps if window_steps is not None
            else _env_int("LLMT_PROFILE_STEPS", 2)
        ))
        root = os.environ.get("LLMT_PROFILE_DIR")
        if root:
            self.artifact_root = Path(root)
        elif self.run_dir is not None:
            self.artifact_root = self.run_dir
        else:
            self.artifact_root = Path(DEFAULT_TRACE_ROOT)
        self._lock = threading.Lock()
        self._pending: dict | None = None  # guarded by: _lock — accepted request awaiting poll()
        self._scheduled: list[dict] = []  # guarded by: _lock — config step windows
        self._active: dict | None = None  # guarded by: _lock — the open capture
        self._captures = 0  # guarded by: _lock
        self._requested = 0  # guarded by: _lock
        self._suppressed = 0  # guarded by: _lock
        self._last_accept_t: float | None = None  # guarded by: _lock
        self._history: list[dict] = []  # guarded by: _lock — completed captures (bounded)
        self._torn_down = False  # guarded by: _lock

    # ------------------------------------------------- jax-free request side

    def request(self, tag: str, source: str = "manual") -> dict:
        """Arm a capture window for the owning loop's next `poll()`.

        Jax-free and thread-safe: callable from scrape handlers, the SLO
        breach path, the watchdog poll thread, the serve reader. Returns
        `{"accepted": bool, "reason": ..., "tag": ...}`; a refusal is an
        answer, not an error. Counter side effects emit AFTER the lock is
        released (the SLOMonitor pattern), so this lock adds no edge into
        the registry leaf."""
        tag = sanitize_tag(tag)
        now = self._clock()
        with self._lock:
            if self._torn_down:
                reason = "torn-down"
            elif self._active is not None or self._pending is not None:
                # jax raises on nested start_trace; one window at a time
                reason = "busy"
            elif self._captures + len(self._scheduled) >= self.budget:
                reason = "budget"
            elif (
                self._last_accept_t is not None
                and now - self._last_accept_t < self.cooldown_s
            ):
                reason = "cooldown"
            else:
                reason = None
                self._last_accept_t = now
                self._pending = {"tag": tag, "source": source, "t_request": now}
            self._requested += 1
            if reason is not None:
                self._suppressed += 1
        registry = self._registry
        if registry is not None:
            registry.counter("profile/requested").inc()
            if reason is not None:
                registry.counter("profile/suppressed").inc()
                registry.counter(f"profile/suppressed/{reason}").inc()
        if reason is not None:
            logger.info(
                "profile request %r (source %s) suppressed: %s",
                tag, source, reason,
            )
        return {"accepted": reason is None, "reason": reason, "tag": tag}

    def schedule(
        self,
        start_step: int,
        num_steps: int,
        trace_dir: str | None = None,
        max_steps: int | None = None,
        source: str = "window",
    ) -> bool:
        """Register a config-time step window (the absorbed
        ProfilerCallback path): capture steps `[start_step, start_step +
        num_steps)`, stop boundary clamped to `max_steps` so a window
        overrunning the fit still closes inside the loop. Scheduled
        windows are explicit operator config — they count against the
        budget up front but bypass the cooldown."""
        stop_step = start_step + num_steps
        if max_steps is not None:
            stop_step = min(stop_step, max_steps)
        if stop_step <= start_step:
            logger.warning(
                "profile window [%d, %d) truncated to nothing; not tracing",
                start_step, start_step + num_steps,
            )
            return False
        entry = {
            "tag": sanitize_tag(f"window-{start_step}"),
            "source": source,
            "start_step": start_step,
            "stop_step": stop_step,
            "trace_dir": trace_dir,
        }
        with self._lock:
            self._scheduled.append(entry)
        return True

    def status(self) -> dict:
        """Jax-free snapshot for `/profilez` and tests."""
        with self._lock:
            return {
                "budget": self.budget,
                "cooldown_s": self.cooldown_s,
                "window_steps": self.window_steps,
                "requested": self._requested,
                "captures": self._captures,
                "suppressed": self._suppressed,
                "active": self._active["tag"] if self._active else None,
                "pending": self._pending["tag"] if self._pending else None,
                "scheduled": [dict(s) for s in self._scheduled],
                "history": [dict(h) for h in self._history[-8:]],
            }

    # --------------------------------------------- capture side (owner loop)

    def poll(self, step: int) -> None:
        """Drive at most ONE capture transition for this step. Called only
        by the loop that owns the device; the jax calls happen outside the
        lock, and stop-before-start means a window closing this step never
        nests with one opening."""
        start_info = stop_info = None
        with self._lock:
            if self._active is not None:
                if step >= self._active["stop_step"]:
                    stop_info = self._active
                    self._active = None
            else:
                info = self._take_due_locked(step)
                if info is not None:
                    self._active = info
                    start_info = info
        if stop_info is not None:
            self._finish_capture(stop_info, step)
        if start_info is not None and not self._begin_capture(start_info):
            with self._lock:
                self._active = None

    def _take_due_locked(self, step: int) -> dict | None:
        """The next capture due at `step`, with its window resolved.
        Caller holds `_lock`."""
        if self._torn_down:
            return None
        if self._pending is not None:
            # lint: allow(race-unguarded-shared): _locked-suffix helper — the only caller is poll(), which invokes it inside its `with self._lock:` block; the lexical checker cannot see through the call edge
            info, self._pending = self._pending, None
            info = dict(info)
            info["start_step"] = step
            info["stop_step"] = step + self.window_steps
            info.setdefault("trace_dir", None)
            return info
        for i, entry in enumerate(self._scheduled):
            # never start a window whose clamped stop boundary has passed
            # (a resume landing past the window must not open a trace only
            # teardown would close)
            if entry["start_step"] <= step < entry["stop_step"]:
                # lint: allow(race-unguarded-shared): _locked-suffix helper — caller (poll) holds _lock across this call
                del self._scheduled[i]
                return dict(entry)
            if step >= entry["stop_step"]:
                # lint: allow(race-unguarded-shared): _locked-suffix helper — caller (poll) holds _lock across this call
                del self._scheduled[i]
                return self._take_due_locked(step)
        return None

    def _trace_dir(self, info: dict) -> Path:
        explicit = info.get("trace_dir")
        if explicit:
            return Path(explicit)
        return self.artifact_root / f"profile-{info['tag']}"

    def _begin_capture(self, info: dict) -> bool:
        trace_dir = self._trace_dir(info)
        try:
            import jax

            trace_dir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(trace_dir))
        except Exception as e:  # noqa: BLE001 — profiling must never kill the run
            logger.warning(
                "profile capture %r failed to start (%s)", info["tag"], e
            )
            if self._registry is not None:
                self._registry.counter("profile/errors").inc()
            return False
        info["trace_dir"] = str(trace_dir)
        info["t_start"] = self._clock()
        with self._lock:
            self._captures += 1
        registry = self._registry
        if registry is not None:
            registry.counter("profile/captures").inc()
            registry.gauge("profile/last_capture_step").set(
                float(info["start_step"])
            )
        get_tracer().instant(
            "profile", "start", tag=info["tag"], source=info["source"],
            step=info["start_step"],
        )
        logger.info(
            "device profile %r started at step %d -> %s",
            info["tag"], info["start_step"], info["trace_dir"],
        )
        return True

    def _finish_capture(self, info: dict, step: int, reason: str = "window") -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "profile capture %r failed to stop (%s)", info["tag"], e
            )
            if self._registry is not None:
                self._registry.counter("profile/errors").inc()
            return
        duration = self._clock() - info.get("t_start", self._clock())
        record = {
            "tag": info["tag"],
            "source": info["source"],
            "start_step": info["start_step"],
            "stop_step": step,
            "trace_dir": info.get("trace_dir"),
            "duration_s": round(duration, 4),
            "stopped_by": reason,
        }
        with self._lock:
            self._history.append(record)
            del self._history[:-32]
        registry = self._registry
        if registry is not None:
            registry.gauge("profile/last_capture_duration_s").set(duration)
        get_tracer().instant(
            "profile", "stop", tag=info["tag"], step=step, reason=reason,
        )
        self._write_manifest(record)
        logger.info(
            "device profile %r stopped at step %d (%.2fs)",
            info["tag"], step, duration,
        )

    def _write_manifest(self, record: dict) -> None:
        """`profile-<tag>.json` beside the capture dir — what `report`
        reads. Never raises: a manifest error must not mask the condition
        being profiled."""
        try:
            self.artifact_root.mkdir(parents=True, exist_ok=True)
            path = self.artifact_root / f"profile-{record['tag']}.json"
            with open(path, "w") as f:
                json.dump(record, f)
                f.write("\n")
        except OSError as e:
            logger.warning("profile manifest write failed: %s", e)

    def teardown(self) -> None:
        """Stop a dangling capture (fit died mid-window) and refuse
        further requests. Idempotent; owner-loop only (it calls jax)."""
        with self._lock:
            self._torn_down = True
            active, self._active = self._active, None
            self._pending = None
            self._scheduled = []
        if active is not None:
            self._finish_capture(
                active, active["start_step"], reason="teardown"
            )


# Process-global trigger, mirroring trace.py's get_tracer/set_tracer: the
# jax-free layers (slo breach path, watchdog dump, anomaly dump, serve
# reader) resolve the owner loop's trigger through this module global.
_current_lock = threading.Lock()
_current: ProfileTrigger | None = None  # guarded by: _current_lock


def set_profile_trigger(trigger: ProfileTrigger | None) -> None:
    global _current
    with _current_lock:
        _current = trigger


def get_profile_trigger() -> ProfileTrigger | None:
    with _current_lock:
        return _current


def build_profile_trigger(registry=None, run_dir=None, **kwargs) -> ProfileTrigger:
    """Construct a trigger and publish it as the process global. Always
    returns one (unlike `build_slo_monitor` there is no arming config —
    `LLMT_PROFILE_BUDGET=0` refuses every request but keeps the counters
    and `/profilez` answering honestly)."""
    trigger = ProfileTrigger(run_dir=run_dir, registry=registry, **kwargs)
    set_profile_trigger(trigger)
    return trigger
