"""Render a post-hoc run summary from a run directory.

`llm-training-tpu report <run_dir>` reads the artifacts the loggers wrote
(`metrics.jsonl`, `telemetry.jsonl`, `run_metadata.json`) and prints a
human-readable summary: loss/throughput stats, the goodput breakdown table,
HBM peak, and MFU when the run recorded it. Pure stdlib — no jax import —
so it runs anywhere the run dir is mounted.
"""

from __future__ import annotations

import json
import statistics
import sys
from pathlib import Path

from llm_training_tpu.telemetry.goodput import PHASES

_GIB = 1024.0**3


def _read_jsonl(path: Path) -> list[dict]:
    records = []
    if not path.exists():
        return records
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            continue  # tolerate a torn tail from a killed run
    return records


def _fmt_seconds(s: float) -> str:
    return f"{s:,.2f}"


def _last_with(records: list[dict], key: str) -> dict | None:
    for record in reversed(records):
        if key in record:
            return record
    return None


def _last_run_segment(records: list[dict]) -> list[dict]:
    """Run dirs are opened in append mode (a legitimate resume continues the
    step sequence), so re-running a fixed-name config stacks multiple runs
    in one file. A step-number RESET marks a new run — summarize only the
    newest segment rather than silently pooling runs."""
    start = 0
    previous = None
    for i, record in enumerate(records):
        step = record.get("step")
        if step is None:
            continue
        if previous is not None and step < previous:
            start = i
        previous = step
    return records[start:]


def _goodput_table(telemetry: dict) -> list[str]:
    total = float(telemetry.get("goodput/total_s", 0.0))
    lines = [
        "== Goodput ==",
        f"{'phase':<16} {'seconds':>12} {'share':>8}",
    ]
    for phase in PHASES + ("other",):
        seconds = float(telemetry.get(f"goodput/{phase}_s", 0.0))
        share = 100.0 * seconds / total if total > 0 else 0.0
        lines.append(f"{phase:<16} {_fmt_seconds(seconds):>12} {share:>7.1f}%")
    lines.append(f"{'total':<16} {_fmt_seconds(total):>12} {100.0 if total > 0 else 0.0:>7.1f}%")
    lines.append(f"goodput: {float(telemetry.get('goodput/goodput_pct', 0.0)):.1f}% of wall time in step compute")
    return lines


def _health_section(telemetry: dict) -> list[str]:
    """Model-health summary from the `health/*` + `nan_guard/*` gauges
    (docs/observability.md): guard counters, the worst layer group by grad
    norm and update ratio, and the MoE balance extremes. Rendered only when
    the run recorded health telemetry (health.every_n_steps set)."""
    numeric: dict[str, float] = {}
    for key, value in telemetry.items():
        if not (key.startswith("health/") or key.startswith("nan_guard/")):
            continue
        try:
            numeric[key] = float(value)
        except (TypeError, ValueError):
            continue
    if not numeric:
        return []

    def by_prefix(prefix: str) -> dict[str, float]:
        return {
            key[len(prefix):]: value
            for key, value in numeric.items()
            if key.startswith(prefix)
        }

    lines = ["", "== Health =="]
    non_finite = numeric.get("nan_guard/non_finite_steps")
    spikes = numeric.get("nan_guard/spike_steps")
    if non_finite is not None or spikes is not None:
        lines.append(
            f"nan_guard: non_finite_steps {int(non_finite or 0)}  "
            f"spike_steps {int(spikes or 0)}"
        )
    grad = by_prefix("health/grad_norm/")
    if grad:
        worst = max(grad, key=grad.get)
        lines.append(
            f"layer groups: {len(grad)}  "
            f"grad_norm max: {grad[worst]:.3g} ({worst})"
        )
    ratio = by_prefix("health/update_ratio/")
    if ratio:
        worst = max(ratio, key=ratio.get)
        lines.append(f"update_ratio max: {ratio[worst]:.3g} ({worst})")
    entropy = by_prefix("health/moe/router_entropy/")
    if entropy:
        coldest = min(entropy, key=entropy.get)
        line = f"moe: router_entropy min {entropy[coldest]:.3f} ({coldest})"
        share = by_prefix("health/moe/max_expert_share/")
        if share:
            hottest = max(share, key=share.get)
            line += f"  max_expert_share {share[hottest]:.3f} ({hottest})"
        lines.append(line)
        if "health/moe/dropped_rows" in numeric:
            lines.append(
                f"moe dropped: {numeric['health/moe/dropped_rows']:.0f} rows "
                f"({100.0 * numeric.get('health/moe/dropped_frac', 0.0):.3f}%)"
            )
    return lines


def _decode_section(telemetry: dict) -> list[str]:
    """Inference telemetry (`decode/*` from `generate`, `eval/*` from
    `evaluate` — docs/inference.md): rendered only when the run dir saw an
    inference invocation merge its gauges into telemetry.jsonl."""
    def num(key):
        try:
            return float(telemetry[key])
        except (KeyError, TypeError, ValueError):
            return None

    lines = []
    prefill = num("decode/prefill_time_s")
    tps = num("decode/tokens_per_sec")
    if prefill is not None or tps is not None:
        line = "generate:"
        if prefill is not None:
            line += f" prefill_time_s {prefill:.3f}"
        if tps is not None:
            line += f"  decode_tokens_per_sec {tps:,.1f}"
        new_tokens = num("decode/new_tokens")
        if new_tokens is not None:
            line += f"  new_tokens {int(new_tokens)}"
        lines.append(line)
        cache = num("decode/cache_bytes")
        if cache is not None:
            line = f"kv cache: {cache / _GIB:.3f} GiB"
            max_len = num("decode/max_length")
            if max_len is not None:
                line += f" ({int(max_len)} slots)"
            lines.append(line)
    nll = num("eval/nll_per_token")
    if nll is not None:
        line = f"evaluate: nll/token {nll:.4f}"
        ppl = num("eval/perplexity")
        if ppl is not None:
            line += f"  perplexity {ppl:.2f}"
        tokens = num("eval/tokens")
        if tokens is not None:
            line += f"  over {int(tokens):,} tokens"
        lines.append(line)
    if not lines:
        return []
    return ["", "== Inference =="] + lines


def _serving_section(telemetry: dict) -> list[str]:
    """Serving telemetry (`serve/*` from the `serve` CLI / loadgen —
    docs/serving.md#telemetry): throughput, latency percentiles, and
    paged-pool pressure. Rendered only when a serve invocation merged its
    gauges into telemetry.jsonl."""
    def num(key):
        try:
            return float(telemetry[key])
        except (KeyError, TypeError, ValueError):
            return None

    completed = num("serve/requests_completed")
    tps = num("serve/tokens_per_sec")
    if completed is None and tps is None:
        return []
    lines = ["", "== Serving =="]
    line = f"requests: {int(completed or 0)} completed"
    failed = num("serve/requests_failed")
    if failed:
        line += f", {int(failed)} failed"
    # shed load (deadline/overloaded) is reported apart from failures —
    # the engine protecting its SLO is not an error condition
    shed_requests = num("serve/requests_shed")
    if shed_requests:
        line += f", {int(shed_requests)} shed"
    evicted = num("serve/requests_evicted")
    if evicted:
        line += f", {int(evicted)} evictions"
    peak = num("serve/peak_running")
    if peak is not None:
        line += f" (peak concurrency {int(peak)})"
    lines.append(line)
    # resilience counters (docs/serving.md#resilience): shed / expired /
    # hot-reloaded / replayed — each omitted when absent (an older run's
    # telemetry predates them) and the whole line omitted when all are
    shed = num("serve/shed_total")
    expired = num("serve/deadline_total")
    generation = num("serve/weights_generation")
    replayed = num("serve/replayed_requests")
    parts = []
    if shed:
        parts.append(f"{int(shed)} shed (overloaded)")
    if expired:
        parts.append(f"{int(expired)} deadline-expired")
    if generation:
        parts.append(f"weights generation {int(generation)}")
    if replayed:
        parts.append(f"{int(replayed)} replayed from journal")
    if parts:
        lines.append("resilience: " + ", ".join(parts))
    if tps is not None:
        line = f"throughput: {tps:,.1f} tokens/s"
        per_chip = num("serve/tokens_per_sec_per_chip")
        if per_chip is not None:
            line += f" ({per_chip:,.1f}/chip)"
        tokens = num("serve/tokens_generated")
        if tokens is not None:
            line += f" over {int(tokens):,} tokens"
        lines.append(line)
    for stat, label in (("ttft", "ttft"), ("tpot", "tpot")):
        p50, p99 = num(f"serve/{stat}_p50_ms"), num(f"serve/{stat}_p99_ms")
        if p50 is not None:
            line = f"{label}: p50 {p50:,.1f} ms"
            if p99 is not None:
                line += f"  p99 {p99:,.1f} ms"
            lines.append(line)
    total = num("decode/cache_blocks_total")
    peak_blocks = num("decode/cache_peak_blocks_in_use")
    if total:
        line = f"kv pool: {int(total)} blocks, peak {int(peak_blocks or 0)} in use"
        line += f" ({100.0 * (peak_blocks or 0) / total:.0f}%)"
        leaked = num("decode/cache_blocks_in_use")
        if leaked:
            line += f" — {int(leaked)} still held at exit (leak?)"
        lines.append(line)
    return lines


def _rl_section(telemetry: dict) -> list[str]:
    """RL post-training telemetry (`rl/*` from the `rl-fit` CLI —
    docs/post-training.md): rounds, reward, rollout accounting, and the
    weight-sync / SLO-yield counters. Rendered only when an rl-fit
    invocation merged its gauges into telemetry.jsonl."""
    def num(key):
        try:
            return float(telemetry[key])
        except (KeyError, TypeError, ValueError):
            return None

    rounds = num("rl/rounds")
    collected = num("rl/rollouts_collected")
    if rounds is None and collected is None:
        return []
    lines = ["", "== RL =="]
    line = f"rounds: {int(rounds or 0)}"
    reward = num("rl/mean_reward")
    if reward is not None:
        line += f", final mean reward {reward:.4f}"
    lines.append(line)
    parts = [f"{int(collected or 0)} collected"]
    stale = num("rl/rollouts_stale_dropped")
    failed = num("rl/rollouts_failed")
    if stale:
        # stale = tokens from an older weights generation: dropped by
        # contract, never trained on (docs/post-training.md#generations)
        parts.append(f"{int(stale)} stale-dropped")
    if failed:
        parts.append(f"{int(failed)} shed/failed")
    submitted = num("rl/rollouts_submitted")
    if submitted is not None:
        parts.append(f"of {int(submitted)} submitted")
    lines.append("rollouts: " + ", ".join(parts))
    yields = num("rl/rollout_yields")
    user_done = num("rl/user_requests_done")
    parts = []
    if yields:
        parts.append(f"{int(yields)} SLO yield(s)")
    if user_done:
        parts.append(f"{int(user_done)} user requests served alongside")
    if parts:
        lines.append("arbitration: " + ", ".join(parts))
    return lines


def _router_section(telemetry: dict) -> list[str]:
    """Router telemetry (`router/*` from the `route` CLI —
    docs/serving.md#router): request census, failover/replay, hedging, and
    elasticity counters, with an exactly-once verdict. Rendered only when a
    route invocation merged its gauges into telemetry.jsonl."""
    def num(key):
        try:
            return float(telemetry[key])
        except (KeyError, TypeError, ValueError):
            return None

    total = num("router/requests_total")
    if total is None:
        return []
    lines = ["", "== Router =="]
    completed = num("router/requests_completed") or 0
    failed = num("router/requests_failed") or 0
    line = f"requests: {int(total)} routed, {int(completed)} completed"
    if failed:
        line += f", {int(failed)} failed"
    peak = num("router/peak_inflight")
    if peak is not None:
        line += f" (peak in-flight {int(peak)})"
    lines.append(line)
    line = (
        f"replicas: {int(num('router/replicas') or 0)} live, "
        f"target {int(num('router/replicas_target') or 0)}"
    )
    evictions = num("router/evictions")
    if evictions:
        line += f", {int(evictions)} evictions"
    lines.append(line)
    parts = []
    failovers = num("router/failovers")
    if failovers:
        parts.append(f"{int(failovers)} failovers")
    replays = num("router/replays")
    if replays:
        parts.append(f"{int(replays)} replays")
    recovered = num("router/recovered_tokens")
    if recovered:
        parts.append(f"{int(recovered)} tokens recovered from journals")
    adoptions = num("router/leg_adoptions")
    if adoptions:
        parts.append(f"{int(adoptions)} leg adoptions")
    if parts:
        lines.append("failover: " + ", ".join(parts))
    parts = []
    hedges = num("router/hedges")
    if hedges:
        parts.append(f"{int(hedges)} hedged")
    wins = num("router/hedge_wins")
    if wins:
        parts.append(f"{int(wins)} hedge wins")
    dup = num("router/duplicate_terminals_suppressed")
    if dup:
        parts.append(f"{int(dup)} duplicate terminals suppressed")
    if parts:
        lines.append("hedging: " + ", ".join(parts))
    parts = []
    out = num("router/scale_out_total")
    if out:
        parts.append(f"{int(out)} scale-out")
    scale_in = num("router/scale_in_total")
    if scale_in:
        parts.append(f"{int(scale_in)} scale-in")
    if parts:
        lines.append("elasticity: " + ", ".join(parts))
    # the failover proof in one line: every routed request got exactly one
    # terminal (completed + failed == total), or the run is called out red
    if completed + failed == total:
        lines.append(f"exactly-once: green ({int(total)}/{int(total)} terminals)")
    else:
        lines.append(
            "exactly-once: RED "
            f"({int(completed + failed)}/{int(total)} terminals)"
        )
    return lines


def _newest_json_record(
    dirs: list[Path], patterns: tuple[str, ...]
) -> tuple[dict, str] | None:
    """The newest JSON dict matching `patterns` reachable from `dirs`:
    first directory with any match wins the tie; within it, newest mtime
    then name (BENCH_rNN names sort by round). Unreadable/non-dict files
    return None — the caller's section degrades or is omitted."""
    candidates: list[Path] = []
    for d in dirs:
        if d is None or not d.is_dir():
            continue
        for pattern in patterns:
            candidates.extend(d.glob(pattern))
        if candidates:
            break
    if not candidates:
        return None
    newest = max(candidates, key=lambda p: (p.stat().st_mtime, p.name))
    try:
        record = json.loads(newest.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(record, dict):
        return None
    return record, newest.name


def _newest_bench_record(dirs: list[Path]) -> tuple[dict, str] | None:
    """The newest bench record reachable from `dirs`. Accepts both shapes:
    a raw bench.py summary record and the driver's wrapper
    {n, cmd, rc, tail, parsed}."""
    found = _newest_json_record(dirs, ("BENCH_r*.json", "bench*.json"))
    if found is None:
        return None
    record, name = found
    if "parsed" in record:  # driver wrapper
        parsed = record.get("parsed")
        if not isinstance(parsed, dict):
            parsed = {"error": f"bench crashed before emitting a record "
                               f"(rc {record.get('rc')})"}
        record = parsed
    return record, name


def _perf_section(bench: tuple[dict, str] | None) -> list[str]:
    """Newest bench record (MFU, vs_baseline, flash blocks used, per-stage
    status — docs/performance.md). Omitted when no bench record is
    reachable from the run/bench dir."""
    if bench is None:
        return []
    record, name = bench
    header = ["", "== Perf ==", f"bench record: {name}"]
    try:
        return header + _perf_lines(record)
    except (KeyError, TypeError, ValueError, AttributeError):
        # the broad bench*.json glob (and its cwd fallback) can pick up a
        # foreign or malformed file — that must cost one honest line, not
        # crash the whole report for a run that never touched bench
        return header + ["unreadable bench record — malformed fields"]


def _perf_lines(record: dict) -> list[str]:
    lines = []
    value = record.get("value")
    if value is not None:
        line = f"mfu: {float(value):.4f}"
        if record.get("vs_baseline") is not None:
            line += f" (vs_baseline {float(record['vs_baseline']):.3f})"
        lines.append(line)
        extras = []
        if record.get("tokens_per_sec_per_chip") is not None:
            extras.append(f"tokens/sec/chip {float(record['tokens_per_sec_per_chip']):,.1f}")
        if record.get("sec_per_step") is not None:
            extras.append(f"sec_per_step {float(record['sec_per_step']):.4f}")
        if record.get("goodput_pct") is not None:
            extras.append(f"goodput {float(record['goodput_pct']):.1f}%")
        if extras:
            lines.append("  ".join(extras))
    else:
        lines.append(f"mfu: unavailable — {record.get('error', 'no value recorded')}")
    blocks = record.get("blocks") or {}
    if blocks:
        parts = [
            f"{kind} {int(bq)}x{int(bk)}"
            for kind, (bq, bk) in sorted(blocks.items())
        ]
        sources = record.get("block_sources") or {}
        src = ", ".join(f"{k} x{v}" for k, v in sorted(sources.items()))
        lines.append("flash blocks: " + "  ".join(parts) + (f"  (resolved: {src})" if src else ""))
    stages = record.get("stages") or {}
    if stages:
        parts = []
        for stage, info in stages.items():
            status = info.get("status", "?")
            part = f"{stage} {status}"
            if status == "error" and info.get("error"):
                part += f" ({info['error']})"
            parts.append(part)
        lines.append("stages: " + "  ".join(parts))
    if record.get("health_overhead_pct") is not None:
        lines.append(f"health_overhead_pct: {float(record['health_overhead_pct']):.2f}")
    if record.get("trace_overhead_pct") is not None:
        lines.append(f"trace_overhead_pct: {float(record['trace_overhead_pct']):.2f}")
    if record.get("decode_tokens_per_sec") is not None:
        lines.append(
            f"decode: {float(record['decode_tokens_per_sec']):,.1f} tokens/sec"
            + (f"  prefill {float(record['prefill_time_s']):.3f}s"
               if record.get("prefill_time_s") is not None else "")
        )
    return lines


def _newest_audit_record(dirs: list[Path]) -> tuple[dict, str] | None:
    """The newest shardcheck audit record (`--audit --json` output saved as
    audit*.json) reachable from `dirs`."""
    return _newest_json_record(dirs, ("audit*.json",))


def _newest_race_record(dirs: list[Path]) -> tuple[dict, str] | None:
    """The newest racecheck record (`--races --json` output saved as
    race*.json) reachable from `dirs` (precommit tees one next to
    audit.json)."""
    return _newest_json_record(dirs, ("race*.json",))


def _audit_section(
    audit: tuple[dict, str] | None,
    races: tuple[dict, str] | None,
    telemetry: dict,
) -> list[str]:
    """Newest shardcheck audit record (docs/static-analysis.md#audit):
    finding count, worst per-chip HBM estimate, and — when the run also
    recorded the measured `hbm/peak_bytes_in_use` gauge — the measured
    number next to the estimate so drift between the audit's model of HBM
    and reality is visible in one place. A race*.json from the `--races`
    gate adds its one-line summary (docs/static-analysis.md#racecheck).
    Omitted when neither record is reachable; a foreign/malformed record
    costs one honest line, mirroring `== Perf ==`."""
    if audit is None and races is None:
        return []
    lines = ["", "== Audit =="]
    if audit is not None:
        record, name = audit
        lines.append(f"audit record: {name}")
        try:
            lines.extend(_audit_lines(record, telemetry))
        except (KeyError, TypeError, ValueError, AttributeError):
            lines.append("unreadable audit record — malformed fields")
    if races is not None:
        record, name = races
        try:
            lines.extend(_race_lines(record, name))
        except (KeyError, TypeError, ValueError, AttributeError):
            lines.append(f"racecheck: unreadable race record {name} — malformed fields")
    return lines


def _race_lines(record: dict, name: str) -> list[str]:
    findings = record.get("findings")
    if not isinstance(findings, list):
        return [f"racecheck: unreadable race record {name} — malformed fields"]
    status = "FAIL" if findings else "OK"
    line = (
        f"racecheck: {status} — {len(findings)} finding(s) "
        f"(record {name}"
    )
    suppressed = record.get("suppressed")
    if suppressed:
        line += f", {int(suppressed)} suppressed"
    baselined = record.get("baselined")
    if baselined:
        line += f", {int(baselined)} baselined"
    line += ")"
    lines = [line]
    by_rule: dict[str, int] = {}
    for finding in findings:
        rule = finding.get("rule", "?") if isinstance(finding, dict) else "?"
        by_rule[rule] = by_rule.get(rule, 0) + 1
    if by_rule:
        lines.append(
            "race findings: "
            + "  ".join(f"{r} x{n}" for r, n in sorted(by_rule.items()))
        )
    return lines


def _audit_lines(record: dict, telemetry: dict) -> list[str]:
    lines = []
    findings = record.get("findings")
    families = record.get("families") or []
    meshes = record.get("meshes") or []
    if findings is None:
        lines.append(
            f"audit: unavailable — {record.get('error', 'no findings recorded')}"
        )
        return lines
    status = "FAIL" if findings else "OK"
    line = (
        f"shardcheck: {status} — {len(findings)} finding(s), "
        f"{len(families)} family(ies) x {len(meshes)} mesh(es)"
    )
    baselined = record.get("baselined")
    if baselined:
        line += f", {int(baselined)} baselined"
    lines.append(line)
    by_rule: dict[str, int] = {}
    for finding in findings:
        by_rule[finding.get("rule", "?")] = by_rule.get(finding.get("rule", "?"), 0) + 1
    if by_rule:
        lines.append(
            "findings: " + "  ".join(f"{r} x{n}" for r, n in sorted(by_rule.items()))
        )
    # lazy import: shard_audit is jax-free at module level, and this keeps
    # the one walk over the estimates schema in one place
    from llm_training_tpu.analysis.shard_audit import worst_estimate

    worst = worst_estimate(record.get("estimates") or {})
    if worst is not None:
        line = f"worst per-chip HBM estimate: {worst[2]:.3f} GiB ({worst[0]} @ {worst[1]}"
        budget = record.get("hbm_budget_gib")
        if budget is not None:
            line += f", budget {float(budget):.1f} GiB"
        line += ")"
        lines.append(line)
        measured = telemetry.get("hbm/peak_bytes_in_use")
        if measured is not None:
            # the audited families are the tiny registry proxies, not this
            # run's model — the cross-reference shows scale drift, not a
            # per-run prediction
            lines.append(
                f"measured hbm/peak_bytes_in_use: {float(measured) / _GIB:.3f} "
                "GiB (this run's model; audit estimates cover the registry "
                "families)"
            )
    return lines


def _read_supervisor_events(path: Path) -> list[dict] | None:
    """Events from a supervisor.jsonl, or None when the file is absent OR
    empty (a zero-byte log left by a killed supervisor says nothing and
    must not force the elastic section into a run's report). A log with
    content but no parseable events returns [] so the section can say so
    honestly instead of crashing."""
    if not path.is_file():
        return None
    try:
        if path.stat().st_size == 0:
            return None
    except OSError:
        return []
    try:
        records = _read_jsonl(path)
    except OSError:
        return []
    return [
        record for record in records
        if isinstance(record, dict) and "event" in record
    ]


def _elastic_section(
    telemetry_records: list[dict], supervisor_events: list[dict] | None
) -> list[str]:
    """Per-segment topology + aggregated goodput-per-dollar
    (docs/resilience.md#elastic).

    Two independent sources, each degrading on its own: `segment_topology`
    / `exit` events from supervisor.jsonl (the per-segment worlds), and the
    `elastic/segment`-tagged telemetry records (each segment's cumulative
    goodput/cost gauges — the LAST record per segment is its total).
    Omitted entirely for runs with nothing elastic to say: a single
    unsupervised segment with no chip-price metadata renders no section."""
    # last telemetry record per segment (cumulative gauges -> totals)
    segments: dict[int, dict] = {}
    for record in telemetry_records:
        seg = record.get("elastic/segment")
        if seg is None:
            continue
        try:
            segments[int(float(seg))] = record
        except (TypeError, ValueError):
            continue
    topology: dict[int, dict] = {}
    exits: dict[int, dict] = {}
    malformed_log = supervisor_events == []
    for event in supervisor_events or ():
        try:
            attempt = int(event.get("attempt", 0))
        except (TypeError, ValueError):
            continue
        if event.get("event") == "segment_topology":
            topology[attempt] = event
        elif event.get("event") == "exit":
            exits[attempt] = event

    has_cost = any("goodput/cost_dollars" in r for r in segments.values())
    if not topology and not malformed_log and not (
        has_cost or len(segments) > 1
    ):
        return []

    lines = ["", "== Elastic =="]
    if malformed_log:
        lines.append(
            "supervisor log present but unreadable — per-segment topology "
            "unavailable"
        )
    attempts = sorted(set(topology) | set(segments))
    for attempt in attempts:
        parts = [f"segment #{attempt}:"]
        event = topology.get(attempt)
        record = segments.get(attempt, {})
        chips = (
            event.get("device_count") if event is not None
            else record.get("goodput/chip_count")
        )
        # every field below may come from a foreign/corrupted-but-parseable
        # log: degrade per field, never crash the report
        try:
            parts.append(f"{int(float(chips))} device(s)")
        except (TypeError, ValueError):
            pass
        mesh = (event or {}).get("mesh")
        if isinstance(mesh, dict) and mesh:
            shown = [f"data={mesh.get('data', '?')}"]
            for axis, size in sorted(mesh.items()):
                try:
                    if axis != "data" and int(size) != 1:
                        shown.append(f"{axis}={size}")
                except (TypeError, ValueError):
                    continue
            parts.append("mesh " + " ".join(shown))
        if (event or {}).get("decision"):
            parts.append(f"[{event['decision']}]")
        runtime = exits.get(attempt, {}).get("runtime_s")
        if runtime is None and "goodput/total_s" in record:
            runtime = record["goodput/total_s"]
        if runtime is not None:
            try:
                parts.append(f"runtime {float(runtime):,.1f}s")
            except (TypeError, ValueError):
                pass
        if "goodput/cost_dollars" in record:
            try:
                parts.append(f"cost ${float(record['goodput/cost_dollars']):,.4f}")
            except (TypeError, ValueError):
                pass
        exit_event = exits.get(attempt)
        if exit_event is not None:
            parts.append(
                f"exit {exit_event.get('signal') or exit_event.get('rc')}"
            )
        lines.append("  ".join(parts))

    def total(key: str) -> float | None:
        values = []
        for record in segments.values():
            if key in record:
                try:
                    values.append(float(record[key]))
                except (TypeError, ValueError):
                    pass
        return sum(values) if values else None

    chip_hours = total("goodput/chip_hours")
    if chip_hours is not None:
        lines.append(
            f"chip-time: {chip_hours:.4f} chip-hours across "
            f"{len(segments)} segment(s)"
        )
    cost = total("goodput/cost_dollars")
    productive = total("goodput/productive_chip_hours")
    if cost is not None:
        line = f"cost: ${cost:,.4f}"
        if productive is not None and cost > 0:
            line += (
                f"  goodput-per-dollar: {productive / cost:.3f} "
                "productive chip-hours / $"
            )
        lines.append(line)
    elif segments or topology:
        lines.append(
            "cost: unavailable (no $/chip-hour — set LLMT_CHIP_PRICE_PER_HOUR "
            "or trainer.resilience.elastic.price_per_chip_hour)"
        )
    return lines


def _trace_summary(run_dir: Path) -> dict | None:
    """Span aggregates + slowest-request breakdowns from the run dir's
    trace.jsonl (docs/observability.md#tracing), or None when the run never
    traced. A present-but-unparseable file returns an `events: 0` summary
    so the section can say so honestly."""
    from llm_training_tpu.telemetry.trace import read_trace_events, summarize_trace

    path = run_dir / "trace.jsonl"
    if not path.is_file():
        return None
    return summarize_trace(read_trace_events(path))


def _fleet_summary(run_dir: Path) -> dict | None:
    """The fleet snapshot a `fleet --out <run_dir>/fleet.json` sweep left
    behind (docs/observability.md#fleet), shaped for trend tracking:
    verdict + per-replica health + rollups, without the per-replica
    metric bulk. None when the run never swept a fleet; a
    present-but-unparseable file returns an honest error record."""
    path = run_dir / "fleet.json"
    if not path.is_file():
        return None
    try:
        snapshot = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return {"error": f"{path.name} unparseable"}
    if not isinstance(snapshot, dict):
        return {"error": f"{path.name} is not a snapshot object"}
    replicas = {}
    for rid, entry in (snapshot.get("replicas") or {}).items():
        if isinstance(entry, dict):
            replicas[rid] = {
                key: entry.get(key)
                for key in ("role", "healthy", "stale", "error", "attempt")
            }
    return {
        "verdict": snapshot.get("verdict"),
        "sweeps": snapshot.get("sweeps"),
        "replicas": replicas,
        "red": snapshot.get("red"),
        "stale_cards": snapshot.get("stale_cards"),
        "rollup": snapshot.get("rollup"),
    }


def _fleet_section(summary: dict | None) -> list[str]:
    """`== Fleet ==`: the persisted sweep's verdict, red/stale names, and
    the serve rollups. Omitted when the run has no fleet.json."""
    if summary is None:
        return []
    lines = ["", "== Fleet =="]
    if summary.get("error"):
        lines.append(f"  {summary['error']}")
        return lines
    replicas = summary.get("replicas") or {}
    lines.append(
        f"  verdict: {str(summary.get('verdict', '?')).upper()} "
        f"({len(replicas)} replica(s))"
    )
    for rid in summary.get("red") or []:
        entry = replicas.get(rid) or {}
        lines.append(f"  red: {rid} — {entry.get('error') or 'unhealthy'}")
    for rid in summary.get("stale_cards") or []:
        lines.append(f"  stale card: {rid}")
    rollup = summary.get("rollup") or {}
    for key in sorted(rollup):
        if key.startswith("llmt_fleet_serve_") and not key.endswith(
            ("_min", "_mean", "_max")
        ):
            lines.append(f"  {key} = {float(rollup[key]):.3f}")
    return lines


def _trace_section(summary: dict | None) -> list[str]:
    """`== Trace ==`: per-phase span aggregates and the top-k slowest
    requests with their queue/prefill/decode breakdowns. Omitted when the
    run has no trace.jsonl; degrades to one honest line on a malformed or
    empty one."""
    if summary is None:
        return []
    lines = ["", "== Trace =="]
    try:
        if not summary.get("events"):
            lines.append("trace.jsonl present but holds no parseable events")
            return lines
        lines.append(
            f"events: {int(summary['events'])}  "
            f"requests traced: {int(summary.get('requests_traced', 0))} "
            f"({int(summary.get('requests_completed', 0))} completed)"
        )
        spans = summary.get("spans") or {}
        if spans:
            lines.append(f"{'span':<24} {'count':>6} {'total_s':>10} {'mean_ms':>9}")
            for name, agg in sorted(spans.items()):
                count = int(agg["count"])
                total = float(agg["total_s"])
                lines.append(
                    f"{name:<24} {count:>6} {total:>10.3f} "
                    f"{1000.0 * total / count:>9.2f}"
                )
        slowest = summary.get("slowest_requests") or []
        if slowest:
            lines.append("slowest requests:")
            for request in slowest:
                parts = [f"  {request['id']}: {float(request['wall_ms']):,.1f} ms"]
                breakdown = "  ".join(
                    f"{phase} {float(request.get(f'{phase}_ms', 0.0)):,.1f}"
                    for phase in ("queue", "prefill", "decode")
                )
                parts.append(f"({breakdown} ms)")
                if request.get("ttft_ms") is not None:
                    parts.append(f"ttft {float(request['ttft_ms']):,.1f} ms")
                if request.get("evictions"):
                    parts.append(f"{int(request['evictions'])} eviction(s)")
                lines.append("  ".join(parts))
    except (KeyError, TypeError, ValueError, AttributeError):
        return ["", "== Trace ==", "unreadable trace summary — malformed fields"]
    return lines


def _slo_section(telemetry: dict) -> list[str]:
    """SLO targets vs reality (`slo/*` gauges from telemetry/slo.py —
    docs/observability.md#slo): per-target line (target, worst observed,
    breach count) plus the totals line with the last breach's step /
    request ordinal. Omitted entirely when the run armed no SLO config —
    no slo/ keys, no section."""
    numeric: dict[str, float] = {}
    for key, value in telemetry.items():
        if not key.startswith("slo/"):
            continue
        try:
            numeric[key] = float(value)
        except (TypeError, ValueError):
            continue
    if not numeric:
        return []
    lines = ["", "== SLO =="]
    targets = sorted(
        key[len("slo/"):-len("/target")]
        for key in numeric if key.endswith("/target")
    )
    for name in targets:
        line = f"{name}: target {numeric[f'slo/{name}/target']:g}"
        worst = numeric.get(f"slo/{name}/worst")
        if worst is not None:
            line += f"  worst {worst:g}"
        breaches = numeric.get(f"slo/{name}/breaches", 0.0)
        line += f"  breaches {int(breaches)}"
        burn = numeric.get(f"slo/{name}/burn_fast")
        if burn is not None:
            line += f"  (burn {burn:.1f}x fast"
            slow = numeric.get(f"slo/{name}/burn_slow")
            if slow is not None:
                line += f" / {slow:.1f}x slow"
            line += ")"
        lines.append(line)
    total = numeric.get("slo/breaches_total", 0.0)
    line = f"breaches: {int(total)} total"
    last_step = numeric.get("slo/last_breach_step")
    if last_step is not None:
        line += f"  last at step {int(last_step)}"
    last_request = numeric.get("slo/last_breach_request_n")
    if last_request is not None:
        line += f"  last at request #{int(last_request)}"
    lines.append(line)
    return lines


def _profile_manifests(run_dir: Path) -> list[dict]:
    """Capture manifests (`profile-<tag>.json`, written by the
    ProfileTrigger next to each trace dir). A torn/unreadable manifest
    keeps its slot with an `error` field — the capture HAPPENED even if
    the record of it is damaged, and the report must say so."""
    entries: list[dict] = []
    for path in sorted(run_dir.glob("profile-*.json")):
        try:
            record = json.loads(path.read_text())
            if not isinstance(record, dict):
                raise ValueError("manifest must be a JSON object")
            record["file"] = path.name
            entries.append(record)
        except (OSError, ValueError) as e:
            entries.append({
                "file": path.name,
                "error": f"unreadable manifest ({type(e).__name__})",
            })
    return entries


def _profiling_summary(run_dir: Path, telemetry: dict) -> dict | None:
    """The structured `profiling` block (docs/observability.md#profiling):
    trigger counters, capture manifests, and the compiled-program
    compute/comm attribution gauges. None when the run recorded none of
    them — a run that never armed the trigger stays unchanged."""
    counters = _numeric_subset(telemetry, ("profile/", "hbm_timeline/"))
    attribution = _numeric_subset(telemetry, ("attr/",))
    captures = _profile_manifests(run_dir)
    if not counters and not attribution and not captures:
        return None
    return {
        "counters": counters or {},
        "attribution": attribution,
        "captures": captures,
    }


def _profiling_section(summary: dict | None) -> list[str]:
    """`== Profiling ==`: trigger activity (captures vs suppressions —
    the suppressed count is the budget/cooldown doing its job), one line
    per capture manifest, the static compute/comm attribution split, and
    the HBM timeline tally. Omitted when the run profiled nothing."""
    if summary is None:
        return []
    try:
        lines = ["", "== Profiling =="]
        counters = summary["counters"]
        requested = int(counters.get("profile/requested", 0.0))
        captures = int(counters.get("profile/captures", 0.0))
        suppressed = int(counters.get("profile/suppressed", 0.0))
        if requested or captures or suppressed:
            lines.append(
                f"captures: {captures} (requested {requested}, "
                f"suppressed {suppressed})"
            )
        errors = counters.get("profile/errors")
        if errors:
            lines.append(f"capture errors: {int(errors)}")
        for record in summary["captures"]:
            name = str(record.get("file", "?"))
            try:
                if record.get("error"):
                    lines.append(f"{name}: {record['error']}")
                    continue
                line = (
                    f"{name}: steps {int(record['start_step'])}"
                    f"..{int(record['stop_step'])}"
                )
                if record.get("duration_s") is not None:
                    line += f", {float(record['duration_s']):.2f}s"
                if record.get("source"):
                    line += f" ({record['source']})"
                lines.append(line)
            except (KeyError, TypeError, ValueError):
                # honest per-capture degrade: a torn manifest costs its
                # own line, never the section
                lines.append(f"{name}: unreadable manifest — malformed fields")
        attribution = summary["attribution"]
        if attribution:
            frac = attribution.get("attr/comm_fraction")
            if frac is not None:
                lines.append(
                    f"comm fraction: {100.0 * frac:.1f}% of bytes accessed"
                )
            flops = attribution.get("attr/flops_per_step")
            if flops is not None:
                lines.append(f"flops/step: {flops:.3g}")
            cbytes = attribution.get("attr/collective_bytes_per_step")
            if cbytes is not None:
                ops = int(attribution.get("attr/collective_ops", 0.0))
                lines.append(
                    f"collective bytes/step: {cbytes:,.0f} ({ops} op(s))"
                )
            for key in sorted(attribution):
                if key.startswith("attr/mesh/") and attribution[key]:
                    axis = key[len("attr/mesh/"):].rsplit("/", 1)[0]
                    lines.append(f"  mesh {axis}: {attribution[key]:,.0f} B")
            decode_frac = attribution.get("attr/decode/comm_fraction")
            if decode_frac is not None:
                lines.append(
                    f"decode comm fraction: {100.0 * decode_frac:.1f}%"
                )
        records = counters.get("hbm_timeline/records")
        if records:
            line = f"hbm timeline: {int(records)} record(s)"
            highwater = counters.get("hbm_timeline/highwater_events")
            if highwater:
                line += f", {int(highwater)} high-water crossing(s)"
            if counters.get("hbm_timeline/truncated"):
                line += " (truncated at cap)"
            lines.append(line)
        return lines
    except (KeyError, TypeError, ValueError):
        return ["", "== Profiling ==", "unreadable profiling data — malformed fields"]


def _counter_section(title: str, rows: list[tuple[str, str]], telemetry: dict) -> list[str]:
    """An event-counter section: one `label: count` line per nonzero
    counter, the whole section omitted when nothing fired — a clean run's
    report stays unchanged."""
    lines = []
    for key, label in rows:
        try:
            value = float(telemetry.get(key, 0.0))
        except (TypeError, ValueError):
            continue
        if value:
            lines.append(f"{label}: {int(value)}")
    if not lines:
        return []
    return ["", f"== {title} =="] + lines


def _recovery_section(telemetry: dict) -> list[str]:
    """Self-healing events (`resilience/rollbacks` etc. —
    docs/resilience.md#recovery)."""
    return _counter_section("Recovery", [
        ("resilience/rollbacks", "in-process rollbacks (rewind + resume)"),
        ("resilience/skip_windows", "poisoned data windows skipped"),
        ("resilience/skipped_steps", "micro-steps served from the reserve pool"),
        ("resilience/lr_cooldowns", "temporary LR cooldowns applied"),
        ("resilience/recovery_escalations", "recovery escalations (budget/same-step)"),
    ], telemetry)


def _resilience_section(telemetry: dict) -> list[str]:
    """Fault-tolerance event counters (`resilience/*` plus the retry
    counters — docs/resilience.md)."""
    return _counter_section("Resilience", [
        ("resilience/preemptions", "preemptions (graceful shutdowns)"),
        ("resilience/emergency_saves", "emergency checkpoint saves"),
        ("resilience/restore_fallbacks", "restore fallbacks (corrupt step skipped)"),
        ("resilience/watchdog_dumps", "watchdog hang dumps"),
        ("resilience/chaos_injections", "chaos-injected faults"),
        ("data/retries", "data-source retries"),
        ("checkpoint/retries", "checkpoint I/O retries"),
    ], telemetry)


def _durability_section(telemetry: dict) -> list[str]:
    """Checkpoint durability plane (docs/resilience.md#durability):
    verify/heal/scrub event counters plus the mirror's end-of-run state.
    Omitted entirely for runs with no mirror and no findings — like the
    other event sections, a clean unmirrored run's report is unchanged."""
    lines = _counter_section("Durability", [
        ("checkpoint/verify_failures",
         "checkpoint verify failures (offending file named in the log)"),
        ("checkpoint/mirror_restores", "restores healed from the mirror"),
        ("ckpt/mirror_verify_rejects",
         "mirror copies rejected by re-verification"),
        ("ckpt/gc_deleted", "mirror steps deleted by retention GC"),
        ("ckpt/scrub_ok", "scrub verifications passed"),
        ("ckpt/scrub_failures", "scrub verifications FAILED"),
    ], telemetry)
    if "ckpt/mirrored_steps" in telemetry:
        try:
            mirrored = int(float(telemetry["ckpt/mirrored_steps"]))
            lag = int(float(telemetry.get("ckpt/mirror_lag_steps", 0)))
        except (TypeError, ValueError):
            return lines
        if not lines:
            lines = ["", "== Durability =="]
        lines.append(f"mirrored steps: {mirrored} (lag {lag} step(s))")
    return lines


def _load_run(run_dir: Path) -> tuple[list[dict], list[dict], dict]:
    """(metrics, telemetry_records, telemetry-total) for the NEWEST run
    segment — the one loader both the text and JSON renderers consume, so
    segment handling can never drift between them."""
    metrics = _read_jsonl(run_dir / "metrics.jsonl")
    telemetry_records = _last_run_segment(_read_jsonl(run_dir / "telemetry.jsonl"))
    if not metrics and not telemetry_records:
        raise FileNotFoundError(
            f"no metrics.jsonl or telemetry.jsonl records under {run_dir}"
            " — is this a run directory?"
        )
    # serve/router run dirs are telemetry-only (no fit loop, no
    # metrics.jsonl): render from the telemetry ledger alone
    metrics = _last_run_segment(metrics)
    # the ledger is cumulative, so the newest record is the run total; fall
    # back to goodput keys embedded in metrics.jsonl (older runs / W&B-only)
    telemetry = (
        telemetry_records[-1]
        if telemetry_records
        else (_last_with(metrics, "goodput/total_s") or {})
    )
    return metrics, telemetry_records, telemetry


def _read_world(run_dir: Path) -> dict | None:
    meta_path = run_dir / "run_metadata.json"
    if not meta_path.exists():
        return None
    try:
        meta = json.loads(meta_path.read_text())
        world = meta.get("world", meta)
        return world if isinstance(world, dict) else None
    except Exception:
        return None


def _training_summary(metrics: list[dict]) -> dict | None:
    """The training-section numbers, shared by both renderers. None only
    when the run logged neither train-loss nor val-loss records."""
    train = [r for r in metrics if "loss" in r]
    last_tokens = _last_with(metrics, "consumed_tokens")
    val = _last_with(metrics, "val_loss")
    if not train and not val:
        return None
    steps = [int(r["step"]) for r in train if "step" in r]
    losses = [float(r["loss"]) for r in train]
    sps = [float(r["steps_per_sec"]) for r in train if "steps_per_sec" in r]
    return {
        "records": len(train),
        "step_min": min(steps) if steps else None,
        "step_max": max(steps) if steps else None,
        "loss_first": losses[0] if losses else None,
        "loss_last": losses[-1] if losses else None,
        "loss_min": min(losses) if losses else None,
        "steps_per_sec_median": statistics.median(sps) if sps else None,
        "steps_per_sec_last": sps[-1] if sps else None,
        "consumed_tokens": (
            int(last_tokens["consumed_tokens"]) if last_tokens else None
        ),
        "consumed_samples": (
            int(last_tokens.get("consumed_samples", 0)) if last_tokens else None
        ),
        "val_loss": float(val["val_loss"]) if val else None,
        "val_step": val.get("step") if val else None,
    }


def render_report(
    run_dir: str | Path,
    bench_dir: str | Path | None = None,
    supervisor_log: str | Path | None = None,
    audit_dir: str | Path | None = None,
) -> str:
    run_dir = Path(run_dir)
    metrics, telemetry_records, telemetry = _load_run(run_dir)

    lines = [f"Run report: {run_dir}"]
    world = _read_world(run_dir)
    if world:
        parts = [
            f"{key}={world[key]}"
            for key in ("backend", "device_kind", "device_count", "num_processes")
            if key in world
        ]
        if parts:
            lines.append("env: " + "  ".join(parts))

    training = _training_summary(metrics)
    lines.append("")
    lines.append("== Training ==")
    if training and training["records"]:
        lines.append(
            f"logged steps: {training['step_min']}..{training['step_max']} "
            f"({training['records']} records)"
        )
        lines.append(
            f"loss: first {training['loss_first']:.4f} -> last "
            f"{training['loss_last']:.4f} (min {training['loss_min']:.4f})"
        )
        if training["steps_per_sec_median"] is not None:
            lines.append(
                f"steps_per_sec: median {training['steps_per_sec_median']:.3f} "
                f"(last {training['steps_per_sec_last']:.3f})"
            )
        if training["consumed_tokens"] is not None:
            lines.append(
                f"consumed: {training['consumed_tokens']:,} tokens, "
                f"{training['consumed_samples']:,} samples"
            )
    if training and training["val_loss"] is not None:
        lines.append(
            f"val_loss: {training['val_loss']:.4f} "
            f"(step {training['val_step'] if training['val_step'] is not None else '?'})"
        )

    # MFU: the time estimator publishes perf/* gauges into telemetry
    for key, label in (
        ("perf/mfu", "MFU (analytic 6N+attention)"),
        ("perf/mfu_xla", "MFU (XLA cost_analysis)"),
        ("perf/tokens_per_sec", "tokens/sec"),
        ("perf/tokens_per_sec_per_device", "tokens/sec/device"),
    ):
        if key in telemetry:
            value = float(telemetry[key])
            lines.append(
                f"{label}: {value:.4f}" if "mfu" in key else f"{label}: {value:,.1f}"
            )
    if "compile_time_s" in telemetry:
        lines.append(f"compile_time_s: {float(telemetry['compile_time_s']):.2f}")

    lines.append("")
    lines.extend(_goodput_table(telemetry))

    hbm_peak = telemetry.get("hbm/peak_bytes_in_use")
    hbm_limit = telemetry.get("hbm/bytes_limit")
    if hbm_peak is not None:
        lines.append("")
        lines.append("== Device memory ==")
        source = "host RSS fallback" if telemetry.get("hbm/host_fallback") else "HBM"
        peak_line = f"peak: {float(hbm_peak) / _GIB:.2f} GiB ({source})"
        if hbm_limit:
            peak_line += (
                f" of {float(hbm_limit) / _GIB:.2f} GiB limit"
                f" ({100.0 * float(hbm_peak) / float(hbm_limit):.0f}%)"
            )
        lines.append(peak_line)

    lines.extend(_health_section(telemetry))
    lines.extend(_perf_section(_newest_bench_record([
        Path(bench_dir) if bench_dir else None, run_dir, Path.cwd(),
    ])))
    lines.extend(_audit_section(
        _newest_audit_record([
            Path(audit_dir) if audit_dir else None, run_dir,
        ]),
        _newest_race_record([
            Path(audit_dir) if audit_dir else None, run_dir,
        ]),
        telemetry,
    ))
    lines.extend(_decode_section(telemetry))
    lines.extend(_serving_section(telemetry))
    lines.extend(_rl_section(telemetry))
    lines.extend(_router_section(telemetry))
    lines.extend(_slo_section(telemetry))
    lines.extend(_profiling_section(_profiling_summary(run_dir, telemetry)))
    lines.extend(_trace_section(_trace_summary(run_dir)))
    lines.extend(_fleet_section(_fleet_summary(run_dir)))
    lines.extend(_elastic_section(
        telemetry_records,
        _read_supervisor_events(
            Path(supervisor_log) if supervisor_log
            else run_dir / "supervisor.jsonl"
        ),
    ))
    lines.extend(_recovery_section(telemetry))
    lines.extend(_resilience_section(telemetry))
    lines.extend(_durability_section(telemetry))
    return "\n".join(lines)


# schema_version of the JSON report below: bump on any breaking key change
# (tests/test_trace.py pins the top-level shape)
REPORT_SCHEMA_VERSION = 1


def _numeric_subset(telemetry: dict, prefixes: tuple[str, ...]) -> dict | None:
    """All numeric telemetry keys under `prefixes`, or None when the run
    recorded none of them (section omitted in the JSON like in the text)."""
    out: dict[str, float] = {}
    for key, value in telemetry.items():
        if not key.startswith(prefixes):
            continue
        try:
            out[key] = float(value)
        except (TypeError, ValueError):
            continue
    return out or None


def _supervisor_segments(events: list[dict] | None) -> list[dict] | None:
    """Per-segment topology/runtime rows from supervisor.jsonl events —
    the structured twin of what `== Elastic ==` renders. None when the log
    was absent or carried no segment events."""
    if not events:
        return None
    topology: dict[int, dict] = {}
    exits: dict[int, dict] = {}
    for event in events:
        try:
            attempt = int(event.get("attempt", 0))
        except (TypeError, ValueError):
            continue
        if event.get("event") == "segment_topology":
            topology[attempt] = event
        elif event.get("event") == "exit":
            exits[attempt] = event
    if not topology and not exits:
        return None
    return [
        {
            "attempt": attempt,
            "device_count": topology.get(attempt, {}).get("device_count"),
            "mesh": topology.get(attempt, {}).get("mesh"),
            "decision": topology.get(attempt, {}).get("decision"),
            "runtime_s": exits.get(attempt, {}).get("runtime_s"),
            "exit": (
                exits.get(attempt, {}).get("signal")
                or exits.get(attempt, {}).get("rc")
            ),
        }
        for attempt in sorted(set(topology) | set(exits))
    ]


def render_report_data(
    run_dir: str | Path,
    bench_dir: str | Path | None = None,
    supervisor_log: str | Path | None = None,
    audit_dir: str | Path | None = None,
) -> dict:
    """The machine-readable twin of `render_report` (`report --format
    json`): every section as structured data, for CI trend tracking of
    goodput/serve/trace numbers. Absent sections are null; `telemetry` is
    the newest persisted record verbatim so nothing numeric is lost to the
    section shaping."""
    run_dir = Path(run_dir)
    metrics, telemetry_records, telemetry = _load_run(run_dir)
    world = _read_world(run_dir)
    training = _training_summary(metrics)

    bench = _newest_bench_record([
        Path(bench_dir) if bench_dir else None, run_dir, Path.cwd(),
    ])
    audit = _newest_audit_record([
        Path(audit_dir) if audit_dir else None, run_dir,
    ])
    audit_data = None
    if audit is not None:
        record, name = audit
        findings = record.get("findings")
        by_rule: dict[str, int] = {}
        for finding in findings or []:
            if isinstance(finding, dict):
                rule = str(finding.get("rule", "?"))
                by_rule[rule] = by_rule.get(rule, 0) + 1
        audit_data = {
            "file": name,
            "findings": len(findings) if findings is not None else None,
            "by_rule": by_rule,
            "error": record.get("error"),
        }

    device_memory = None
    if telemetry.get("hbm/peak_bytes_in_use") is not None:
        device_memory = {
            "peak_bytes": float(telemetry["hbm/peak_bytes_in_use"]),
            "limit_bytes": (
                float(telemetry["hbm/bytes_limit"])
                if telemetry.get("hbm/bytes_limit") else None
            ),
            "host_fallback": bool(telemetry.get("hbm/host_fallback")),
        }

    # elastic: the flat gauges plus the per-segment rows text mode renders
    # from supervisor.jsonl (same default path as `== Elastic ==`)
    elastic_gauges = _numeric_subset(telemetry, ("elastic/",))
    segments = _supervisor_segments(_read_supervisor_events(
        Path(supervisor_log) if supervisor_log
        else run_dir / "supervisor.jsonl"
    ))
    elastic = None
    if elastic_gauges or segments:
        elastic = {"gauges": elastic_gauges or {}, "segments": segments}

    return {
        "schema_version": REPORT_SCHEMA_VERSION,
        "run_dir": str(run_dir),
        "world": world,
        "training": training,
        "goodput": _numeric_subset(telemetry, ("goodput/",)),
        "device_memory": device_memory,
        "health": _numeric_subset(telemetry, ("health/", "nan_guard/")),
        "perf": {"file": bench[1], "data": bench[0]} if bench else None,
        "audit": audit_data,
        "inference": _numeric_subset(telemetry, ("decode/", "eval/")),
        "serving": _numeric_subset(telemetry, ("serve/",)),
        # null when the run never post-trained (no `rl-fit` invocation) —
        # additive: schema_version stays 1
        "rl": _numeric_subset(telemetry, ("rl/",)),
        # null when the run never routed (no `route` invocation)
        "router": _numeric_subset(telemetry, ("router/",)),
        # null when the run armed no SLO config — the structured twin of
        # the text section's absent-config omission
        "slo": _numeric_subset(telemetry, ("slo/",)),
        # null when the run profiled nothing (no trigger counters, no
        # capture manifests, no attr/ gauges)
        "profiling": _profiling_summary(run_dir, telemetry),
        "elastic": elastic,
        "trace": _trace_summary(run_dir),
        # null when no `fleet --out` sweep was persisted into the run dir
        "fleet": _fleet_summary(run_dir),
        "recovery": _numeric_subset(telemetry, ("resilience/",)),
        # null when the run mirrored nothing and had no verify findings —
        # full-key "prefixes" pick the two checkpoint/ durability counters
        # without dragging in save/wait timers
        "durability": _numeric_subset(telemetry, (
            "ckpt/", "checkpoint/verify_failures", "checkpoint/mirror_restores",
        )),
        "flash": _numeric_subset(telemetry, ("flash/",)),
        "telemetry": telemetry,
    }


def report_main(
    run_dir: str,
    bench_dir: str | None = None,
    supervisor_log: str | None = None,
    audit_dir: str | None = None,
    format: str = "text",
) -> int:
    """`llm-training-tpu report <run_dir>` entry point."""
    try:
        if format == "json":
            print(json.dumps(render_report_data(
                run_dir, bench_dir=bench_dir, supervisor_log=supervisor_log,
                audit_dir=audit_dir,
            )))
        else:
            print(render_report(
                run_dir, bench_dir=bench_dir, supervisor_log=supervisor_log,
                audit_dir=audit_dir,
            ))
    except FileNotFoundError as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    return 0
