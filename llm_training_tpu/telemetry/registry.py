"""Thread-safe per-host metric registry: counters, gauges, timers.

The trainer owns one registry per fit; producer threads (the device
prefetcher) and the checkpointer record into it concurrently with the step
loop, and its `snapshot()` is merged into the metrics dict on log steps so
the JSONL/W&B loggers persist it for free. A module-level *current* registry
lets components that are constructed independently of the trainer (the
checkpointer) find the active run's registry without plumbing.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Iterator


class Counter:
    """Monotonic accumulator (events, bytes, items)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0  # guarded by: _lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar (HBM bytes, compile seconds)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value: float | None = None  # guarded by: _lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float | None:
        with self._lock:
            return self._value


class Timer:
    """Accumulated duration + invocation count; use as a context manager."""

    __slots__ = ("_lock", "total_s", "count", "_clock")

    def __init__(self, lock: threading.RLock, clock=time.perf_counter):
        self._lock = lock
        self._clock = clock
        self.total_s = 0.0  # guarded by: _lock
        self.count = 0  # guarded by: _lock

    def add(self, seconds: float) -> None:
        with self._lock:
            self.total_s += seconds
            self.count += 1

    @contextmanager
    def time(self) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(self._clock() - t0)


class TelemetryRegistry:
    """Create-on-access metric registry. All mutation goes through one RLock,
    so any thread may record; `snapshot()` flattens everything into a
    `{name: float}` dict (timers emit `<name>_s` and `<name>_n`)."""

    def __init__(self, clock=time.perf_counter):
        self._lock = threading.RLock()
        self._clock = clock
        self._counters: dict[str, Counter] = {}  # guarded by: _lock
        self._gauges: dict[str, Gauge] = {}  # guarded by: _lock
        self._timers: dict[str, Timer] = {}  # guarded by: _lock

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter(self._lock))

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge(self._lock))

    def timer(self, name: str) -> Timer:
        with self._lock:
            return self._timers.setdefault(name, Timer(self._lock, self._clock))

    def snapshot(self) -> dict[str, float]:
        # one flatten implementation: telemetry.jsonl (this path) and the
        # /metrics scrape (snapshot_with_kinds) can never disagree on key
        # scheme or skip rules
        return self.snapshot_with_kinds()[0]

    def snapshot_with_kinds(self) -> tuple[dict[str, float], dict[str, str]]:
        """(values, kinds) under ONE lock hold — the /metrics scrape path
        (telemetry/exporter.py). Because the flatten happens inside the
        same critical section every mutation uses, a scrape landing
        mid-write can never observe a torn metric: a Timer's `_s`/`_n`
        pair always moves together (tests/test_interleave.py pins the
        window). Kinds map to Prometheus types: counters and timer
        accumulators are 'counter', everything else 'gauge'."""
        with self._lock:
            values: dict[str, float] = {}
            kinds: dict[str, str] = {}
            for name, counter in self._counters.items():
                values[name] = counter._value
                kinds[name] = "counter"
            for name, gauge in self._gauges.items():
                if gauge._value is not None:
                    values[name] = gauge._value
                    kinds[name] = "gauge"
            for name, timer in self._timers.items():
                values[name + "_s"] = timer.total_s
                values[name + "_n"] = float(timer.count)
                kinds[name + "_s"] = "counter"
                kinds[name + "_n"] = "counter"
            return values, kinds


# ---------------------------------------------------------------- current
# A plain module global (not a contextvar): worker threads spawned inside a
# fit must see the fit's registry, and new threads do not inherit contextvars.
_default_registry = TelemetryRegistry()
_current_registry = _default_registry  # guarded by: _current_lock
_current_lock = threading.Lock()


def get_registry() -> TelemetryRegistry:
    """The active run's registry (a process-default one outside any fit)."""
    return _current_registry


def set_registry(registry: TelemetryRegistry) -> TelemetryRegistry:
    """Install `registry` as current; returns the previous one (restore it
    in a finally)."""
    global _current_registry
    with _current_lock:
        previous = _current_registry
        _current_registry = registry
        return previous
