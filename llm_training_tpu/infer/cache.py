"""KV-cache construction + sharding for the inference subsystem.

The `DecodeState` pytree itself lives in `models/base.py` (next to
`CausalLMOutput`, so model files never import `infer/`); this module owns
everything about *building* one: sizing from a model config, the cache
dtype policy, the mesh placement (k/v heads shard over 'tensor', batch over
'data'/'fsdp' — the same rule table the attention activations use), and the
HBM-footprint gauge the decode telemetry publishes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from llm_training_tpu.models.base import DecodeState, resolve_dtype
from llm_training_tpu.parallel.sharding import LogicalAxisRules, logical_to_spec

# cache buffer layout: [num_layers, batch, max_length, num_kv_heads, head_dim]
KV_LOGICAL_AXES = ("layers", "batch", None, "kv_heads", None)
SEG_LOGICAL_AXES = ("batch", None)


def cache_dims(config) -> tuple[int, int, int]:
    """(num_layers, num_kv_heads, head_dim) for any shared-stack config.

    Gemma carries a mandatory explicit `head_dim`; llama-family configs
    derive it via `resolved_head_dim`."""
    head_dim = getattr(config, "resolved_head_dim", None) or config.head_dim
    return config.num_hidden_layers, config.num_key_value_heads, head_dim


def resolve_cache_dtype(config, cache_dtype: str | None) -> jnp.dtype:
    """None / 'param' -> the model's param dtype; otherwise an explicit
    dtype name ('float32' for an exactness oracle, 'bfloat16' to halve the
    cache HBM)."""
    if cache_dtype in (None, "param"):
        return config.param_jnp_dtype
    return resolve_dtype(cache_dtype)


def _divisible_spec(
    shape: tuple[int, ...],
    logical_axes: tuple[str | None, ...],
    mesh: Mesh,
    rules: LogicalAxisRules,
) -> PartitionSpec:
    """logical axes -> PartitionSpec, dropping any mesh axis whose ways do
    not divide the dimension (a 1-prompt batch on an 8-way data mesh must
    replicate, not error)."""
    spec = logical_to_spec(logical_axes, rules)
    out = []
    for dim, entry in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if entry is None:
            out.append(None)
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        ways = 1
        for axis in axes:
            ways *= mesh.shape[axis]
        out.append(entry if ways and dim % ways == 0 else None)
    return PartitionSpec(*out)


def decode_state_shardings(
    config,
    batch_size: int,
    max_length: int,
    mesh: Mesh,
    rules: LogicalAxisRules,
    rope_length: int | None = None,
) -> DecodeState:
    """A DecodeState-shaped tree of NamedShardings for jit in/out.
    `rope_length` must match the state the shardings are used with — it is
    static pytree metadata, so a mismatch is a structure mismatch."""
    num_layers, kv_heads, head_dim = cache_dims(config)
    kv_shape = (num_layers, batch_size, max_length, kv_heads, head_dim)
    kv = NamedSharding(mesh, _divisible_spec(kv_shape, KV_LOGICAL_AXES, mesh, rules))
    seg = NamedSharding(
        mesh,
        _divisible_spec((batch_size, max_length), SEG_LOGICAL_AXES, mesh, rules),
    )
    return DecodeState(
        k=kv, v=kv, index=NamedSharding(mesh, PartitionSpec()), segment_ids=seg,
        rope_length=rope_length,
    )


def init_decode_state(
    config,
    batch_size: int,
    max_length: int,
    mesh: Mesh | None = None,
    rules: LogicalAxisRules | None = None,
    cache_dtype: str | None = None,
    rope_length: int | None = None,
) -> DecodeState:
    """Fresh all-zeros cache (index 0, no slot filled). With a mesh the
    buffers are created ALREADY sharded (jit with out_shardings), so the
    first prefill never materializes a replicated cache. `rope_length` is
    the planned total sequence length when it is shorter than the cache
    capacity (length-dependent RoPE variants select tables from it)."""
    num_layers, kv_heads, head_dim = cache_dims(config)
    dtype = resolve_cache_dtype(config, cache_dtype)

    def build() -> DecodeState:
        kv_shape = (num_layers, batch_size, max_length, kv_heads, head_dim)
        return DecodeState(
            k=jnp.zeros(kv_shape, dtype),
            v=jnp.zeros(kv_shape, dtype),
            index=jnp.int32(0),
            segment_ids=jnp.zeros((batch_size, max_length), jnp.int32),
            rope_length=rope_length,
        )

    if mesh is None:
        state = build()
    else:
        shardings = decode_state_shardings(
            config, batch_size, max_length, mesh, rules or (), rope_length=rope_length
        )
        state = jax.jit(build, out_shardings=shardings)()
    _publish_cache_bytes(state)
    return state


def _publish_cache_bytes(state: DecodeState) -> None:
    """Every cache construction lands its HBM footprint in telemetry
    (`decode/cache_bytes`) — callers used to re-publish this themselves,
    which left non-engine constructions (eval, serve warm-up) invisible in
    telemetry.jsonl and `report`."""
    from llm_training_tpu.telemetry import get_registry

    get_registry().gauge("decode/cache_bytes").set(cache_bytes(state))


def cache_bytes(state: DecodeState) -> int:
    """Global HBM footprint of the cache buffers (the `decode/cache_bytes`
    gauge)."""
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in (state.k, state.v)
    )
