"""Held-out evaluation: stream a datamodule through the objective.

Runs inside the SAME sharded program shape as training validation (the
pjit/TPUv4 eval-inside-the-mesh pattern, arxiv 2204.06514): one jitted
loss step over packed batches, with the objective's segment-id masking —
packed-document boundaries and padding never count — so the numbers are
directly comparable to training `val_loss`.

Reported per-token NLL is the token-weighted corpus mean (sum of per-token
losses / number of target tokens), and perplexity its exp; batch means are
re-weighted by their `target_tokens` so ragged final batches don't skew
the aggregate. Results are published as `eval/*` registry gauges so the
`evaluate` CLI lands them in telemetry.jsonl for `report`.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any

import flax.linen as nn
import jax
import numpy as np

logger = logging.getLogger(__name__)


def run_evaluation(
    objective: Any,
    state: Any,
    datamodule: Any,
    mesh: Any,
    state_shardings: Any | None = None,
    limit_batches: int | None = None,
    split: str = "val",
) -> dict[str, float]:
    """-> {eval/nll_per_token, eval/perplexity, eval/tokens, eval/batches,
    eval/time_s, eval/tokens_per_sec}, also set as registry gauges."""
    from llm_training_tpu.telemetry import get_registry
    from llm_training_tpu.trainer.trainer import (
        LOGICAL_AXIS_RULES,
        _batch_shardings,
    )

    if split not in ("val", "train"):
        raise ValueError(f"split must be 'val' or 'train', got {split!r}")
    if split == "train" and not limit_batches:
        raise ValueError(
            "split='train' streams an infinite batch sequence; set "
            "limit_batches"
        )
    datamodule.setup()
    batches = (
        datamodule.val_batches() if split == "val"
        else datamodule.train_batches()
    )

    def eval_step(state, batch):
        _, metrics = objective.loss_and_metrics(
            state.params, batch, rng=state.rng, train=False
        )
        loss = metrics["loss"]
        if "aux_loss" in metrics:
            # MoE configs fold coef*aux_loss into metrics['loss'] (clm.py);
            # a PERPLEXITY must be exp of the token-level cross entropy
            # only — same convention as clm's own `perplexity` metric —
            # so back the balancing penalty out (exact reversal up to one
            # fp32 rounding; the trainer's val_loss keeps the penalty, so
            # eval/nll_per_token may differ from it by coef*aux on MoE)
            coef = getattr(
                objective.model.config, "router_aux_loss_coef", 0.0
            )
            loss = loss - coef * metrics["aux_loss"]
        return {
            "loss": loss,
            "target_tokens": metrics["target_tokens"],
        }

    total_nll = 0.0
    total_tokens = 0.0
    n_batches = 0
    t0 = time.perf_counter()
    with mesh, nn.logical_axis_rules(LOGICAL_AXIS_RULES):
        step_fn = None
        for i, batch in enumerate(batches):
            if limit_batches and i >= limit_batches:
                break
            if step_fn is None:
                in_shardings = (
                    (state_shardings, _batch_shardings(batch, mesh))
                    if state_shardings is not None
                    else None
                )
                step_fn = jax.jit(eval_step, in_shardings=in_shardings)
            out = jax.device_get(step_fn(state, batch))
            tokens = float(out["target_tokens"])
            total_nll += float(out["loss"]) * tokens
            total_tokens += tokens
            n_batches += 1
    elapsed = time.perf_counter() - t0
    if n_batches == 0:
        raise ValueError(
            f"datamodule produced no {split} batches "
            "(set validation_split or provide a val dataset)"
        )

    nll = total_nll / max(total_tokens, 1.0)
    result = {
        "eval/nll_per_token": nll,
        "eval/perplexity": float(np.exp(np.minimum(nll, 700.0))) if math.isfinite(nll) else float("inf"),
        "eval/tokens": total_tokens,
        "eval/batches": float(n_batches),
        "eval/time_s": elapsed,
        "eval/tokens_per_sec": total_tokens / elapsed if elapsed > 0 else 0.0,
    }
    registry = get_registry()
    for key, value in result.items():
        if math.isfinite(value):
            registry.gauge(key).set(value)
    logger.info(
        "evaluate[%s]: nll/token %.4f | ppl %.2f | %d tokens in %d batches "
        "(%.1f tok/s)",
        split, nll, result["eval/perplexity"], int(total_tokens), n_batches,
        result["eval/tokens_per_sec"],
    )
    return result
