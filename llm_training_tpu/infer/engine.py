"""Batched KV-cache generation engine.

Two jitted programs over the SAME sharded decoder stack the trainer runs
(pjit-style train/infer unification, arxiv 2204.06514):

- `prefill`: the whole (left-padded) prompt batch at full width — one
  forward that writes every prompt position's k/v into the cache and
  samples the first new token from the last column's logits;
- `decode_step`: one token per row, appended to the cache at the shared
  dynamic index, next token sampled in-program (greedy / temperature /
  top-k / top-p under an explicit PRNG key).

Prompts are LEFT-padded to a common width so the whole batch shares one
cache append index (`models/base.py:DecodeState`); per-row RoPE positions
subtract the pad length, and pad slots carry segment id 0 so the attention
mask never reaches them. The cache buffers are donated through both
programs — decoding mutates them in place in HBM.

Decode telemetry (prefill_time_s, tokens/sec, cache bytes) is published
through the process registry, so the `generate` CLI lands it in
`telemetry.jsonl` and `report` renders it with zero extra wiring.
"""

from __future__ import annotations

import contextlib
import inspect
import logging
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import BaseModel, ConfigDict, model_validator

from llm_training_tpu.infer.cache import (
    cache_bytes,
    decode_state_shardings,
    init_decode_state,
)
from llm_training_tpu.infer.sampling import (
    SamplingConfig,
    sample_tokens_with_logprob,
)
from llm_training_tpu.models.base import DecodeState

logger = logging.getLogger(__name__)


class GenerateConfig(BaseModel):
    """Knobs of one `generate` call (docs/inference.md)."""

    model_config = ConfigDict(extra="forbid")

    max_new_tokens: int = 32
    # cache capacity; default = padded prompt width + max_new_tokens
    max_length: int | None = None
    # None/'param' = the model's param dtype; 'float32' | 'bfloat16'
    cache_dtype: str | None = None
    seed: int = 0
    # stop a row at this token; generation ends early once every row stopped
    eos_token_id: int | None = None
    sampling: SamplingConfig = SamplingConfig()

    @model_validator(mode="after")
    def _validate(self) -> "GenerateConfig":
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        if self.max_length is not None and self.max_length < 1:
            raise ValueError(f"max_length must be >= 1, got {self.max_length}")
        return self


def mesh_context(mesh: Any, rules: Any = ()) -> contextlib.ExitStack:
    """The ambience every sharded inference/serving program runs under:
    the mesh + the logical axis rules, or nothing off-mesh. Shared by
    `InferenceEngine` and `serve.ServingEngine`."""
    context = contextlib.ExitStack()
    if mesh is not None:
        import flax.linen as nn

        context.enter_context(mesh)
        context.enter_context(nn.logical_axis_rules(rules or ()))
    return context


def supports_decoding(model: Any) -> bool:
    """A model family opts into KV-cache decoding by accepting a
    `decode_state` kwarg (the shared llama/gemma stacks do; non-standard
    mixers — bamba's mamba layers, qwen3-next/minimax linear attention,
    deepseek MLA — have not been threaded yet)."""
    try:
        return "decode_state" in inspect.signature(model.__call__).parameters
    except (TypeError, ValueError):
        return False


def _left_pad(prompts: Sequence[Sequence[int]], pad_id: int) -> tuple[np.ndarray, np.ndarray]:
    """-> (input_ids [B, P] left-padded, pad_lens [B])."""
    if len(prompts) == 0:
        raise ValueError("generate() needs at least one prompt")
    lengths = [len(p) for p in prompts]
    if min(lengths) == 0:
        raise ValueError("empty prompt: each prompt needs at least one token")
    width = max(lengths)
    ids = np.full((len(prompts), width), pad_id, np.int32)
    for row, prompt in enumerate(prompts):
        ids[row, width - len(prompt):] = np.asarray(prompt, np.int32)
    return ids, np.asarray([width - n for n in lengths], np.int32)


class InferenceEngine:
    """Drives a restored model over the decode programs.

    `variables` is the model's full variable dict (what `model.init` /
    checkpoint restore return: `{"params": ...}`); `mesh` + `rules` give
    the cache its sharding (heads over 'tensor', batch over 'data'/'fsdp')
    — omit both for single-process use (tests)."""

    def __init__(
        self,
        model: Any,
        variables: Any,
        mesh: Any | None = None,
        rules: Any = (),
    ):
        if not supports_decoding(model):
            raise NotImplementedError(
                f"{type(model).__name__} does not support KV-cache decoding "
                "yet: its __call__ takes no decode_state (non-standard "
                "sequence mixers need their own cache layout — see "
                "docs/inference.md)"
            )
        self.model = model
        self.variables = variables
        self.mesh = mesh
        self.rules = rules
        self._prefill_jit = None
        self._decode_jit = None
        self._sampling: SamplingConfig | None = None

    # ------------------------------------------------------------ programs

    def _build_programs(self, sampling: SamplingConfig):
        """(Re)build the jitted prefill/decode programs; cached until the
        sampling config changes (it is baked into the traces)."""
        if self._sampling == sampling and self._prefill_jit is not None:
            return
        model = self.model

        def prefill(variables, input_ids, segment_ids, position_ids, state, rng):
            out = model.apply(
                variables,
                input_ids=input_ids,
                segment_ids=segment_ids,
                position_ids=position_ids,
                decode_state=state,
            )
            logits = out.logits[:, -1, :].astype(jnp.float32)
            token, logprob = sample_tokens_with_logprob(logits, rng, sampling)
            return out.decode_state, token, logprob

        def decode_step(variables, tokens, pad_lens, state, rng):
            # per-row RoPE position: absolute cache slot minus left-pad
            position_ids = (state.index - pad_lens)[:, None]
            out = model.apply(
                variables,
                input_ids=tokens[:, None],
                segment_ids=jnp.ones((tokens.shape[0], 1), jnp.int32),
                position_ids=position_ids,
                decode_state=state,
            )
            logits = out.logits[:, -1, :].astype(jnp.float32)
            token, logprob = sample_tokens_with_logprob(logits, rng, sampling)
            return out.decode_state, token, logprob

        # the cache is donated: k/v update in place across the token loop
        self._prefill_jit = jax.jit(prefill, donate_argnums=(4,))
        self._decode_jit = jax.jit(decode_step, donate_argnums=(3,))
        self._sampling = sampling

    # ------------------------------------------------------------ generate

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        config: GenerateConfig | None = None,
    ) -> dict[str, Any]:
        """-> {"tokens": new tokens per row (truncated after eos),
        "logprobs": chosen-token logprobs per row (aligned with "tokens"),
        "sequences": prompt + new tokens, "lengths": generated count per
        row, "stop_reasons": "eos" | "max_tokens" per row, "stats": decode
        telemetry}."""
        from llm_training_tpu.telemetry import get_registry

        config = config or GenerateConfig()
        model_config = self.model.config
        pad_id = model_config.pad_token_id or 0
        ids, pad_lens = _left_pad(prompts, pad_id)
        batch, width = ids.shape
        max_length = config.max_length or width + config.max_new_tokens
        if max_length < width + config.max_new_tokens:
            raise ValueError(
                f"max_length {max_length} cannot hold the padded prompt "
                f"({width}) plus max_new_tokens ({config.max_new_tokens})"
            )
        self._build_programs(config.sampling)

        with mesh_context(self.mesh, self.rules):
            state = init_decode_state(
                model_config, batch, max_length,
                mesh=self.mesh, rules=self.rules,
                cache_dtype=config.cache_dtype,
                # length-dependent RoPE variants select tables from the
                # length the generation will REACH, not the cache capacity
                rope_length=width + config.max_new_tokens,
            )
            # decode/cache_bytes is published by init_decode_state itself
            registry = get_registry()
            registry.gauge("decode/max_length").set(max_length)

            # a prompt may legitimately CONTAIN pad_id tokens, so padding is
            # identified positionally (the left-pad region), not by value
            segment_ids = (
                np.arange(width)[None, :] >= pad_lens[:, None]
            ).astype(np.int32)
            position_ids = np.maximum(
                np.arange(width)[None, :] - pad_lens[:, None], 0
            ).astype(np.int32)
            ids_j, seg_j, pos_j, pad_j = self._place(
                ids, segment_ids, position_ids, pad_lens
            )

            rng = jax.random.key(config.seed)
            t0 = time.perf_counter()
            state, token, logprob = self._prefill_jit(
                self.variables, ids_j, seg_j, pos_j, state,
                jax.random.fold_in(rng, 0),
            )
            token.block_until_ready()
            prefill_s = time.perf_counter() - t0
            registry.gauge("decode/prefill_time_s").set(prefill_s)

            eos = config.eos_token_id
            if eos is not None:
                # early-stop needs each token on host: the per-step fetch
                # IS the stop check (and the natural decode sync point)
                new_tokens = [np.asarray(jax.device_get(token))]
                new_logprobs = [np.asarray(jax.device_get(logprob))]
                step_times: list[float] = []
                for step in range(1, config.max_new_tokens):
                    t_step = time.perf_counter()
                    state, token, logprob = self._decode_jit(
                        self.variables, token, pad_j, state,
                        jax.random.fold_in(rng, step),
                    )
                    host_token = np.asarray(jax.device_get(token))
                    step_times.append(time.perf_counter() - t_step)
                    new_tokens.append(host_token)
                    new_logprobs.append(np.asarray(jax.device_get(logprob)))
                    if all(eos in row for row in np.stack(new_tokens, 1)):
                        break
                grid = np.stack(new_tokens, axis=1)  # [B, T]
                lp_grid = np.stack(new_logprobs, axis=1)
                steady = step_times[1:] if len(step_times) > 1 else step_times
                steady_steps, steady_s = len(steady), sum(steady)
            else:
                # no stop token: free-running dispatch, ONE fence at the
                # end — per-step host round trips would serialize the loop
                # for nothing. The first decode step is fenced separately
                # so its trace+compile stays out of the steady-state rate.
                device_tokens = [token]
                device_logprobs = [logprob]
                steady_steps = steady_s = 0
                for step in range(1, config.max_new_tokens):
                    state, token, logprob = self._decode_jit(
                        self.variables, token, pad_j, state,
                        jax.random.fold_in(rng, step),
                    )
                    device_tokens.append(token)
                    device_logprobs.append(logprob)
                    if step == 1:
                        jax.device_get(token)  # compile fence
                        t_steady = time.perf_counter()
                host = jax.device_get(device_tokens)  # the real fence
                host_lp = jax.device_get(device_logprobs)
                if config.max_new_tokens > 2:
                    steady_s = time.perf_counter() - t_steady
                    steady_steps = config.max_new_tokens - 2
                grid = np.stack([np.asarray(t) for t in host], axis=1)
                lp_grid = np.stack([np.asarray(t) for t in host_lp], axis=1)
        tokens, logprobs, sequences, lengths, stop_reasons = [], [], [], [], []
        for row in range(batch):
            emitted = grid[row].tolist()
            if eos is not None and eos in emitted:
                emitted = emitted[: emitted.index(eos) + 1]
                stop_reasons.append("eos")
            else:
                stop_reasons.append("max_tokens")
            tokens.append(emitted)
            logprobs.append([float(v) for v in lp_grid[row, : len(emitted)]])
            lengths.append(len(emitted))
            sequences.append(list(prompts[row]) + emitted)

        # steady-state decode rate: the first decode step carries the
        # trace+compile and is excluded in both loop variants above
        decode_tps = batch * steady_steps / steady_s if steady_s > 0 else 0.0
        stats = {
            "decode/prefill_time_s": prefill_s,
            "decode/tokens_per_sec": decode_tps,
            "decode/new_tokens": int(sum(len(t) for t in tokens)),
            "decode/cache_bytes": cache_bytes(state),
            "decode/max_length": max_length,
        }
        registry.gauge("decode/tokens_per_sec").set(decode_tps)
        registry.gauge("decode/new_tokens").set(stats["decode/new_tokens"])
        logger.info(
            "generate: %d prompts, %d new tokens | prefill %.3fs | "
            "%.1f tokens/s decode",
            batch, stats["decode/new_tokens"], prefill_s, decode_tps,
        )
        return {
            "tokens": tokens,
            # chosen-token logprob per emitted token, under the sampled
            # distribution (raw for greedy, filtered for temperature > 0 —
            # see infer/sampling.py:sample_tokens_with_logprob)
            "logprobs": logprobs,
            "sequences": sequences,
            # per-row generated length + why each row stopped ("eos" |
            # "max_tokens") — callers (serve scheduler, evaluate, bench)
            # no longer re-scan the outputs for the eos token
            "lengths": lengths,
            "stop_reasons": stop_reasons,
            "stats": stats,
        }

    def _place(self, ids, segment_ids, position_ids, pad_lens):
        """Host arrays -> device, batch-sharded over the mesh when the
        batch divides its data ways (replicated otherwise)."""
        arrays = (
            jnp.asarray(ids), jnp.asarray(segment_ids),
            jnp.asarray(position_ids), jnp.asarray(pad_lens),
        )
        if self.mesh is None:
            return arrays
        from jax.sharding import NamedSharding

        from llm_training_tpu.infer.cache import _divisible_spec

        batch2d = NamedSharding(
            self.mesh,
            _divisible_spec(arrays[0].shape, ("batch", None), self.mesh, self.rules),
        )
        batch1d = NamedSharding(
            self.mesh,
            _divisible_spec(arrays[3].shape, ("batch",), self.mesh, self.rules),
        )
        return tuple(
            jax.device_put(a, batch1d if a.ndim == 1 else batch2d)
            for a in arrays
        )
