"""Inference & evaluation subsystem (docs/inference.md).

TPU-native batched decoding over the training mesh and checkpoints: a
static-shape mesh-sharded KV cache (`cache.py` + `models/base.DecodeState`)
threaded through the shared decoder stack, jitted prefill / decode-step
programs with sampling (`engine.py`, `sampling.py`), and a packed-
perplexity eval harness (`evaluate.py`) — behind the `generate` and
`evaluate` CLI subcommands.

Leaf modules (cache, sampling) import eagerly; the engine/evaluate modules
are lazy so model files can import `llm_training_tpu.infer.cache` without
pulling the trainer stack in (engine -> telemetry -> ... would cycle).
"""

from llm_training_tpu.infer.cache import (
    cache_bytes,
    decode_state_shardings,
    init_decode_state,
)
from llm_training_tpu.infer.sampling import SamplingConfig, sample_tokens

__all__ = [
    "GenerateConfig",
    "InferenceEngine",
    "SamplingConfig",
    "cache_bytes",
    "decode_state_shardings",
    "init_decode_state",
    "run_evaluation",
    "sample_tokens",
    "supports_decoding",
]

_LAZY = {
    "GenerateConfig": "llm_training_tpu.infer.engine",
    "InferenceEngine": "llm_training_tpu.infer.engine",
    "supports_decoding": "llm_training_tpu.infer.engine",
    "run_evaluation": "llm_training_tpu.infer.evaluate",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
