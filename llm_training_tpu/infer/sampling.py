"""Token sampling for the decode loop: greedy, temperature, top-k, top-p.

All transforms operate on the fp32 next-token logits [batch, vocab] INSIDE
the jitted decode program, under an explicit PRNG key (no global state —
`jax.random.fold_in(key, step)` gives each step its stream, so a generation
is reproducible from (params, prompt, seed) alone). temperature == 0 is
greedy argmax and compiles with no random bits at all.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from pydantic import BaseModel, ConfigDict, model_validator

_FILTERED = -1e10  # large-negative fill for filtered logits (fp32-safe)


class SamplingConfig(BaseModel):
    """The sampling knobs of the `generate` CLI (docs/inference.md).

    Filters compose HF-style (the default LogitsProcessor order):
    temperature scaling FIRST — the top-p nucleus must be computed on the
    temperature-warped distribution, or a high temperature would keep the
    narrow temperature-1 nucleus — then top_k, then top_p over the
    survivors, then the categorical draw. temperature=0.0 (the default) is
    deterministic greedy decoding and ignores the filters."""

    model_config = ConfigDict(extra="forbid")

    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None

    @model_validator(mode="after")
    def _validate(self) -> "SamplingConfig":
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        return self


def top_k_filter(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Keep each row's k largest logits; fill the rest with -inf-like."""
    if k >= logits.shape[-1]:
        return logits
    threshold = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits >= threshold, logits, _FILTERED)


def top_p_filter(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of the probability-sorted
    vocab whose mass reaches p (the token that crosses the boundary is kept,
    HF semantics), fill the rest with -inf-like."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # exclusive cumsum: a token survives if the mass BEFORE it is < p, so
    # the first token always survives and the boundary-crossing token stays
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep = mass_before < p
    # threshold = smallest kept logit per row
    threshold = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= threshold, logits, _FILTERED)


def filtered_logits(logits: jnp.ndarray, config: SamplingConfig) -> jnp.ndarray:
    """Apply the temperature/top-k/top-p pipeline (HF order) to raw logits.

    The result is the logits of the distribution the categorical draw
    actually samples from — the behavior policy an RL importance ratio
    must be computed against. Only meaningful for temperature > 0."""
    logits = logits / jnp.float32(config.temperature)
    if config.top_k is not None:
        logits = top_k_filter(logits, config.top_k)
    if config.top_p is not None:
        logits = top_p_filter(logits, config.top_p)
    return logits


def sample_tokens(
    logits: jnp.ndarray,
    rng: jax.Array | None,
    config: SamplingConfig,
) -> jnp.ndarray:
    """logits [batch, vocab] (fp32) -> token ids [batch] int32."""
    if config.temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        raise ValueError("temperature > 0 sampling requires a PRNG key")
    return jax.random.categorical(
        rng, filtered_logits(logits, config), axis=-1
    ).astype(jnp.int32)


def sample_tokens_with_logprob(
    logits: jnp.ndarray,
    rng: jax.Array | None,
    config: SamplingConfig,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """logits [batch, vocab] (fp32) -> (token ids [batch] int32,
    chosen-token logprobs [batch] fp32).

    The logprob is taken under the distribution the token was actually
    drawn from: greedy scores under the RAW log-softmax (so logprobs
    collected incrementally during paged decode are comparable to a
    teacher-forced full forward over the same tokens), temperature > 0
    scores under the temperature-scaled, top-k/top-p-filtered
    distribution (the behavior policy for importance ratios — a token
    outside the nucleus has ~-inf there, never the raw value)."""
    if config.temperature == 0.0:
        tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        log_probs = jax.nn.log_softmax(logits, axis=-1)
    else:
        if rng is None:
            raise ValueError("temperature > 0 sampling requires a PRNG key")
        filtered = filtered_logits(logits, config)
        tokens = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)
        log_probs = jax.nn.log_softmax(filtered, axis=-1)
    chosen = jnp.take_along_axis(log_probs, tokens[:, None], axis=-1)[:, 0]
    return tokens, chosen.astype(jnp.float32)
